"""TensorBoard event-file writer: format round-trip + callback integration.

The writer hand-encodes the TFRecord/Event-proto format (no tensorflow in
the image — utils/tensorboard.py); the reader verifies the exact CRCs
TensorBoard checks, so a round-trip pass here means TB would load the file.
"""

from __future__ import annotations

import glob
import os
import struct

import numpy as np
import pytest

from distributed_machine_learning_tpu.utils.tensorboard import (
    SummaryWriter,
    _masked_crc,
    crc32c,
    read_events,
)


def test_crc32c_known_vectors():
    """Published CRC-32C test vectors (RFC 3720 appendix + classics)."""
    assert crc32c(b"") == 0x0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"\xff" * 32) == 0x62A8AB43


def test_masked_crc_matches_tensorflow_convention():
    # mask(crc) = ((crc >> 15) | (crc << 17)) + 0xa282ead8 (mod 2^32)
    crc = crc32c(b"123456789")
    expected = (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF
    assert _masked_crc(b"123456789") == expected


def test_scalar_round_trip(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.add_scalar("loss", 0.5, step=1, wall_time=100.0)
    w.add_scalar("loss", 0.25, step=2, wall_time=101.0)
    w.add_scalars([("loss", 0.125), ("mape", 3.5)], step=3, wall_time=102.0)
    w.close()

    events = read_events(w.path)  # verify_crc=True: TB-grade framing check
    assert events[0]["file_version"] == "brain.Event:2"
    scalars = [(e["step"], e["scalars"]) for e in events[1:]]
    assert scalars[0] == (1, {"loss": pytest.approx(0.5)})
    assert scalars[1] == (2, {"loss": pytest.approx(0.25)})
    assert scalars[2][0] == 3
    assert scalars[2][1]["loss"] == pytest.approx(0.125)
    assert scalars[2][1]["mape"] == pytest.approx(3.5)
    assert events[1]["wall_time"] == pytest.approx(100.0)


def test_corrupted_record_fails_crc(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.add_scalar("x", 1.0, step=1)
    w.close()
    with open(w.path, "r+b") as f:
        f.seek(-3, os.SEEK_END)  # flip a payload byte of the last record
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(ValueError, match="CRC"):
        read_events(w.path)
    assert read_events(w.path, verify_crc=False)  # still structurally parseable


def test_filename_is_tb_discoverable(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.close()
    assert "tfevents" in os.path.basename(w.path)


def test_varint_boundaries(tmp_path):
    """Steps that straddle varint byte boundaries survive the round trip."""
    w = SummaryWriter(str(tmp_path))
    steps = [0, 127, 128, 16383, 16384, 2**31 - 1]
    for s in steps:
        w.add_scalar("t", float(s % 7), step=s)
    w.close()
    got = [e["step"] for e in read_events(w.path)[1:]]
    assert got == steps


def test_callback_writes_per_trial_runs(tmp_path):
    """End to end under tune.run: one TB run dir per trial, metrics at every
    training_iteration, config stamped as scalars."""
    from distributed_machine_learning_tpu import tune
    from distributed_machine_learning_tpu.data import dummy_regression_data

    train, val = dummy_regression_data(
        num_samples=128, seq_len=8, num_features=4
    )
    analysis = tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        {"model": "mlp", "hidden_sizes": (16,),
         "learning_rate": tune.loguniform(1e-3, 1e-2),
         "num_epochs": 2, "batch_size": 32},
        metric="validation_loss",
        num_samples=2,
        storage_path=str(tmp_path),
        name="tb_test",
        callbacks=[tune.TensorBoardCallback()],
        verbose=0,
    )
    tb_root = os.path.join(analysis.root, "tensorboard")
    all_dirs = sorted(os.listdir(tb_root))
    # One run per trial, plus the experiment-scope "_experiment" run that
    # carries the always-on checkpoint I/O counters (ckpt.metrics).
    run_dirs = [d for d in all_dirs if not d.startswith("_")]
    assert len(run_dirs) == 2  # one run per trial
    if "_experiment" in all_dirs:
        exp_files = glob.glob(
            os.path.join(tb_root, "_experiment", "events.out.tfevents.*")
        )
        exp_tags = {
            t for f in exp_files for e in read_events(f)
            for t in e["scalars"]
        }
        assert any(t.startswith("checkpoint/") for t in exp_tags)
    for rd in run_dirs:
        files = glob.glob(os.path.join(tb_root, rd, "events.out.tfevents.*"))
        assert len(files) == 1
        events = read_events(files[0])
        steps = [e["step"] for e in events if "validation_loss" in e["scalars"]]
        assert steps == [1, 2]  # every epoch reported
        cfg_tags = {
            t for e in events for t in e["scalars"] if t.startswith("config/")
        }
        assert "config/learning_rate" in cfg_tags
        losses = [
            e["scalars"]["validation_loss"]
            for e in events if "validation_loss" in e["scalars"]
        ]
        assert np.all(np.isfinite(losses))
