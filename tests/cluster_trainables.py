"""Module-level trainables for the multi-host control-plane tests.

Workers resolve trainables by import (``cluster_trainables:fn``), mirroring how
a real pod ships the same container image to every host — so these live in an
importable module, not inside the test functions.
"""

from __future__ import annotations

import os

from distributed_machine_learning_tpu import tune


def quadratic_trial(config):
    """Deterministic synthetic loss curve: converges toward (x - 3)^2."""
    x = float(config["x"])
    epochs = int(config.get("epochs", 5))
    for epoch in range(1, epochs + 1):
        loss = (x - 3.0) ** 2 + 1.0 / epoch
        tune.report(
            {"loss": loss, "epoch": epoch},
            checkpoint={"x": x, "epoch": epoch},
        )


def resumable_quadratic_trial(config):
    """quadratic_trial that honors checkpoints — resumes at the restored
    epoch instead of re-reporting from 1 (the contract restore_base assumes)."""
    x = float(config["x"])
    restored = tune.get_checkpoint()
    start = int(restored["epoch"]) if restored else 0
    for epoch in range(start + 1, int(config.get("epochs", 5)) + 1):
        loss = (x - 3.0) ** 2 + 1.0 / epoch
        tune.report(
            {"loss": loss, "epoch": epoch},
            checkpoint={"x": x, "epoch": epoch},
        )


def crash_once_trial(config):
    """Fails on its first attempt, succeeds after restart (retry-path test).

    Uses a marker file under ``config['marker_dir']`` keyed by trial id, the
    cross-process analogue of an in-memory attempt counter.
    """
    marker = os.path.join(config["marker_dir"], f"{tune.get_trial_id()}.attempted")
    first_attempt = not os.path.exists(marker)
    if first_attempt:
        with open(marker, "w") as f:
            f.write("1")
    restored = tune.get_checkpoint()
    start = int(restored["epoch"]) if restored else 0
    for epoch in range(start + 1, 4):
        if first_attempt and epoch == 2:
            raise RuntimeError("injected failure (first attempt)")
        tune.report(
            {"loss": 10.0 / epoch, "epoch": epoch},
            checkpoint={"epoch": epoch},
        )


def slow_resumable_trial(config):
    """Deterministic quadratic curve, checkpoint per epoch, configurable
    per-epoch sleep — the liveness-test workload: slow enough that trials
    are in flight when a partition/hang lands, checkpointed so a requeued
    incarnation resumes instead of restarting, and bit-deterministic in x
    so a faulted sweep's best trial must equal the fault-free run's."""
    import time

    x = float(config["x"])
    restored = tune.get_checkpoint()
    start = int(restored["epoch"]) if restored else 0
    for epoch in range(start + 1, int(config.get("epochs", 5)) + 1):
        time.sleep(float(config.get("sleep_s", 0.1)))
        loss = (x - 3.0) ** 2 + 1.0 / epoch
        tune.report(
            {"loss": loss, "epoch": epoch},
            checkpoint={"x": x, "epoch": epoch},
        )


def slow_trial(config):
    """Reports slowly; used by the worker-death test so trials are in flight."""
    import time

    for epoch in range(1, int(config.get("epochs", 10)) + 1):
        time.sleep(float(config.get("sleep_s", 0.2)))
        tune.report({"loss": 1.0 / epoch, "epoch": epoch})


def pbt_trial(config):
    """Checkpoint-carrying trainable for PBT-over-cluster: loss improves with
    a per-config 'rate', so PBT exploits good rates into bad trials.

    If ``barrier_dir``/``population`` are set, trials pace each other in
    lockstep through a filesystem barrier: a marker ``{tid}__{epoch}`` is
    written only AFTER ``report`` returns (i.e. after the driver has processed
    that epoch's metrics), and no trial starts epoch k+1 until every
    population member's epoch-k marker exists.  That makes "the whole
    population has comparable scores when the perturbation interval fires"
    true by construction instead of by race, so the PBT-over-cluster test is
    deterministic."""
    import time

    bdir = config.get("barrier_dir")
    population = int(config.get("population", 0))
    restored = tune.get_checkpoint()
    start = int(restored["epoch"]) if restored else 0
    score = float(restored["score"]) if restored else 100.0
    rate = float(config["rate"])
    tid = tune.get_trial_id()

    def wait_for_peers(epoch):
        if not bdir:
            return
        deadline = time.time() + 30.0
        while time.time() < deadline:
            reached = set()
            for name in os.listdir(bdir):
                peer, _, ep = name.partition("__")
                if ep and int(ep) == epoch:
                    reached.add(peer)
            if len(reached) >= population:
                return
            time.sleep(0.02)

    if bdir:
        # A respawned trial restored at epoch e never re-reports epochs <= e;
        # back-fill its markers so peers' barriers don't wait out the timeout.
        for ep in range(1, start + 1):
            with open(os.path.join(bdir, f"{tid}__{ep}"), "w"):
                pass

    for epoch in range(start + 1, int(config.get("epochs", 8)) + 1):
        score = score * (1.0 - rate)
        tune.report(
            {"loss": score, "epoch": epoch},
            checkpoint={"epoch": epoch, "score": score},
        )
        if bdir:
            with open(os.path.join(bdir, f"{tid}__{epoch}"), "w"):
                pass
            wait_for_peers(epoch)


def compiling_trial(config):
    """Jit-compiles a program whose SHAPE depends on ``config['width']``
    (the shape class; ``learning_rate`` is the non-structural knob, so
    same-width trials share one program key) and reports compile/fetch
    accounting — the workload behind the compile-artifact-origin tests:
    the first trial of a width must compile (and publish), its siblings
    must hit the local or fetched cache instead.  (Deterministic fetch-hit
    tests run two sweeps against one shared ``ArtifactRegistry`` — sweep 1
    publishes, sweep 2's fresh-cache worker fetches — rather than racing
    two workers inside one sweep.)"""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu import compilecache as cc

    width = int(config["width"])
    lr = float(config.get("learning_rate", 1.0))
    tracker = cc.get_tracker()
    before = tracker.total_uncached_compiles()
    x = jnp.full((width, width), lr, jnp.float32)
    y = float(jax.jit(lambda v: jnp.tanh(v @ v.T).sum())(x))
    counters = cc.get_counters()
    for epoch in range(1, int(config.get("epochs", 2)) + 1):
        tune.report({
            "loss": abs(y) / epoch + (lr - 1.5) ** 2,
            "epoch": epoch,
            "uncached_compiles": tracker.total_uncached_compiles() - before,
            "worker_fetch_hits": counters.get("fetch_hits"),
            "worker_fetch_fallbacks": counters.get("fetch_fallbacks"),
            "worker_publishes": counters.get("publishes"),
        })


def jax_device_trial(config):
    """Touches jax on the worker host to prove device-pinned execution."""
    import jax
    import jax.numpy as jnp

    x = jnp.arange(8.0) * float(config["x"])
    y = float(jax.jit(lambda v: (v**2).sum())(x))
    tune.report({"loss": y, "device": str(jax.devices()[0])})


def mesh_probe_trial(config):
    """Reports the slot's device lease — the cluster ``mesh_shape``
    plumbing test: a mesh trial must receive prod(mesh_shape) DISTINCT
    local devices (worker slot groups), and the stamped config must carry
    the sweep-wide mesh shape."""
    from distributed_machine_learning_tpu.tune import session

    devices = session.get_devices()
    for epoch in range(1, 3):
        tune.report({
            "loss": float(config["x"]) + 1.0 / epoch,
            "epoch": epoch,
            "n_devices": len(devices),
            "n_distinct": len({getattr(d, "id", i)
                               for i, d in enumerate(devices)}),
            "mesh_shape": dict(config.get("mesh_shape") or {}),
        })


def sharded_compiling_trial(config):
    """Sharded-program analogue of ``compiling_trial`` (ISSUE 7): jits a
    program with explicit NamedSharding in_shardings over the mesh built
    from ``config['mesh_shape']`` via the partition-rule layer, and
    reports compile/fetch accounting.  ``mesh_shape`` is stamped into the
    trial config by the driver, so the artifact-origin program key splits
    on it — same mesh shape on another worker = fetch + zero compiles;
    a different mesh shape = honest recompile."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_machine_learning_tpu import compilecache as cc
    from distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from distributed_machine_learning_tpu.parallel.partition import (
        mesh_axis_sizes,
        rules_fingerprint,
        shardings_from_rules,
    )
    from distributed_machine_learning_tpu.tune import session

    devices = session.get_devices()
    mesh = make_mesh(dict(config["mesh_shape"]), devices)
    width = int(config.get("width", 16))
    lr = float(config.get("learning_rate", 1.0))
    rules = ((r"w$", P(None, "tp")), (r".*", P()),)
    tree = {"w": jnp.full((width, width), lr, jnp.float32)}
    sh = shardings_from_rules(tree, mesh, rules)
    tracker = cc.get_tracker()
    before = tracker.total_uncached_compiles()
    program = jax.jit(
        lambda t: jnp.tanh(t["w"] @ t["w"].T).sum(), in_shardings=(sh,)
    )
    y = float(program(jax.device_put(tree, sh)))
    counters = cc.get_counters()
    key = cc.sharded_program_key(
        {k: v for k, v in config.items() if k != "mesh_shape"},
        mesh_shape=mesh_axis_sizes(mesh),
        rules_fingerprint=rules_fingerprint(rules),
    )
    for epoch in range(1, int(config.get("epochs", 2)) + 1):
        tune.report({
            "loss": abs(y) / epoch + (lr - 1.5) ** 2,
            "epoch": epoch,
            "uncached_compiles": tracker.total_uncached_compiles() - before,
            "worker_fetch_hits": counters.get("fetch_hits"),
            "worker_publishes": counters.get("publishes"),
            "n_devices": len(devices),
            "sharded_key": key,
        })
