"""Repeater searcher wrapper: noisy objectives evaluated as seed-varied
repeats, wrapped searcher learns from the group mean
(ray.tune.search.Repeater parity)."""

import numpy as np
import pytest

from distributed_machine_learning_tpu import tune
from distributed_machine_learning_tpu.tune.search.base import Searcher
from distributed_machine_learning_tpu.tune.search_space import SearchSpace


class SpySearcher(Searcher):
    """Deterministic inner searcher that records what it observes."""

    def __init__(self):
        self.suggested = []
        self.completed = []

    def suggest(self, trial_index):
        if trial_index >= 3:
            return None
        cfg = {"x": float(trial_index), "seed": 100 + trial_index}
        self.suggested.append(trial_index)
        return cfg

    def on_trial_complete(self, trial_id, config, result, metric, mode):
        self.completed.append((trial_id, dict(config), result))


def _space():
    return SearchSpace({"x": tune.uniform(0, 1), "seed": 0})


def test_repeater_groups_and_seed_variation():
    inner = SpySearcher()
    rep = tune.Repeater(inner, repeat=3)
    rep.set_search_space(_space(), seed=0)
    configs = [rep.suggest(i) for i in range(9)]
    # 3 groups of 3; inner asked exactly once per group.
    assert inner.suggested == [0, 1, 2]
    for g in range(3):
        group = configs[g * 3:(g + 1) * 3]
        assert all(c["x"] == float(g) for c in group)
        seeds = [c["seed"] for c in group]
        assert seeds[0] == 100 + g          # repeat 0 keeps the base seed
        assert len(set(seeds)) == 3         # later repeats vary it
    # Inner exhaustion propagates at the group boundary.
    assert rep.suggest(9) is None


def test_repeater_feeds_mean_to_inner():
    inner = SpySearcher()
    rep = tune.Repeater(inner, repeat=3)
    rep.set_search_space(_space(), seed=0)
    for i in range(3):
        rep.suggest(i)
    losses = [2.0, 4.0, 9.0]
    for i, loss in enumerate(losses):
        rep.on_trial_complete(
            f"trial_{i:05d}", {"x": 0.0}, {"loss": loss}, "loss", "min"
        )
    assert len(inner.completed) == 1
    tid, cfg, result = inner.completed[0]
    assert tid == "repeat_group_00000"
    assert cfg["x"] == 0.0 and cfg["seed"] == 100  # the BASE config
    assert result["loss"] == pytest.approx(np.mean(losses))


def test_repeater_errored_repeats():
    """Errored repeats (result None / NaN) are excluded from the mean; a
    fully-failed group completes the inner searcher with result=None."""
    inner = SpySearcher()
    rep = tune.Repeater(inner, repeat=2)
    rep.set_search_space(_space(), seed=0)
    rep.suggest(0), rep.suggest(1), rep.suggest(2), rep.suggest(3)
    rep.on_trial_complete("trial_00000", {}, None, "loss", "min")
    rep.on_trial_complete("trial_00001", {}, {"loss": 6.0}, "loss", "min")
    assert inner.completed[-1][2] == {"loss": 6.0}  # mean over survivors
    rep.on_trial_complete("trial_00002", {}, None, "loss", "min")
    rep.on_trial_complete("trial_00003", {}, {"loss": float("nan")},
                          "loss", "min")
    assert inner.completed[-1][2] is None  # nothing finite: errored group


def test_repeater_e2e_with_bayesopt(tmp_results):
    """Through tune.run: 2x repeats over a noisy quadratic; the experiment
    runs every repeat as its own trial and the wrapped GP still learns."""

    def noisy(config):
        rng = np.random.default_rng(config["seed"])
        loss = (config["x"] - 0.3) ** 2 + 0.05 * rng.standard_normal()
        tune.report(loss=float(loss))

    inner = tune.BayesOptSearch(random_search_steps=2)
    analysis = tune.run(
        noisy, {"x": tune.uniform(0.0, 1.0), "seed": 7},
        metric="loss", mode="min", num_samples=8,
        search_alg=tune.Repeater(inner, repeat=2),
        storage_path=tmp_results, name="repeater_e2e", verbose=0,
    )
    assert analysis.num_terminated() == 8
    # 4 groups of 2: consecutive trials share x but not seeds.
    xs = [t.config["x"] for t in analysis.trials]
    seeds = [t.config["seed"] for t in analysis.trials]
    for g in range(4):
        assert xs[2 * g] == xs[2 * g + 1]
        assert seeds[2 * g] != seeds[2 * g + 1]
    # The GP observed group means: one completion per group.
    assert len(inner._y) == 4


def test_repeater_group_with_crashed_member_still_dispatches(tmp_results):
    """An ERRORed repeat completes to the searcher with result=None
    (tune/_driver.py finish), so the group dispatches its mean over the
    survivors instead of stalling forever."""

    def flaky(config):
        # SpySearcher's base seeds are 100+group; folded repeat seeds differ
        # — so exactly the non-first repeat of every group crashes.
        if config["seed"] not in (100, 101):
            raise RuntimeError("boom")
        tune.report(loss=float(config["x"]))

    inner = SpySearcher()
    tune.run(
        flaky, {"x": tune.uniform(0.0, 1.0), "seed": 7},
        metric="loss", mode="min", num_samples=4,
        search_alg=tune.Repeater(inner, repeat=2),
        storage_path=tmp_results, name="repeater_flaky", verbose=0,
    )
    # Both groups dispatched despite one crashed member each.
    assert len(inner.completed) == 2
    for _, cfg, result in inner.completed:
        assert result == {"loss": pytest.approx(cfg["x"])}


def test_repeater_composes_with_points_to_evaluate(tmp_results):
    """maybe_warm_start keeps the Repeater OUTERMOST (warm start moves
    inside): the point config is itself repeated, and group/id alignment
    holds so means map to the right configs."""

    def quadratic(config):
        tune.report(loss=float((config["x"] - 0.25) ** 2))

    inner = SpySearcher()
    analysis = tune.run(
        quadratic, {"x": tune.uniform(0.0, 1.0), "seed": 3},
        metric="loss", mode="min", num_samples=6,
        search_alg=tune.Repeater(inner, repeat=2),
        points_to_evaluate=[{"x": 0.5}],
        storage_path=tmp_results, name="repeater_points", verbose=0,
    )
    assert analysis.num_terminated() == 6
    xs = [t.config["x"] for t in analysis.trials]
    assert xs[0] == 0.5 and xs[1] == 0.5  # the point ran `repeat` times
    # Inner saw one mean per group, each matching that group's config.
    # Key by the deterministic group ids, NOT arrival order: trials run as
    # concurrent threads, so on a loaded machine a later group's repeats
    # can both finish (and dispatch their mean) before group 0's — the
    # completion LIST order is thread-finish order by design.  Asserting
    # ``completed[0]`` was the point group made this test fail under full-
    # suite load while passing alone.
    assert len(inner.completed) == 3
    by_tid = {tid: (cfg, result) for tid, cfg, result in inner.completed}
    assert set(by_tid) == {f"repeat_group_{g:05d}" for g in range(3)}
    for cfg, result in by_tid.values():
        assert result["loss"] == pytest.approx((cfg["x"] - 0.25) ** 2)
    # Group 0 IS the warm-start point (id alignment holds through the
    # Repeater-outside/WarmStart-inside composition).
    assert by_tid["repeat_group_00000"][0]["x"] == 0.5


def test_repeater_metric_override_through_warmstart():
    """A searcher-level metric override is found through wrapper layers
    (the warm-start composition interposes a WarmStartSearcher), and
    dispatched group state is released."""
    from distributed_machine_learning_tpu.tune.search.base import (
        maybe_warm_start,
    )

    class OverrideSpy(SpySearcher):
        metric = "val_acc"
        mode = "max"

    inner = OverrideSpy()
    rep = maybe_warm_start(tune.Repeater(inner, repeat=2),
                           [{"x": 0.9, "seed": 1}])
    rep.set_search_space(_space(), seed=0)
    for i in range(2):
        rep.suggest(i)
    for i, acc in enumerate((0.6, 0.8)):
        rep.on_trial_complete(
            f"trial_{i:05d}", {"x": 0.9},
            {"loss": 99.0, "val_acc": acc}, "loss", "min"
        )
    assert len(inner.completed) == 1
    # The group mean is keyed by the OVERRIDE metric, so the inner
    # searcher's own _effective_score can consume it.
    assert inner.completed[0][2] == {"val_acc": pytest.approx(0.7)}
    assert rep._group_configs == {} and rep._group_scores == {}


def test_repeater_in_vectorized_runner(tmp_results):
    """Repeats share the static config, so a Repeater group vmaps into one
    population program — seeds are exactly the vectorized axis."""
    from distributed_machine_learning_tpu.data import dummy_regression_data

    train, val = dummy_regression_data(
        num_samples=96, seq_len=6, num_features=3, seed=4
    )
    inner = tune.BayesOptSearch(random_search_steps=1)
    analysis = tune.run_vectorized(
        {"model": "mlp", "learning_rate": tune.loguniform(1e-3, 1e-1),
         "num_epochs": 2, "batch_size": 32, "seed": 11},
        train_data=train, val_data=val,
        metric="validation_loss", num_samples=6, max_batch_trials=6,
        search_alg=tune.Repeater(inner, repeat=3),
        storage_path=tmp_results, name="repeater_vec", verbose=0,
    )
    assert analysis.num_terminated() == 6
    lrs = [t.config["learning_rate"] for t in analysis.trials]
    seeds = [t.config["seed"] for t in analysis.trials]
    assert lrs[0] == lrs[1] == lrs[2] and lrs[3] == lrs[4] == lrs[5]
    assert len(set(seeds[:3])) == 3  # the repeats vary only the seed
    assert len(inner._y) == 2        # one observation per group
