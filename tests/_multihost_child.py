"""Child process body for the 2-process jax.distributed CPU test.

Launched by tests/test_multihost.py with a sanitized CPU env. Each process
joins the distributed runtime via parallel/multihost.py's own initialize()
(the non-degenerate path single-process tests can't reach), then exercises
barrier / broadcast / multihost_mesh / global_batch_array across the two
processes and writes its observations as JSON for the parent to assert.
"""

import json
import os
import sys


def main() -> None:
    idx, nproc, port, outfile = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    result = {}
    try:
        import jax

        try:
            # Cross-process CPU collectives need a backend; gloo ships in
            # jaxlib. Older/newer jax spell the knob differently.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception as exc:  # pragma: no cover - version drift
            result["collectives_note"] = repr(exc)

        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec as P

        from distributed_machine_learning_tpu.parallel import multihost

        active = multihost.initialize(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=nproc,
            process_id=idx,
        )
        result.update(multihost.describe(), active=bool(active))
        result["is_coordinator"] = multihost.is_coordinator()

        multihost.barrier("phase-1")

        # Coordinator's value must win on every process.
        seed = {"x": np.arange(3.0) + (0 if idx == 0 else 99)}
        got = multihost.broadcast_from_coordinator(seed)
        result["broadcast_x"] = np.asarray(got["x"]).tolist()

        mesh = multihost.multihost_mesh()
        result["mesh_shape"] = {k: int(v) for k, v in mesh.shape.items()}

        # Host-local shard -> global array -> a jitted cross-process
        # reduction (the collective rides the distributed runtime).
        local = np.full((2, 4), float(idx), np.float32)
        garr = multihost.global_batch_array(local, mesh, P("dp"))
        result["global_shape"] = list(garr.shape)
        total = jax.jit(jnp.sum)(garr)
        result["total"] = float(total)

        # ONE MODEL OVER TWO PROCESSES: the full GSPMD train step (dp=4
        # spanning both processes' devices) — gradients all-reduce across
        # the process boundary; every process must observe the same loss.
        import optax

        from distributed_machine_learning_tpu.models import build_model
        from distributed_machine_learning_tpu.parallel.train_step import (
            make_sharded_train_step,
        )

        model = build_model({"model": "mlp", "hidden_sizes": (8,),
                             "dropout": 0.0})
        init_fn, step_fn = make_sharded_train_step(
            model, optax.adam(1e-2),
            lambda p, t: jnp.mean((p - t) ** 2), mesh, shard_seq=False,
        )
        # DIFFERENT data per process: if the dp collective silently
        # degraded to per-process local reductions, each process would see
        # its own local-mean loss and the cross-process equality assertion
        # in the parent would catch it. (Identical per-host data would make
        # that check vacuous — code review r4.)
        rng = np.random.RandomState(idx)
        xg = multihost.global_batch_array(
            rng.normal(size=(2, 4, 3)).astype(np.float32), mesh, P("dp")
        )
        yg = multihost.global_batch_array(
            np.full((2, 1), float(idx), np.float32), mesh, P("dp")
        )
        with mesh:
            # init from a host-local sample: eager flax init over a
            # process-spanning global array is rejected by some jax
            # versions (non-fully-addressable shards).
            params, opt_state = init_fn(jax.random.key(0),
                                        jnp.zeros((1, 4, 3), jnp.float32))
            losses = []
            for i in range(3):
                params, opt_state, loss = step_fn(
                    params, opt_state, xg, yg, jax.random.key(i)
                )
                losses.append(float(loss))
        result["train_losses"] = [round(l, 6) for l in losses]
        result["learns"] = losses[-1] < losses[0]

        multihost.barrier("phase-2")
        result["ok"] = True
    except Exception:  # noqa: BLE001 - parent decides skip vs fail
        import traceback

        result["ok"] = False
        result["error"] = traceback.format_exc()[-2000:]
    with open(outfile, "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
