"""loop/: the self-healing serving loop (ISSUE 17) — drift detection,
journaled retrain episodes, guarded promotion with probation rollback,
and the chaos-hardened end-to-end: drifting stream + producer crash +
mid-promotion replica kill + controller crash, with zero dropped
requests, zero serving-path compiles after warmup, and one trace id
spanning detection through probation."""

import glob
import json
import os

import numpy as np
import pytest

from distributed_machine_learning_tpu import chaos, loop, obs, serve
from distributed_machine_learning_tpu.models import build_model
from distributed_machine_learning_tpu.serve.export import (
    BUNDLE_VERSION,
    write_bundle,
)
from distributed_machine_learning_tpu.tune._regression_program import (
    detect_call_convention,
)

SEQ, FEAT = 4, 3
_W = np.array([0.7, -0.4, 1.1], np.float32)

DRIFT_SPEC = {
    "at_request": 0, "feature_shift": 2.5,
    "label_scale": 1.0, "label_shift": 0.5, "seed": 11,
}


def _make_xy(n, seed, drift=None):
    """The synthetic labeled stream: stationary by default, shifted
    through chaos.apply_drift when ``drift`` is given."""
    r = np.random.default_rng(seed)
    x = r.standard_normal((n, SEQ, FEAT)).astype(np.float32)
    y = (x[:, -2:, :] @ _W).mean(axis=1, keepdims=True)
    if drift is not None:
        x, y = chaos.apply_drift(drift, x, y)
    return x.astype(np.float32), y.astype(np.float32)


def _drifted_data_fn(kind):
    seeds = {"train": 100, "holdout": 200, "probation": 300}
    return _make_xy(48, seeds[kind], DRIFT_SPEC)


CONFIG = {"model": "mlp", "hidden_sizes": [8], "seed": 3}


@pytest.fixture(scope="module")
def incumbent_variables():
    """One briefly-trained incumbent shared by the module (training it
    once keeps gate comparisons meaningful without per-test fit cost)."""
    x, y = _make_xy(64, 1)
    model = build_model(CONFIG)
    probe, _ = detect_call_convention(model, x[:1])
    variables = {"params": probe["params"]}
    if "batch_stats" in probe:
        variables["batch_stats"] = probe["batch_stats"]
    variables, _ = loop.fine_tune(
        CONFIG, variables, x, y, epochs=8, learning_rate=0.05, seed=0
    )
    return variables


def _bundle_dir(tmp_path, variables, name="incumbent", scale=None):
    out = str(tmp_path / name)
    if scale is not None:
        import jax

        variables = dict(variables)
        variables["params"] = jax.tree.map(
            lambda a: np.asarray(a) * scale, variables["params"]
        )
    write_bundle(
        out,
        {"bundle_version": BUNDLE_VERSION, "config": CONFIG,
         "precision": "f32"},
        variables,
    )
    return out


def _server(bundle_dir, fault_plan=None, num_replicas=1):
    srv = serve.PredictionServer(
        serve.load_bundle(bundle_dir), port=0,
        num_replicas=num_replicas, max_bucket=16, fault_plan=fault_plan,
    )
    srv.warmup(_make_xy(1, 0)[0])
    return srv


def _controller(srv, tmp_path, drift=None, plan=None, **cfg_kwargs):
    drift = drift or loop.DriftMonitor(window=24, z_threshold=4.0,
                                       sustain=4)
    journal = loop.LoopJournal(str(tmp_path / "loop.json"))
    cfg = loop.LoopConfig(retrain_epochs=5, probation_batches=4,
                          **cfg_kwargs)
    ctl = loop.SelfHealingController(
        srv, journal, drift, _drifted_data_fn, str(tmp_path),
        cfg, fault_plan=plan,
    )
    return ctl, drift, journal


def _feed(srv, n, seed0, drift=None):
    """``n`` requests through the live replica set + drift monitor;
    returns mean served MAPE."""
    apes = []
    for i in range(n):
        xb, yb = _make_xy(4, seed0 + i, drift)
        preds = np.asarray(srv.replicas.predict(xb))
        srv.metrics.observe_streams(
            float(np.mean(xb)), float(np.mean(preds))
        )
        apes.append(float(np.mean(
            np.abs(yb - preds) / (np.abs(yb) + 1e-8)
        )))
    return float(np.mean(apes))


# --------------------------------------------------------------------------
# drift monitor
# --------------------------------------------------------------------------


def test_drift_monitor_trigger_and_debounce():
    mon = loop.DriftMonitor(window=16, z_threshold=4.0, sustain=3)
    try:
        r = np.random.default_rng(0)
        for _ in range(20):  # freeze baselines
            mon.observe(float(r.normal()), float(r.normal()))
        for _ in range(10):  # stationary current window
            mon.observe(float(r.normal()), float(r.normal()))
        assert mon.consume_trigger() is None
        snap = mon.snapshot()
        assert snap["baseline_frozen_features"]
        assert snap["triggers"] == 0

        for _ in range(20):  # a genuine shift on both streams
            mon.observe(float(5 + r.normal()), float(5 + r.normal()))
        snap = mon.snapshot()
        assert snap["triggers"] == 1 and snap["trigger_pending"]
        assert snap["score_features"] > 4.0

        detail = mon.consume_trigger()
        assert detail is not None and "features" in detail["streams"]
        assert mon.consume_trigger() is None  # exactly once
        # Disarmed: further drift cannot re-trigger until rearm.
        for _ in range(20):
            mon.observe(float(9 + r.normal()), float(9 + r.normal()))
        assert mon.snapshot()["triggers"] == 1
    finally:
        mon.close()


def test_drift_monitor_rearm_semantics():
    mon = loop.DriftMonitor(window=16, z_threshold=4.0, sustain=3)
    try:
        r = np.random.default_rng(1)
        for _ in range(40):
            mon.observe(float(r.normal()), float(r.normal()))
        for _ in range(20):
            mon.observe(float(6 + r.normal()), float(6 + r.normal()))
        assert mon.consume_trigger() is not None

        # rearm(rebaseline=True): the drifted distribution is the new
        # normal — continuing at the same level must NOT re-trigger.
        mon.rearm(rebaseline=True)
        for _ in range(40):
            mon.observe(float(6 + r.normal()), float(6 + r.normal()))
        assert mon.consume_trigger() is None

        # ...but a FURTHER shift from the adopted baseline re-triggers.
        for _ in range(20):
            mon.observe(float(12 + r.normal()), float(12 + r.normal()))
        assert mon.consume_trigger() is not None

        # rearm(rebaseline=False) keeps the old baseline: still-drifted
        # traffic re-triggers (the rollback case — drift is still real).
        mon.rearm(rebaseline=False)
        for _ in range(20):
            mon.observe(float(12 + r.normal()), float(12 + r.normal()))
        assert mon.consume_trigger() is not None
    finally:
        mon.close()


def test_drift_monitor_registry_family():
    mon = loop.DriftMonitor(window=8)
    try:
        fams = obs.get_registry().snapshot()["families"]
        assert "drift" in fams and fams["drift"]["observations"] == 0
    finally:
        mon.close()
    assert "drift" not in obs.get_registry().snapshot()["families"]


# --------------------------------------------------------------------------
# journal
# --------------------------------------------------------------------------


def test_journal_transitions_and_exactly_once_guard(tmp_path):
    j = loop.LoopJournal(str(tmp_path / "j.json"))
    ep = j.begin_episode("trace-1", trigger=["features"])
    assert ep == 1 and j.state == "detected"
    with pytest.raises(RuntimeError):  # open episode blocks a second
        j.begin_episode("trace-2")
    j.transition("retraining", warm_start="/ckpt/3")
    j.transition("candidate", candidate="/cand")
    j.transition("probation", swapped=True)
    j.transition("promoted")
    # Data merges across transitions; terminal counters bump once.
    assert j.data["candidate"] == "/cand" and j.data["swapped"] is True
    snap = j.snapshot()
    assert snap["completed_episodes"] == 1 and snap["promotions"] == 1
    assert not j.open_episode()
    assert j.begin_episode("trace-2") == 2  # terminal episode unblocks

    # Durability: a fresh reader sees exactly the journaled state.
    j2 = loop.LoopJournal(str(tmp_path / "j.json"))
    assert j2.episode == 2 and j2.state == "detected"
    assert j2.trace_id == "trace-2"
    with pytest.raises(ValueError):
        j.transition("nonsense")


# --------------------------------------------------------------------------
# controller: crash-resume matrix, rollback, chaos legs
# --------------------------------------------------------------------------


@pytest.mark.parametrize(
    "crash_state", ["detected", "retraining", "candidate", "probation"]
)
def test_controller_crash_resume_completes_exactly_once(
    tmp_path, incumbent_variables, crash_state
):
    """Crash at EVERY journal transition; a fresh controller incarnation
    resumes from the journal and the episode completes exactly once."""
    plan = chaos.FaultPlan(seed=7, controller_crash_at=(crash_state,))
    srv = _server(_bundle_dir(tmp_path, incumbent_variables))
    ctl, drift, journal = _controller(srv, tmp_path, plan=plan)
    try:
        with pytest.raises(chaos.InjectedControllerCrash):
            ctl.run_episode({"streams": ["features"]})
        assert journal.open_episode()
        assert plan.snapshot()["controller_crashes"] == 1
        ctl.close()

        # A new incarnation (fresh journal object, same path) resumes.
        drift2 = loop.DriftMonitor(window=24, z_threshold=4.0, sustain=4)
        journal2 = loop.LoopJournal(str(tmp_path / "loop.json"))
        ctl2 = loop.SelfHealingController(
            srv, journal2, drift2, _drifted_data_fn, str(tmp_path),
            loop.LoopConfig(retrain_epochs=5, probation_batches=4),
            fault_plan=plan,
        )
        try:
            result = ctl2.resume()
            assert result is not None
            assert result["state"] in ("promoted", "rolled_back")
            assert journal2.snapshot()["completed_episodes"] == 1
            assert ctl2.resume() is None  # exactly once: terminal no-op
            assert journal2.snapshot()["completed_episodes"] == 1
        finally:
            ctl2.close()
            drift2.close()
    finally:
        ctl.close()
        drift.close()
        srv.close()


def test_probation_rollback_on_regressed_candidate(
    tmp_path, incumbent_variables
):
    """Satellite 4: a deliberately-worse candidate passes through the
    guarded promotion and is auto-rolled-back — with zero dropped
    requests and zero new serving-path compiles, counter-verified."""
    incumbent_dir = _bundle_dir(tmp_path, incumbent_variables)
    bad_dir = _bundle_dir(tmp_path, incumbent_variables, "bad", scale=25.0)
    srv = _server(incumbent_dir)
    ctl, drift, journal = _controller(srv, tmp_path)
    try:
        programs_before = srv.replicas.program_stats()
        result = ctl.promote_with_probation(bad_dir)
        assert result["state"] == "rolled_back"
        assert result["probation_mape"] > result["threshold"]
        # The fleet serves the incumbent again, remembered by path.
        assert srv.replicas.bundle.path == incumbent_dir
        assert srv.bundle.path == incumbent_dir
        assert srv.replicas.rollbacks == 1
        assert ctl.snapshot()["rollbacks"] == 1
        # Zero-recompile promotion AND rollback: same program class.
        stats = srv.replicas.program_stats()
        assert stats["new_programs_since_warmup"] == 0, stats
        assert stats["programs"] == programs_before["programs"]
        # Probation traffic all answered (predict raised nowhere), and
        # the swap history annotated the rollback for forensics.
        last = srv.replicas.swap_history[-1]
        assert last["rollback"] and last["reason"] == "probation_regression"
    finally:
        ctl.close()
        drift.close()
        srv.close()


def test_mid_retrain_crash_absorbed_by_retry_budget(
    tmp_path, incumbent_variables
):
    plan = chaos.FaultPlan(seed=3, trial_crashes=(("loop-ep1", 2),))
    srv = _server(_bundle_dir(tmp_path, incumbent_variables))
    ctl, drift, journal = _controller(srv, tmp_path, plan=plan)
    try:
        result = ctl.run_episode({"streams": ["features"]})
        assert result["state"] in ("promoted", "rolled_back")
        assert ctl.snapshot()["retrain_retries"] == 1
        assert plan.snapshot()["trial_crashes"] == 1
    finally:
        ctl.close()
        drift.close()
        srv.close()


def test_corrupt_candidate_reexported_then_promoted(
    tmp_path, incumbent_variables
):
    """One scheduled export corruption: the gate load refuses the torn
    bundle (checkpoint sha256), the episode rewinds to retraining, and
    the clean re-export promotes — the old model served throughout."""
    plan = chaos.FaultPlan(seed=5, corrupt_bundle_on_export=1)
    srv = _server(_bundle_dir(tmp_path, incumbent_variables))
    with chaos.active(plan):
        ctl, drift, journal = _controller(srv, tmp_path, plan=plan)
        try:
            result = ctl.run_episode({"streams": ["features"]})
            assert result["state"] in ("promoted", "rolled_back")
            snap = ctl.snapshot()
            assert snap["candidate_corruptions"] == 1
            assert plan.snapshot()["bundle_corruptions"] == 1
        finally:
            ctl.close()
            drift.close()
            srv.close()


def test_corrupt_candidate_budget_exhausted_aborts_gracefully(
    tmp_path, incumbent_variables
):
    """A corruptor that outlives the export budget lands in ``aborted``
    with the OLD bundle still serving — degrade, don't promote."""
    plan = chaos.FaultPlan(seed=5, corrupt_bundle_on_export=5)
    incumbent_dir = _bundle_dir(tmp_path, incumbent_variables)
    srv = _server(incumbent_dir)
    with chaos.active(plan):
        ctl, drift, journal = _controller(
            srv, tmp_path, plan=plan, export_retries=1
        )
        try:
            result = ctl.run_episode({"streams": ["features"]})
            assert result["state"] == "aborted"
            assert result["reason"] == "candidate_corrupt"
            assert ctl.snapshot()["candidate_corruptions"] == 2
            assert srv.replicas.bundle.path == incumbent_dir
            x = _make_xy(3, 9)[0]
            assert np.asarray(srv.replicas.predict(x)).shape[0] == 3
        finally:
            ctl.close()
            drift.close()
            srv.close()


def test_gate_rejects_non_improving_candidate(
    tmp_path, incumbent_variables
):
    """The quality gate refuses a candidate that does not beat the
    incumbent on the holdout window — nothing is ever swapped."""
    incumbent_dir = _bundle_dir(tmp_path, incumbent_variables)
    srv = _server(incumbent_dir)
    # An impossible gate: even a better candidate cannot pass ratio 0.
    ctl, drift, journal = _controller(
        srv, tmp_path, gate_ratio=0.0, gate_margin=0.0
    )
    try:
        result = ctl.run_episode({"streams": ["features"]})
        assert result["state"] == "aborted"
        assert result["reason"] == "gate_reject"
        assert ctl.snapshot()["gate_rejects"] == 1
        assert srv.replicas.bundle.path == incumbent_dir
        assert srv.replicas.program_stats()[
            "new_programs_since_warmup"] == 0
    finally:
        ctl.close()
        drift.close()
        srv.close()


# --------------------------------------------------------------------------
# swap history + /admin/rollback (satellite 2, HTTP surface)
# --------------------------------------------------------------------------


def test_swap_history_metrics_and_admin_rollback(
    tmp_path, incumbent_variables
):
    import urllib.error
    import urllib.request

    incumbent_dir = _bundle_dir(tmp_path, incumbent_variables)
    next_dir = _bundle_dir(tmp_path, incumbent_variables, "next")
    srv = _server(incumbent_dir)
    host, port = srv.start()
    base = f"http://{host}:{port}"

    def post(path, body):
        req = urllib.request.Request(
            base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    def metrics():
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            return json.loads(r.read())

    try:
        # A fresh fleet has retired nothing: rollback is 409, not 500.
        assert metrics()["swap"]["history_depth"] == 0
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            post("/admin/rollback", {})
        assert exc_info.value.code == 409

        post("/admin/swap", {"bundle": next_dir})
        m = metrics()["swap"]
        assert m["history_depth"] == 1
        assert m["retained"] == [incumbent_dir]

        out = post("/admin/rollback", {"reason": "operator"})
        assert out["rollback"] and out["rolled_back_to"] == incumbent_dir
        assert srv.replicas.bundle.path == incumbent_dir
        m = metrics()["swap"]
        assert m["rollbacks_total"] == 1
        # The rolled-back-FROM bundle is itself retained (roll forward
        # stays possible), so depth is 1 again — now holding next_dir.
        assert m["history_depth"] == 1
        assert m["retained"] == [next_dir]
        assert m["history"][-1]["rollback"] is True
    finally:
        srv.close()


def test_swap_history_bounded(tmp_path, incumbent_variables):
    from distributed_machine_learning_tpu.serve import swap as swap_lib

    dirs = [
        _bundle_dir(tmp_path, incumbent_variables, f"gen{i}")
        for i in range(swap_lib.HISTORY_DEPTH + 3)
    ]
    srv = _server(dirs[0])
    try:
        for d in dirs[1:]:
            swap_lib.hot_swap(srv.replicas, serve.load_bundle(d))
        assert len(srv.replicas.bundle_history) == swap_lib.HISTORY_DEPTH
        retained = [e["path"] for e in srv.replicas.bundle_history]
        assert retained == dirs[-swap_lib.HISTORY_DEPTH - 1:-1]
    finally:
        srv.close()


# --------------------------------------------------------------------------
# the chaos-hardened end-to-end (acceptance)
# --------------------------------------------------------------------------


def test_self_healing_e2e_under_chaos(tmp_path, incumbent_variables):
    """ISSUE 17 acceptance: a drifting stream with a producer crash, a
    mid-promotion replica kill, a mid-swap crash, and one controller
    crash.  Quality recovers after promotion, a deliberately-bad
    candidate auto-rolls-back, zero requests dropped, zero serving-path
    compiles after warmup, and ONE trace id spans detection -> retrain ->
    swap -> probation — verified in experiment_state.json["loop"],
    /metrics, and the trace stream."""
    obs.configure(trace_dir=str(tmp_path / "traces"),
                  dump_dir=str(tmp_path / "dumps"))
    plan = chaos.FaultPlan(
        seed=13,
        drift_inject={"at_request": 28, "feature_shift": 2.5,
                      "label_scale": 1.0, "label_shift": 0.5},
        producer_crash_at=35,            # the labeled-stream producer
        replica_kills=((50, 0),),        # lands mid-probation traffic
        mid_swap_crash=(1,),             # first slot switch of the swap
        controller_crash_at=("candidate",),
    )
    incumbent_dir = _bundle_dir(tmp_path, incumbent_variables)
    srv = _server(incumbent_dir, fault_plan=plan, num_replicas=2)
    drift = loop.DriftMonitor(window=24, z_threshold=4.0, sustain=4)
    srv.metrics.attach_drift(drift)

    global DRIFT_SPEC
    spec_before = DRIFT_SPEC
    dropped = 0
    sent = 0

    def feed(n, seed0):
        """The labeled request stream: drift injection via the plan, a
        producer crash restarted by the harness (degrade, don't stop)."""
        nonlocal dropped, sent
        apes = []
        for i in range(n):
            try:
                plan.maybe_producer_fault(
                    _feed_index[0], name="loop-stream"
                )
            except chaos.InjectedProducerCrash:
                continue  # producer restarts; that request is re-made
            spec = plan.maybe_drift(_feed_index[0])
            xb, yb = _make_xy(4, seed0 + i, spec)
            sent += 1
            _feed_index[0] += 1
            try:
                body = srv.handle_predict({"instances": xb.tolist()})
            except Exception:  # noqa: BLE001 - drops are the assertion
                dropped += 1
                continue
            preds = np.asarray(body["predictions"], np.float32)
            apes.append(float(np.mean(
                np.abs(yb - preds) / (np.abs(yb) + 1e-8)
            )))
        return float(np.mean(apes)) if apes else float("nan")

    _feed_index = [0]
    try:
        # The e2e's retrain windows must carry the SAME injected shift
        # the serving stream sees.
        DRIFT_SPEC = {**plan._drift_inject, "seed": plan.seed,
                      "at_request": 0}
        ctl, _, journal = _controller(srv, tmp_path, drift=drift,
                                      plan=plan)

        feed(10, 1000)                       # pre-drift baseline
        pre_drift_mape = feed(8, 2000)
        degraded_mape = feed(30, 3000)       # drift fires at request 40
        assert plan.snapshot()["drift_injections"] == 1
        assert plan.snapshot()["producer_crashes"] == 1
        assert degraded_mape > pre_drift_mape * 1.5  # visibly degraded
        assert drift.snapshot()["trigger_pending"]

        # Episode 1: crashes at the journaled "candidate" transition.
        with pytest.raises(chaos.InjectedControllerCrash):
            ctl.poll()
        ctl.close()

        # New incarnation resumes from the journal; the mid-swap crash
        # fires during ITS promotion and is converged by one retry; the
        # scheduled replica kill lands inside probation traffic.
        journal2 = loop.LoopJournal(str(tmp_path / "loop.json"))
        ctl2 = loop.SelfHealingController(
            srv, journal2, drift, _drifted_data_fn, str(tmp_path),
            loop.LoopConfig(retrain_epochs=5, probation_batches=4),
            fault_plan=plan,
        )
        result = ctl2.resume()
        assert result is not None and result["state"] == "promoted"
        snap = plan.snapshot()
        assert snap["mid_swap_crashes"] == 1
        assert snap["controller_crashes"] == 1
        assert ctl2.snapshot()["swap_retries"] == 1

        recovered_mape = feed(10, 4000)      # quality recovers
        assert recovered_mape < degraded_mape * 0.5, (
            recovered_mape, degraded_mape,
        )

        # A deliberately-bad candidate through the SAME guarded path:
        # probation catches it and auto-rolls-back to the promotion.
        promoted_path = srv.replicas.bundle.path
        bad_dir = _bundle_dir(tmp_path, incumbent_variables, "bad",
                              scale=25.0)
        bad = ctl2.promote_with_probation(bad_dir)
        assert bad["state"] == "rolled_back"
        assert srv.replicas.bundle.path == promoted_path
        post_rollback_mape = feed(8, 5000)
        assert post_rollback_mape < degraded_mape * 0.5

        # -- the counters the issue names ------------------------------------
        assert dropped == 0 and sent > 50
        stats = srv.replicas.program_stats()
        assert stats["new_programs_since_warmup"] == 0, stats

        state_path = ctl2.save_state()
        doc = json.load(open(state_path))["loop"]
        assert doc["promotions"] == 1 and doc["rollbacks"] == 1
        assert doc["resumes"] == 1
        # One journaled episode completed (the bad-candidate probation
        # ran outside an episode, through the same guarded path).
        assert doc["journal"]["completed_episodes"] == 1
        assert doc["journal"]["promotions"] == 1

        m = srv.handle_metrics()
        assert m["drift"]["triggers"] == 1
        assert m["swap"]["rollbacks_total"] == 1
        assert m["injected_faults"]["mid_swap_crashes"] == 1
        assert m["injected_faults"]["replica_kills"] == 1
        assert m["swap"]["swaps_total"] >= 2

        # -- one trace id spans detection -> retrain -> swap -> probation ----
        trace_id = journal2.trace_id
        assert trace_id
        assert all(h.get("state") for h in journal2.history)
        obs.flush()
        spans = []
        for f in glob.glob(str(tmp_path / "traces" / "*.jsonl")):
            with open(f) as fh:
                spans += [json.loads(line) for line in fh if line.strip()]
        loop_spans = {
            s["name"] for s in spans
            if s.get("args", {}).get("trace_id") == trace_id
        }
        assert {"loop.resume", "loop.retrain", "loop.promote"} <= \
            loop_spans, loop_spans
        ctl2.close()
    finally:
        DRIFT_SPEC = spec_before
        drift.close()
        srv.close()
        obs.shutdown()
