"""Pod-scale serving (ISSUE 19): TP-sharded inference gangs that span
processes.

Three layers:

* **Units** (always run): the broadcast header wire format, the chaos
  gang hooks' decision logic, the manifest's recorded source topology.
* **Single-process resharding matrix** (always run): a bundle exported
  from one topology served on an in-process multi-device mesh must
  answer bit-identically to the unsharded reference engine.
* **Gang e2e** (probe-gated on 2-process CPU collectives): a real
  2-process gang serves TP-sharded bundles bit-identically with zero
  serving-path compiles after warmup, survives a mid-traffic chaos
  member kill with zero dropped non-shed requests (teardown → redispatch
  → monitor rebuild), and hot-swaps whole gangs.

The sharded ruleset below is chosen deliberately: Dense_0 column-sharded
feeding a WIDER second layer means XLA all-gathers the narrow activations
(exact) instead of psumming wide partials (reordered accumulation), so
sharded and unsharded programs are bit-identical — the property every
parity assertion here leans on.
"""

import json
import os
import time

import numpy as np
import pytest

import _env_probe

from distributed_machine_learning_tpu import chaos, serve, tune
from distributed_machine_learning_tpu.data import dummy_regression_data
from distributed_machine_learning_tpu.serve import _gang_member as gm
from distributed_machine_learning_tpu.serve.gang import (
    GangReplica,
    gang_counters,
    make_gang_replica_factory,
)

# Column-shard Dense_0 into a wider Dense_1: the all-gather propagation
# choice is exact, so sharded == unsharded bit-for-bit.
TP_RULES = [
    ["params/Dense_0/kernel", [None, "tp"]],
    ["params/Dense_0/bias", ["tp"]],
    [".*", []],
]


def _train_bundle(tmp, name, seed, rules):
    train, val = dummy_regression_data(
        num_samples=96, seq_len=6, num_features=4, seed=7
    )
    config = {
        "model": "mlp", "hidden_sizes": [16, 64], "learning_rate": 0.005,
        "num_epochs": 2, "batch_size": 32, "seed": seed,
    }
    if rules is not None:
        config["partition_rules"] = rules
    analysis = tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        config,
        metric="validation_loss", mode="min", num_samples=1,
        storage_path=os.path.join(tmp, f"exp_{name}"), name=name, verbose=0,
    )
    bundle_dir = os.path.join(tmp, f"bundle_{name}")
    serve.export_bundle(analysis, bundle_dir)
    return bundle_dir, np.asarray(val.x[:5], np.float32)


@pytest.fixture(scope="module")
def bundles(tmp_path_factory):
    """One sharded (TP rules) and one replicated bundle + the reference
    predictions of each from the plain single-process engine."""
    tmp = str(tmp_path_factory.mktemp("gang_bundles"))
    sharded_dir, x = _train_bundle(tmp, "tp", seed=5, rules=TP_RULES)
    replicated_dir, _ = _train_bundle(tmp, "rep", seed=9, rules=[[".*", []]])
    out = {}
    for key, bdir in (("sharded", sharded_dir), ("replicated", replicated_dir)):
        bundle = serve.load_bundle(bdir)
        ref = serve.InferenceEngine(bundle, max_bucket=8).predict(x)
        out[key] = {"dir": bdir, "ref": ref}
    out["x"] = x
    return out


# --------------------------------------------------------------------------
# units
# --------------------------------------------------------------------------


def test_broadcast_header_roundtrip():
    hdr = gm.encode_header(gm.OP_PREDICT, 17, (8, 6, 4), np.float32)
    assert hdr.dtype == np.int64 and hdr.shape == (gm.HEADER_LEN,)
    op, n, shape, dtype = gm.decode_header(hdr)
    assert (op, n, shape, dtype) == (gm.OP_PREDICT, 17, (8, 6, 4), "float32")
    # Warmup/stop headers carry empty shapes.
    op, _, shape, _ = gm.decode_header(
        gm.encode_header(gm.OP_STOP, 1, (), "float32")
    )
    assert op == gm.OP_STOP and shape == ()
    with pytest.raises(ValueError):
        gm.encode_header(gm.OP_PREDICT, 1, (1,) * 7, np.float32)
    with pytest.raises(ValueError):
        gm.encode_header(gm.OP_PREDICT, 1, (4,), np.complex64)


def test_chaos_gang_member_kill_decision(monkeypatch):
    exits = []
    monkeypatch.setattr(os, "_exit", lambda code: exits.append(code))
    plan = chaos.FaultPlan(kill_gang_member_at_request=[(3, 1)])
    # Wrong round / wrong member / wrong incarnation: no fire.
    plan.maybe_kill_gang_member(2, 1)
    plan.maybe_kill_gang_member(3, 0)
    plan.maybe_kill_gang_member(3, 1, incarnation=2)
    assert exits == [] and "gang_member_kills" not in plan.snapshot()
    # The scheduled (round, member) fires exactly once, then is consumed.
    plan.maybe_kill_gang_member(3, 1)
    assert exits == [86]
    assert plan.snapshot()["gang_member_kills"] == 1
    plan.maybe_kill_gang_member(3, 1)
    assert exits == [86]


def test_chaos_gang_bootstrap_hang_decision():
    plan = chaos.FaultPlan(gang_bootstrap_hang=[(1, 0.05)])
    t0 = time.monotonic()
    plan.maybe_gang_bootstrap_hang(0)  # not scheduled
    plan.maybe_gang_bootstrap_hang(1, incarnation=2)  # rebuilt: clean
    assert time.monotonic() - t0 < 0.04
    assert "gang_bootstrap_hangs" not in plan.snapshot()
    plan.maybe_gang_bootstrap_hang(1)
    assert time.monotonic() - t0 >= 0.05
    assert plan.snapshot()["gang_bootstrap_hangs"] == 1
    t1 = time.monotonic()
    plan.maybe_gang_bootstrap_hang(1)  # consumed: no second stall
    assert time.monotonic() - t1 < 0.04


def test_manifest_records_source_topology(bundles):
    """Satellite: export records the training topology so load_bundle
    decides reshard-vs-direct (and `dml-tpu serve` logs source→target)
    from the manifest alone, never by probing chunk files."""
    bundle = serve.load_bundle(bundles["sharded"]["dir"])
    topo = json.load(
        open(os.path.join(bundles["sharded"]["dir"], "bundle.json"))
    )["source"]["topology"]
    assert set(topo) == {"mesh_shape", "process_count", "rules_fingerprint"}
    assert topo["process_count"] >= 1
    assert str(topo["rules_fingerprint"]).startswith("pr_")
    assert bundle.source_topology == topo


def test_gang_replica_requires_on_disk_bundle(bundles):
    bundle = serve.load_bundle(bundles["sharded"]["dir"])
    bundle.path = None
    with pytest.raises(ValueError, match="on-disk bundle"):
        GangReplica(0, bundle)


# --------------------------------------------------------------------------
# resharding matrix, single-process half: serve on an in-process mesh
# --------------------------------------------------------------------------


@pytest.mark.parametrize("source", ["sharded", "replicated"])
def test_mesh_engine_bit_identical_single_process(bundles, source):
    """{1-device, TP-ruled} exports × 1-process multi-device serving mesh:
    the resharding load route must not move a single bit."""
    import jax
    from jax.sharding import Mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 (virtual) devices")
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    sb = serve.load_bundle(bundles[source]["dir"], mesh=mesh)
    eng = serve.InferenceEngine(sb, max_bucket=8, mesh=mesh, aot_cache=False)
    out = eng.predict(bundles["x"])
    np.testing.assert_array_equal(out, bundles[source]["ref"])


# --------------------------------------------------------------------------
# gang e2e (probe-gated: two real processes, gloo collectives)
# --------------------------------------------------------------------------


def _require_gang_env():
    ok, why = _env_probe.multiprocess_cpu_collectives()
    if not ok:
        pytest.skip(f"2-process jax.distributed unavailable here: {why}")


@pytest.mark.parametrize("source", ["sharded", "replicated"])
def test_gang_serves_bit_identically_zero_compiles(bundles, source):
    """The tentpole acceptance: a 2-process gang answers bit-identically
    to the 1-process reference, and traffic after warmup compiles
    nothing."""
    _require_gang_env()
    bundle = serve.load_bundle(bundles[source]["dir"])
    x = bundles["x"]
    gang = GangReplica(0, bundle, processes=2, max_bucket=8)
    try:
        warm = gang.warmup(x)
        programs_after_warmup = warm["programs"]
        assert programs_after_warmup > 0
        assert warm["topology"]["process_count"] == 2
        out = gang.submit(x).result(timeout=120)
        np.testing.assert_array_equal(out, bundles[source]["ref"])
        stats = gang.engine.program_stats()
        assert stats["programs"] == programs_after_warmup, (
            "serving-path compile after warmup"
        )
        gs = gang.gang_stats()
        assert gs["members_alive"] == 2
        assert gs["incarnation"] == 1
        assert gs["source_topology"]["process_count"] == 1
        assert gang.health()["gang"]["gang_id"] == gs["gang_id"]
    finally:
        gang.retire()
    assert not gang.alive()
    counts = gang_counters().snapshot()
    assert counts["spawns"] >= 1 and counts["teardowns"] >= 1


def test_gang_soak_member_kill_zero_drops_then_swap(bundles):
    """The chaos soak + swap acceptance in one gang session:

    1. mid-traffic chaos kill of a NON-coordinator member → whole-gang
       teardown, queued/in-flight requests redispatched, monitor rebuilds
       the slot as incarnation 2 — every non-shed request answers, zero
       drops;
    2. `new_programs_since_warmup` stays 0 across the rebuild;
    3. hot swap replaces the whole gang with one serving the second
       bundle, warmed off-path — predictions flip to the new reference
       with zero serving-path compiles.
    """
    _require_gang_env()
    x = bundles["x"]
    bundle = serve.load_bundle(bundles["sharded"]["dir"])
    base = gang_counters().snapshot()
    # Round 1 is the warmup round; the kill lands on predict round 3,
    # member 1 (non-coordinator) — mid-traffic by construction.
    os.environ["DML_CHAOS_PLAN"] = json.dumps(
        {"kill_gang_member_at_request": [[3, 1]]}
    )
    try:
        rs = serve.ReplicaSet(
            bundle,
            num_replicas=1,
            max_bucket=8,
            restart=True,
            monitor_interval_s=0.1,
            replica_factory=make_gang_replica_factory(processes=2),
        )
    finally:
        os.environ.pop("DML_CHAOS_PLAN", None)
    try:
        rs.warmup(x)
        answered = 0
        deadline = time.monotonic() + 240
        for i in range(8):
            req = np.asarray(x[(i % 3):(i % 3) + 2], np.float32)
            want = bundles["sharded"]["ref"][(i % 3):(i % 3) + 2]
            while True:
                try:
                    got = rs.predict(req, timeout=60.0)
                    break
                except RuntimeError:
                    # Shed/unavailable while the slot rebuilds (429/503
                    # upstream): the client's Retry-After loop. A shed is
                    # not a drop — the request must still answer.
                    assert time.monotonic() < deadline, (
                        "gang slot never came back"
                    )
                    time.sleep(0.25)
            np.testing.assert_array_equal(got, want)
            answered += 1
        assert answered == 8, "dropped a non-shed request"

        counts = gang_counters().snapshot()
        for key in ("member_deaths", "teardowns", "rebuilds",
                    "chaos_member_kills"):
            assert counts.get(key, 0) > base.get(key, 0), key
        assert rs.replicas[0].gang_stats()["incarnation"] == 2
        assert rs.program_stats()["new_programs_since_warmup"] == 0
        assert rs.restarts >= 1

        # Swap-on-gang: fresh gang loads+warms the OTHER bundle on every
        # member off-path, then the slot switches atomically.
        new_bundle = serve.load_bundle(bundles["replicated"]["dir"])
        event = rs.hot_swap(new_bundle, sample=x)
        assert event["replicas_swapped"] == 1
        out = rs.predict(x, timeout=60.0)
        np.testing.assert_array_equal(out, bundles["replicated"]["ref"])
        assert rs.program_stats()["new_programs_since_warmup"] == 0
        assert rs.replicas[0].gang_stats()["incarnation"] == 3
    finally:
        rs.close()
