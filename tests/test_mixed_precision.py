"""Mixed precision: config["compute_dtype"]="bfloat16" must mean REAL bf16
compute — bf16 matmuls/activations through the model (flax module dtype) —
while params, optimizer state, and losses stay float32.

The reference has no precision story at all (torch f32 everywhere); on TPU
bf16 doubles MXU throughput and halves activation HBM traffic, so this is a
first-class knob of the TPU-native framework (SURVEY.md §7 design stance).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models import (
    build_model,
    compute_dtype_of,
)

FAMILIES = [
    {"model": "mlp"},
    {"model": "cnn1d"},
    {"model": "simple_transformer", "d_model": 16, "num_heads": 2,
     "num_layers": 1, "dim_feedforward": 32},
    {"model": "transformer", "d_model": 16, "num_heads": 2, "num_layers": 1,
     "dim_feedforward": 32},
    {"model": "transformer", "d_model": 16, "num_heads": 2, "num_layers": 2,
     "dim_feedforward": 32, "shared_weights": True},
    {"model": "transformer", "d_model": 16, "num_heads": 2, "num_layers": 1,
     "dim_feedforward": 32, "feedforward_type": "moe", "num_experts": 2},
    {"model": "transformer", "d_model": 16, "num_heads": 2, "num_layers": 1,
     "dim_feedforward": 32, "depthwise_separable_conv": True},
    {"model": "rnn", "hidden_size": 16, "num_layers": 1},
    {"model": "resnet18"},
]


def _init_and_apply(config, x):
    model = build_model(config)
    try:
        vs = model.init(
            {"params": jax.random.key(0), "dropout": jax.random.key(1)},
            x, deterministic=True,
        )
        out = model.apply(vs, x, deterministic=True, mutable=["moe"])[0]
    except TypeError:
        vs = model.init(
            {"params": jax.random.key(0), "dropout": jax.random.key(1)},
            x, train=False,
        )
        out = model.apply(vs, x, train=False)
    return vs, out


@pytest.mark.parametrize(
    "config", FAMILIES, ids=[
        "-".join(
            str(v) for k, v in sorted(c.items())
            if k in ("model", "feedforward_type", "shared_weights",
                     "depthwise_separable_conv")
        )
        for c in FAMILIES
    ],
)
def test_bf16_compute_f32_params(config):
    """bf16 config -> bf16 output (compute threaded end to end), f32 params."""
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 8, 6)), jnp.bfloat16
    )
    cfg = dict(config, compute_dtype="bfloat16")
    vs, out = _init_and_apply(cfg, x)
    assert out.dtype == jnp.bfloat16, (
        f"{config['model']}: output {out.dtype}, not bf16 — a layer in the "
        f"chain is missing the dtype thread and promoted back to f32"
    )
    for leaf in jax.tree_util.tree_leaves(vs["params"]):
        assert leaf.dtype == jnp.float32
    assert np.all(np.isfinite(np.asarray(out, np.float32)))


@pytest.mark.parametrize("config", [FAMILIES[0], FAMILIES[3]],
                         ids=["mlp", "transformer"])
def test_f32_default_unchanged(config):
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, 6)),
                    jnp.float32)
    _, out = _init_and_apply(dict(config), x)
    assert out.dtype == jnp.float32


def test_compute_dtype_of_resolution():
    assert compute_dtype_of({}) is None
    assert compute_dtype_of({"compute_dtype": "bfloat16"}) == jnp.bfloat16
    assert compute_dtype_of({"compute_dtype": "bf16"}) == jnp.bfloat16
    assert compute_dtype_of({"compute_dtype": "float32"}) == jnp.float32
    with pytest.raises(ValueError, match="compute_dtype"):
        compute_dtype_of({"compute_dtype": "float16"})


def test_bf16_training_tracks_f32(tmp_path):
    """A short bf16 training run stays finite and lands near the f32 loss —
    params/optimizer in f32 keep the update math stable (loss computed in
    f32 on f32-cast predictions, tune/_regression_program.py)."""
    from distributed_machine_learning_tpu import tune
    from distributed_machine_learning_tpu.data import dummy_regression_data

    train, val = dummy_regression_data(
        num_samples=256, seq_len=12, num_features=6
    )
    from distributed_machine_learning_tpu.tune import session

    losses = {}
    for dt in ("float32", "bfloat16"):
        result = {}

        def report_spy(metrics, _ckpt, _sink=result):
            _sink.update(metrics)
            return "continue"

        cfg = {
            "model": "mlp", "hidden_sizes": (32,), "learning_rate": 1e-2,
            "num_epochs": 4, "batch_size": 32, "seed": 3,
            "compute_dtype": dt,
        }
        session.set_session(
            session.Session(None, report_spy, lambda: None)
        )
        try:
            tune.train_regressor(cfg, train_data=train, val_data=val)
        finally:
            session.set_session(None)
        losses[dt] = float(result["validation_loss"])

    assert np.isfinite(losses["bfloat16"])
    # Same seed/schedule: bf16 should track f32 within a loose band (the
    # dummy target is learnable; both should reach the same basin).
    assert losses["bfloat16"] < losses["float32"] * 2.0 + 0.1
