"""compilecache/: program keys, AOT executables, counters, and the
counter-verified compile-once acceptance (ISSUE 5).

The decisive property: the SECOND occurrence of any (shape class, batch
shape, dtype, donation signature) program is free — in this process (jit
cache), in a fresh process (persistent + AOT tiers, asserted by counters,
not eyeballed), and across workers (origin tests in
test_compile_origin.py).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from distributed_machine_learning_tpu import compilecache as cc

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# program keys
# ---------------------------------------------------------------------------

BASE_CFG = {
    "model": "transformer", "d_model": 64, "num_heads": 4, "num_layers": 2,
    "batch_size": 32, "num_epochs": 5, "learning_rate": 0.01,
    "weight_decay": 1e-4, "seed": 7, "lr_schedule": "constant",
    "hidden_sizes": (16, 8),
}


def test_key_ignores_non_structural_hparams():
    """lr / weight_decay / seed ride in optimizer state and PRNG args —
    configs differing ONLY there trace identical HLO and must share a key."""
    k0 = cc.program_key(BASE_CFG)
    assert k0 == cc.program_key(
        dict(BASE_CFG, learning_rate=3.3, weight_decay=0.0, seed=999)
    )


@pytest.mark.parametrize("change", [
    {"d_model": 128},
    {"num_heads": 8},
    {"num_layers": 3},
    {"batch_size": 64},
    {"num_epochs": 6},          # scan trip counts shape the program
    {"hidden_sizes": (32,)},
    {"model": "mlp"},
    {"optimizer": "lamb"},      # optimizer family = chain structure
    {"compute_dtype": "bfloat16"},
])
def test_key_splits_on_shape_bearing_hparams(change):
    assert cc.program_key(BASE_CFG) != cc.program_key(dict(BASE_CFG, **change))


def test_key_splits_on_batch_shape_dtype_donation():
    k = cc.program_key(BASE_CFG, batch_shape=[(64, 8, 4)], dtype="float32",
                       donation=(0,))
    assert k != cc.program_key(BASE_CFG, batch_shape=[(32, 8, 4)],
                               dtype="float32", donation=(0,))
    assert k != cc.program_key(BASE_CFG, batch_shape=[(64, 8, 4)],
                               dtype="bfloat16", donation=(0,))
    assert k != cc.program_key(BASE_CFG, batch_shape=[(64, 8, 4)],
                               dtype="float32", donation=())


def test_key_baked_hyperparams_become_structural():
    """inject_hyperparams=False bakes lr/wd into the HLO as constants — the
    key must split what the compiler splits."""
    a = dict(BASE_CFG, inject_hyperparams=False)
    b = dict(a, learning_rate=0.5)
    assert cc.program_key(a) != cc.program_key(b)
    # seed is a traced ARGUMENT either way: never structural.
    assert cc.program_key(a) == cc.program_key(dict(a, seed=123))


GOLDEN_KEY = "pk_8c850e7eb4de69d133dee5c989b42a74"


def test_key_golden_and_stable_across_processes():
    """The key is a pure content hash: identical in this process, in a
    fresh interpreter, and against the committed golden value — hosts can
    exchange artifacts by key only because of this."""
    kwargs = dict(batch_shape=[(64, 8, 4)], dtype="float32", donation=(0, 1))
    assert cc.program_key(BASE_CFG, **kwargs) == GOLDEN_KEY
    code = (
        "import json,sys\n"
        "from distributed_machine_learning_tpu.compilecache import "
        "program_key\n"
        f"cfg = json.loads({json.dumps(json.dumps(BASE_CFG))!s})\n"
        "cfg['hidden_sizes'] = tuple(cfg['hidden_sizes'])\n"
        "print(program_key(cfg, batch_shape=[(64, 8, 4)], dtype='float32',"
        " donation=(0, 1)))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        env=dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-500:]
    assert out.stdout.strip().splitlines()[-1] == GOLDEN_KEY


def test_key_tuple_list_agnostic():
    """Configs round-tripped through JSON (lists) and live configs (tuples)
    must agree — cluster frames ship configs through pickle/json freely."""
    assert cc.program_key(BASE_CFG) == cc.program_key(
        dict(BASE_CFG, hidden_sizes=[16, 8])
    )


# ---------------------------------------------------------------------------
# compiled-PBT generation-scan keys (ISSUE 9)
# ---------------------------------------------------------------------------

_PBT_SPEC = {
    "quantile": 0.25, "resample_p": 0.25, "factors": (0.8, 1.2),
    "keys": ["learning_rate"],
    "specs": [{"key": "learning_rate", "lo": 1e-3, "hi": 1e-1, "log": True}],
    "grid_points": 1024, "sign": 1.0,
}
PBT_GOLDEN_KEY = "pk_5f43c740785e3c9878f6b7ade4a87320"


def _pbt_key(cfg=None, **over):
    kwargs = dict(interval=2, generations=4, rows=8,
                  mutation_spec=_PBT_SPEC, batch_shape=[(64, 8, 4)])
    kwargs.update(over)
    return cc.pbt_program_key(cfg or BASE_CFG, **kwargs)


def test_pbt_key_golden_and_seed_invariant():
    """The generation-scan key is a pure content hash (committed golden),
    and the PBT/trial seeds must NOT split it — seeds ride in as per-row
    PRNG key ARGUMENTS, exactly like trial seeds in the base key, so one
    compiled scan serves every seeding of the same sweep shape."""
    assert _pbt_key() == PBT_GOLDEN_KEY
    assert _pbt_key(dict(BASE_CFG, seed=999, learning_rate=3.3,
                         weight_decay=0.0)) == PBT_GOLDEN_KEY


@pytest.mark.parametrize("change", [
    {"interval": 4},                       # inner scan trip count
    {"generations": 2},                    # outer scan trip count
    {"rows": 16},                          # population size
    {"objective": "quality_latency_params"},
    {"mutation_spec": dict(_PBT_SPEC, resample_p=0.5)},
    {"mutation_spec": dict(_PBT_SPEC, specs=[
        {"key": "learning_rate", "lo": 1e-4, "hi": 1e-1, "log": True}])},
])
def test_pbt_key_splits_on_scan_identity(change):
    assert _pbt_key(**change) != PBT_GOLDEN_KEY


_PBT_SWEEP_CODE = """
import json, os
import numpy as np
from distributed_machine_learning_tpu import tune
from distributed_machine_learning_tpu.data import Dataset

rng = np.random.default_rng(7)
x = rng.normal(size=(128, 8, 4)).astype(np.float32)
w = rng.normal(size=(4,)).astype(np.float32)
y = (x.mean(axis=1) @ w)[:, None].astype(np.float32)
train, val = Dataset(x[:96], y[:96]), Dataset(x[96:], y[96:])
space = {
    "model": "mlp", "hidden_sizes": (16, 8),
    "learning_rate": tune.loguniform(1e-3, 1e-1),
    "weight_decay": 1e-6, "seed": tune.randint(0, 10_000),
    "num_epochs": 12, "batch_size": 16, "loss_function": "mse",
    "lr_schedule": "constant",
}
pbt = tune.PopulationBasedTraining(
    perturbation_interval=1,
    hyperparam_mutations={"learning_rate": tune.loguniform(1e-3, 1e-1)},
    quantile_fraction=0.25, seed=3,
)
analysis = tune.run_vectorized(
    space, train_data=train, val_data=val,
    metric="validation_mse", mode="min", num_samples=8,
    scheduler=pbt, epochs_per_dispatch=3,  # 4 chunks x 3 generations
    storage_path=os.environ["SWEEP_DIR"], seed=2, verbose=0,
)
with open(os.path.join(analysis.root, "experiment_state.json")) as f:
    print(json.dumps(json.load(f)))
"""


def test_compiled_pbt_zero_recompile_across_generations(tmp_path):
    """Acceptance (ISSUE 9 satellite): generations >> uncached backend
    compiles.  A chunked compiled-PBT sweep re-dispatches ONE generation-
    scan program — the second chunk compiles nothing new.

    Runs in a FRESH process (honest compile census, and the big scan's
    fusions must not pollute this process's XLA CPU symbol registry —
    in-process, a later ``deserialize_executable`` can fail with
    'Symbols not found' and silently cost other tests a recompile)."""
    out = subprocess.run(
        [sys.executable, "-c", _PBT_SWEEP_CODE],
        env=dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu",
                 SWEEP_DIR=str(tmp_path)),
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    state = json.loads(out.stdout.strip().splitlines()[-1])
    pbt_block = state["pbt"]
    assert pbt_block["mode"] == "compiled"
    assert pbt_block["generations"] == 12
    assert pbt_block["host_dispatches"] == 4
    compile_block = state["compile"]
    # Program count: vmapped init + ONE generation scan (reused by all 4
    # chunks) + the handful of tiny eager helpers (key creation).  The
    # decisive property: uncached compiles stay far below the generation
    # count — the scan recompiles for NO generation and NO chunk.
    assert compile_block["backend_compiles_uncached"] <= 6
    assert (compile_block["backend_compiles_uncached"]
            < pbt_block["generations"])
    # The cross-chunk program cache registered 1 miss (first build) and
    # 3 hits for the generation scan.
    assert compile_block.get("program_hits", 0) >= 3


# ---------------------------------------------------------------------------
# AOT executable cache
# ---------------------------------------------------------------------------


def test_aot_roundtrip_and_counters(tmp_path):
    import jax.numpy as jnp

    counters = cc.get_counters()
    base = counters.snapshot()
    store = cc.ExecutableCache(str(tmp_path))
    fn = lambda x: x * 2 + 1  # noqa: E731
    x = jnp.ones((4,), jnp.float32)
    f1 = store.get_or_compile("pk_t1", fn, x)
    np.testing.assert_allclose(np.asarray(f1(x)), 3.0)
    # Fresh cache instance (a "restarted process" in-process): disk import.
    store2 = cc.ExecutableCache(str(tmp_path))
    assert "pk_t1" in store2
    f2 = store2.get_or_compile("pk_t1", fn, x)
    np.testing.assert_allclose(np.asarray(f2(x)), 3.0)
    d = counters.delta_since(base)
    assert d["program_misses"] == 1
    assert d["aot_exports"] == 1
    assert d["aot_imports"] == 1
    assert d["program_hits"] == 1
    assert store2.disk_keys() == ["pk_t1"]


def test_aot_corrupt_entry_recompiles(tmp_path):
    import jax.numpy as jnp

    store = cc.ExecutableCache(str(tmp_path))
    fn = lambda x: x - 1  # noqa: E731
    x = jnp.ones((3,), jnp.float32)
    store.get_or_compile("pk_bad", fn, x)
    path = os.path.join(str(tmp_path), "pk_bad.aotexec")
    with open(path, "wb") as f:
        f.write(b"DMLAOT1\n" + b"garbage")
    fresh = cc.ExecutableCache(str(tmp_path))
    g = fresh.get_or_compile("pk_bad", fn, x)  # must not raise
    np.testing.assert_allclose(np.asarray(g(x)), 0.0)


def test_aot_donated_program_roundtrip(tmp_path):
    import jax.numpy as jnp

    store = cc.ExecutableCache(str(tmp_path))

    def step(p, g):
        return p - 0.1 * g, (g * g).sum()

    p = jnp.ones((8, 8), jnp.float32)
    f = store.get_or_compile("pk_don", step, p, p, donate_argnums=(0,))
    out, s = f(jnp.ones((8, 8), jnp.float32), jnp.ones((8, 8), jnp.float32))
    assert float(s) == 64.0
    fresh = cc.ExecutableCache(str(tmp_path))
    f2 = fresh.get_or_compile("pk_don", step, p, p, donate_argnums=(0,))
    out, s = f2(jnp.ones((8, 8), jnp.float32), jnp.ones((8, 8), jnp.float32))
    assert float(s) == 64.0


# ---------------------------------------------------------------------------
# origin primitives
# ---------------------------------------------------------------------------


def test_install_artifacts_rejects_traversal(tmp_path):
    dest = tmp_path / "cache"
    dest.mkdir()
    n = cc.install_artifacts(str(dest), {
        "ok.bin": b"fine",
        "../escape.bin": b"nope",
        "sub/dir/entry.bin": b"fine too",
    })
    assert n == 2
    assert (dest / "ok.bin").exists()
    assert (dest / "sub" / "dir" / "entry.bin").exists()
    assert not (tmp_path / "escape.bin").exists()


def test_artifact_registry_first_publish_wins():
    reg = cc.ArtifactRegistry()
    assert reg.publish("pk_a", {"f": b"1"})
    assert not reg.publish("pk_a", {"f": b"2"})  # later copies add nothing
    assert reg.fetch("pk_a") == {"f": b"1"}
    assert reg.fetch("pk_missing") is None
    snap = reg.snapshot()
    assert snap["origin_publishes"] == 1
    assert snap["origin_fetch_hits"] == 1
    assert snap["origin_fetch_misses"] == 1
    assert snap["distinct_keys"] == 1


def test_snapshot_and_pack_roundtrip(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.bin").write_bytes(b"aa")
    (src / "sub" / "b.bin").write_bytes(b"bb")
    names = cc.snapshot_cache_dir(str(src))
    assert names == {"a.bin", os.path.join("sub", "b.bin")}
    files = cc.pack_artifacts(str(src), sorted(names))
    dest = tmp_path / "dest"
    dest.mkdir()
    assert cc.install_artifacts(str(dest), files) == 2
    assert (dest / "sub" / "b.bin").read_bytes() == b"bb"


# ---------------------------------------------------------------------------
# compile-once, counter-verified (acceptance criterion 3a)
# ---------------------------------------------------------------------------

_TRIAL_DRIVER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
from distributed_machine_learning_tpu import tune
from distributed_machine_learning_tpu.data import dummy_regression_data

train, val = dummy_regression_data(num_samples=120, seq_len=8, num_features=4)
analysis = tune.run(
    tune.with_parameters(tune.train_regressor, train_data=train, val_data=val),
    {"model": "mlp", "hidden_sizes": (16,), "learning_rate": 0.01,
     "num_epochs": 2, "batch_size": 32, "lr_schedule": "constant", "seed": 5},
    metric="validation_loss", num_samples=1,
    storage_path=sys.argv[1], compile_cache_dir=sys.argv[2], verbose=0,
)
state = json.load(open(os.path.join(analysis.root, "experiment_state.json")))
print(json.dumps(state["compile"]))
"""


def test_fresh_process_with_populated_cache_compiles_nothing(tmp_path):
    """THE compile-once assertion: run the same trial config in two fresh
    processes sharing one compile-cache dir.  Process 1 compiles; process 2
    must record ZERO uncached backend compiles (every compile request is a
    persistent-cache hit) — asserted from the experiment's own ``compile``
    counter block, not eyeballed."""
    env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
    env.pop("DML_TPU_COMPILE_CACHE", None)
    cache = str(tmp_path / "xla")
    blocks = []
    for i in range(2):
        out = subprocess.run(
            [sys.executable, "-c", _TRIAL_DRIVER,
             str(tmp_path / f"results{i}"), cache],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert out.returncode == 0, out.stderr[-800:]
        blocks.append(json.loads(out.stdout.strip().splitlines()[-1]))
    cold, warm = blocks
    assert cold["backend_compiles_uncached"] > 0  # process 1 really compiled
    assert warm["backend_compiles_uncached"] == 0, warm
    assert warm["persistent_cache_hits"] > 0


# ---------------------------------------------------------------------------
# pre-warmed runner pool
# ---------------------------------------------------------------------------


def test_prewarm_pool_spawns_warm_runners(tmp_path):
    from distributed_machine_learning_tpu import tune
    from distributed_machine_learning_tpu.data import dummy_regression_data

    train, val = dummy_regression_data(
        num_samples=120, seq_len=8, num_features=4
    )
    analysis = tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        {"model": "mlp", "hidden_sizes": (16,),
         "learning_rate": tune.loguniform(1e-3, 1e-2),
         "num_epochs": 2, "batch_size": 32, "lr_schedule": "constant"},
        metric="validation_loss", num_samples=3, max_concurrent=1,
        storage_path=str(tmp_path / "results"),
        compile_cache_dir=str(tmp_path / "xla"),
        trial_executor="process", prewarm_runners=2, verbose=0,
    )
    assert analysis.num_terminated() == 3
    state = json.load(
        open(os.path.join(analysis.root, "experiment_state.json"))
    )
    comp = state["compile"]
    # Initial fill is 2 and the pool replenishes on take: every trial of
    # this serialized sweep starts on a pre-warmed runner.
    assert comp.get("prewarmed_spawns", 0) >= 2, comp
    assert comp.get("cold_spawns", 0) <= 1, comp


def test_child_precompile_frame(tmp_path):
    """Protocol-level check of think-time precompile: a warm child answers
    a precompile frame with ("prewarmed", key, n) and still runs a normal
    trial afterwards."""
    import cloudpickle

    from distributed_machine_learning_tpu.tune import _process_child as pc

    def trainable(config):
        import jax
        import jax.numpy as jnp

        from distributed_machine_learning_tpu.tune import session

        y = float(jax.jit(lambda v: (v * config["learning_rate"]).sum())(
            jnp.ones((4,))
        ))
        session.report({"loss": y})

    env = dict(os.environ, PYTHONPATH=REPO_ROOT, JAX_PLATFORMS="cpu")
    env[pc.PREWARM_ENV] = "1"
    env["DML_TPU_COMPILE_CACHE"] = str(tmp_path / "xla")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_machine_learning_tpu.tune._process_child"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
        cwd=REPO_ROOT,
    )
    try:
        blob = cloudpickle.dumps(trainable)
        pc.write_frame(proc.stdin, ("precompile", {
            "key": "pk_unit", "trainable": blob,
            "config": {"learning_rate": 2.0}, "sys_path": [REPO_ROOT],
        }))
        assert pc.read_frame(proc.stdout) == ("warm",)
        kind, key, compiles = pc.read_frame(proc.stdout)
        assert (kind, key) == ("prewarmed", "pk_unit")
        # Now the real trial on the same (already hot) child.
        pc.write_frame(proc.stdin, {
            "trial_id": "t0", "config": {"learning_rate": 2.0},
            "trainable": blob, "restore": None, "sys_path": [REPO_ROOT],
        })
        kind, metrics, ckpt = pc.read_frame(proc.stdout)
        assert kind == "result" and metrics["loss"] == 8.0
        pc.write_frame(proc.stdin, ("decision", "stop"))
        assert pc.read_frame(proc.stdout)[0] == "complete"
    finally:
        proc.stdin.close()
        proc.wait(timeout=30)
