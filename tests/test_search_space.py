"""Search-space DSL unit tests (SURVEY.md §4: what the reference lacked)."""

import numpy as np
import pytest

from distributed_machine_learning_tpu import tune
from distributed_machine_learning_tpu.tune.search_space import SearchSpace
from distributed_machine_learning_tpu.utils.seeding import rng_from


def test_domains_sample_within_bounds():
    rng = rng_from("t", 0)
    for _ in range(100):
        assert tune.choice([1, 2, 3]).sample(rng) in (1, 2, 3)
        assert 0.0 <= tune.uniform(0.0, 1.0).sample(rng) <= 1.0
        v = tune.loguniform(1e-5, 1e-1).sample(rng)
        assert 1e-5 <= v <= 1e-1
        assert tune.randint(2, 8).sample(rng) in range(2, 8)
        q = tune.quniform(0.0, 1.0, 0.25).sample(rng)
        assert q in (0.0, 0.25, 0.5, 0.75, 1.0)


def test_sampling_is_deterministic_per_seed():
    space = SearchSpace({
        "a": tune.choice(["x", "y", "z"]),
        "b": tune.loguniform(1e-4, 1e-1),
        "c": 42,
    })
    c1 = space.sample(("seed", 7, 3))
    c2 = space.sample(("seed", 7, 3))
    c3 = space.sample(("seed", 7, 4))
    assert c1 == c2
    assert c1 != c3
    assert c1["c"] == 42  # literals pass through


def test_sample_from_conditional_resolution():
    # The reference's intended dim_feedforward = d_model * choice(2,3,4)
    # (its version returned a sampler object — SURVEY.md §2 C19).
    space = SearchSpace({
        "d_model": tune.choice([64, 128]),
        "dim_feedforward": tune.sample_from(
            lambda cfg: cfg["d_model"] * tune.choice([2, 3, 4])
        ),
    })
    for i in range(20):
        cfg = space.sample(("s", i))
        assert cfg["dim_feedforward"] in {
            cfg["d_model"] * k for k in (2, 3, 4)
        }
        assert isinstance(cfg["dim_feedforward"], int)


def test_sample_from_chained_dependencies_any_order():
    space = SearchSpace({
        "c": tune.sample_from(lambda cfg: cfg["b"] + 1),
        "b": tune.sample_from(lambda cfg: cfg["a"] * 2),
        "a": tune.choice([1, 2]),
    })
    cfg = space.sample(("s", 0))
    assert cfg["b"] == cfg["a"] * 2
    assert cfg["c"] == cfg["b"] + 1


def test_sample_from_cycle_raises():
    space = SearchSpace({
        "a": tune.sample_from(lambda cfg: cfg["b"]),
        "b": tune.sample_from(lambda cfg: cfg["a"]),
    })
    with pytest.raises(RuntimeError, match="Cyclic"):
        space.sample(("s", 0))


def test_constraints_reject_invalid_joint_samples():
    space = SearchSpace(
        {
            "d_model": tune.choice([60, 64, 100, 128]),
            "num_heads": tune.choice([3, 4, 8]),
        },
        constraints=[
            tune.Constraint(
                lambda c: c["d_model"] % c["num_heads"] == 0,
                "d_model divisible by num_heads",
            )
        ],
    )
    for i in range(50):
        cfg = space.sample(("s", i))
        assert cfg["d_model"] % cfg["num_heads"] == 0


def test_continuous_keys_and_unit_mapping():
    space = SearchSpace({
        "lr": tune.loguniform(1e-5, 1e-1),
        "wd": tune.uniform(0.0, 0.1),
        "opt": tune.choice(["adam", "sgd"]),
    })
    assert set(space.continuous_keys()) == {"lr", "wd"}
    dom = space.domain("lr")
    for v in (1e-5, 1e-3, 1e-1):
        assert np.isclose(dom.from_unit(dom.to_unit(v)), v, rtol=1e-6)


def test_nested_sample_from_defers_cleanly():
    # Regression: a nested SampleFrom referencing a not-yet-resolved key must
    # defer to the next fixpoint pass, not leak the internal exception.
    space = SearchSpace({
        "a": tune.sample_from(
            lambda c: tune.sample_from(lambda c2: c2["b"] * 2)),
        "b": tune.sample_from(lambda c: 5),
    })
    cfg = space.sample(("s", 0))
    assert cfg["a"] == 10 and cfg["b"] == 5


def test_grid_search_skips_infeasible_points():
    from distributed_machine_learning_tpu.tune.search import GridSearch

    space = SearchSpace(
        {"d_model": tune.choice([64, 100]), "num_heads": tune.choice([4, 8])},
        constraints=[tune.Constraint(lambda c: c["d_model"] % c["num_heads"] == 0)],
    )
    gs = GridSearch()
    gs.set_search_space(space, seed=0)
    configs = []
    i = 0
    while (cfg := gs.suggest(i)) is not None:
        configs.append(cfg)
        i += 1
    # (100, 8) is infeasible and must be skipped, not crash.
    assert len(configs) == 3
    assert all(c["d_model"] % c["num_heads"] == 0 for c in configs)


def test_gridsearch_fast_forward_resumes_cursor():
    """Experiment resume advances GridSearch past the already-proposed
    prefix instead of re-proposing it (suggest-side cursor state)."""
    from distributed_machine_learning_tpu import tune
    from distributed_machine_learning_tpu.tune.search.base import GridSearch
    from distributed_machine_learning_tpu.tune.search_space import SearchSpace

    space = SearchSpace({"a": tune.choice([1, 2, 3]), "b": tune.choice([10, 20])})

    fresh = GridSearch()
    fresh.set_search_space(space, seed=0)
    all_points = [fresh.suggest(i) for i in range(6)]
    assert fresh.suggest(6) is None  # exhausted after 3*2 points

    resumed = GridSearch()
    resumed.set_search_space(space, seed=0)
    resumed.fast_forward(4)  # 4 trials restored from the prior run
    tail = [resumed.suggest(i) for i in (4, 5)]
    assert [(p["a"], p["b"]) for p in tail] == [
        (p["a"], p["b"]) for p in all_points[4:]
    ]
    assert resumed.suggest(6) is None


def test_warm_start_points_run_first():
    """points_to_evaluate: exact values honored, partial keys sampled,
    then the inner searcher takes over with an unshifted sequence."""
    from distributed_machine_learning_tpu import tune
    from distributed_machine_learning_tpu.tune.search.base import (
        RandomSearch,
        WarmStartSearcher,
    )
    from distributed_machine_learning_tpu.tune.search_space import SearchSpace

    mk_space = lambda: SearchSpace({
        "lr": tune.loguniform(1e-4, 1e-1),
        "depth": tune.choice([2, 4, 8]),
    })
    points = [{"lr": 3e-3, "depth": 4}, {"depth": 8}]  # second is partial

    ws = WarmStartSearcher(RandomSearch(), points)
    ws.set_search_space(mk_space(), seed=7)
    c0, c1 = ws.suggest(0), ws.suggest(1)
    assert c0["lr"] == 3e-3 and c0["depth"] == 4
    assert c1["depth"] == 8 and 1e-4 <= c1["lr"] <= 1e-1  # lr sampled

    # The wrapped searcher's own proposals are the SAME sequence a plain
    # RandomSearch would produce — warm points shift, not perturb, it.
    plain = RandomSearch()
    plain.set_search_space(mk_space(), seed=7)
    for i in (2, 3, 4):
        assert ws.suggest(i) == plain.suggest(i - len(points))


def test_warm_start_respects_constraints():
    from distributed_machine_learning_tpu import tune
    from distributed_machine_learning_tpu.tune.search.base import (
        RandomSearch,
        WarmStartSearcher,
    )
    from distributed_machine_learning_tpu.tune.search_space import (
        Constraint,
        SearchSpace,
    )
    import pytest as _pytest

    space = SearchSpace(
        {"d_model": tune.choice([64, 100]), "num_heads": tune.choice([4, 8])},
        [Constraint(lambda c: c["d_model"] % c["num_heads"] == 0)],
    )
    ws = WarmStartSearcher(RandomSearch(), [{"d_model": 100, "num_heads": 8}])
    ws.set_search_space(space, seed=0)
    with _pytest.raises(RuntimeError):
        ws.suggest(0)  # infeasible point must fail loudly, not run silently


def test_warm_start_fast_forward_shifts_inner():
    """Resume: the inner GridSearch cursor advances by resumed-trials minus
    warm points, so the tail continues exactly where the prior run stopped."""
    from distributed_machine_learning_tpu import tune
    from distributed_machine_learning_tpu.tune.search.base import (
        GridSearch,
        WarmStartSearcher,
    )
    from distributed_machine_learning_tpu.tune.search_space import SearchSpace

    mk = lambda: SearchSpace(
        {"a": tune.choice([1, 2, 3]), "b": tune.choice([10, 20])}
    )
    points = [{"a": 2, "b": 20}]

    fresh = WarmStartSearcher(GridSearch(), points)
    fresh.set_search_space(mk(), seed=0)
    full = [fresh.suggest(i) for i in range(7)]
    assert fresh.suggest(7) is None  # 1 point + 6 grid cells

    resumed = WarmStartSearcher(GridSearch(), points)
    resumed.set_search_space(mk(), seed=0)
    resumed.fast_forward(4)  # 4 trials existed: the point + 3 grid cells
    tail = [resumed.suggest(i) for i in (4, 5, 6)]
    assert [(p["a"], p["b"]) for p in tail] == [
        (p["a"], p["b"]) for p in full[4:]
    ]


def test_points_to_evaluate_through_tune_run(tmp_path):
    from distributed_machine_learning_tpu import tune
    from distributed_machine_learning_tpu.data import dummy_regression_data

    train, val = dummy_regression_data(
        num_samples=128, seq_len=8, num_features=4
    )
    known_good = {"learning_rate": 5e-3, "hidden_sizes": (16,)}
    analysis = tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        {"model": "mlp", "hidden_sizes": tune.choice([(8,), (16,)]),
         "learning_rate": tune.loguniform(1e-4, 1e-1),
         "num_epochs": 1, "batch_size": 32},
        metric="validation_loss",
        num_samples=3,
        points_to_evaluate=[known_good],
        storage_path=str(tmp_path),
        name="warm",
        verbose=0,
    )
    first = analysis.trials[0].config
    assert first["learning_rate"] == 5e-3
    assert tuple(first["hidden_sizes"]) == (16,)
    assert analysis.num_terminated() == 3


def test_hpo_full_space_samples_are_valid():
    """The flagship example's 20+-hp space: every sample satisfies its own
    constraints, num_kv_heads always divides num_heads (GQA validity), and
    dim_feedforward resolves to d_model * ff_multiplier (the reference's
    `:383` sample_from bug, fixed semantics)."""
    import argparse
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "hpo_full",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "examples", "hpo_full.py"),
    )
    hpo_full = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(hpo_full)

    args = argparse.Namespace(fast=False, num_epochs=20)
    space = hpo_full.build_search_space(args)
    for i in range(100):
        cfg = space.sample(["hpo_full_validity", i])
        assert cfg["d_model"] % cfg["num_heads"] == 0
        assert cfg["num_heads"] % cfg["num_kv_heads"] == 0
        assert cfg["dim_feedforward"] == cfg["d_model"] * cfg["ff_multiplier"]
        assert cfg["position_encoding"] in ("sincos", "rope")


def test_extended_domain_menu():
    """Ray-parity domains beyond the reference's usage: qloguniform, randn,
    qrandint (INCLUSIVE high, Ray's convention), lograndint."""
    import numpy as np

    rng = np.random.default_rng(3)
    for _ in range(200):
        v = tune.qloguniform(1e-4, 1e-1, 1e-4).sample(rng)
        assert 1e-4 <= v <= 1e-1
        assert abs(v / 1e-4 - round(v / 1e-4)) < 1e-6  # quantized
        q = tune.qrandint(8, 64, 8).sample(rng)
        assert 8 <= q <= 64 and q % 8 == 0 and isinstance(q, int)
        li = tune.lograndint(1, 100).sample(rng)
        assert 1 <= li <= 99 and isinstance(li, int)
    draws = [tune.randn(5.0, 2.0).sample(rng) for _ in range(500)]
    assert abs(np.mean(draws) - 5.0) < 0.3
    assert abs(np.std(draws) - 2.0) < 0.3
    # log-spread: lograndint mass concentrates at small values
    lis = [tune.lograndint(1, 1000).sample(rng) for _ in range(500)]
    assert np.median(lis) < 100


def test_quantized_domains_unaligned_bounds_and_degenerate_ranges():
    """Review findings: quantized domains always emit multiples of q even
    at unaligned bounds; impossible quantized ranges and degenerate
    lograndint ranges raise at construction."""
    import numpy as np
    import pytest

    rng = np.random.default_rng(5)
    for _ in range(300):
        v = tune.qrandint(8, 60, 8).sample(rng)   # 60 is not a multiple
        assert v % 8 == 0 and 8 <= v <= 56
        u = tune.quniform(0.15, 1.0, 0.1).sample(rng)
        assert abs(u / 0.1 - round(u / 0.1)) < 1e-9 and 0.2 <= u <= 1.0
        w = tune.qloguniform(3e-4, 1e-1, 1e-3).sample(rng)
        assert abs(w / 1e-3 - round(w / 1e-3)) < 1e-9 and 1e-3 <= w <= 0.1
    with pytest.raises(ValueError):
        tune.qrandint(9, 15, 8)     # no multiple of 8 in [9, 15]
    with pytest.raises(ValueError):
        tune.lograndint(5, 5)       # degenerate, like randint(5, 5)


def test_pbt_lograndint_clamp_respects_exclusive_high():
    from distributed_machine_learning_tpu import tune as t

    s = t.PopulationBasedTraining(
        metric="loss", mode="min", perturbation_interval=1,
        hyperparam_mutations={"units": t.lograndint(16, 256)},
        resample_probability=0.0,
    )
    import numpy as np

    rng = np.random.default_rng(3)
    for _ in range(30):
        new = s._mutate({"units": 240}, rng)
        assert 16 <= new["units"] <= 255 and isinstance(new["units"], int)


def test_qloguniform_tiny_low_never_emits_zero_and_pbt_snaps_to_grid():
    """Review findings: a tiny positive low under a larger q maps to the
    first positive multiple (never 0.0); PBT explores stay on the q grid."""
    import numpy as np

    rng = np.random.default_rng(6)
    dom = tune.qloguniform(1e-12, 1e-1, 1e-3)
    vals = [dom.sample(rng) for _ in range(300)]
    assert min(vals) >= 1e-3  # log-mass at tiny v snaps UP, not to 0

    s = tune.PopulationBasedTraining(
        metric="loss", mode="min", perturbation_interval=1,
        hyperparam_mutations={"bs": tune.qrandint(8, 60, 8)},
        resample_probability=0.0,
    )
    for _ in range(40):
        new = s._mutate({"bs": 56}, rng)
        assert new["bs"] % 8 == 0 and 8 <= new["bs"] <= 56
        assert isinstance(new["bs"], int)
