"""CI guard: every module imports under JAX_PLATFORMS=cpu, imports trigger
ZERO jit compilation, and the checkpoint path stays pickle-free.

Invariants the ckpt/ and compilecache/ subsystems depend on:

* **importability** — every module under ``distributed_machine_learning_tpu``
  must import on the CPU test platform (conftest pins
  ``JAX_PLATFORMS=cpu``).  A module that only imports where a TPU is
  attached would make the recovery paths (which import lazily during
  incident handling) fail exactly when they are needed.
* **no jit work at import** — import-time tracing/compilation is hidden
  startup cost that EVERY process pays before doing any work (trial
  children, serve replicas, bench children, cluster workers), exactly the
  latency the compile-artifact layer exists to kill.  The import sweep
  runs under a compile-counter hook (``compilecache.get_tracker``) and any
  trace or backend-compile event it records is a failure naming the cost.
* **no pickle in the checkpoint path** — the on-disk formats (msgpack
  blob, sharded chunk+JSON generations, serve bundles) must stay process-
  and framework-portable: a checkpoint written by one Python version/
  process must restore in any other, which pickle does not guarantee (and
  unpickling untrusted shared-storage bytes executes code).  ``pickle``
  is allowed in the process-executor IPC frames (same-host, same-build
  pipe) but never in anything that writes or reads checkpoint bytes.
"""

import glob
import importlib
import os
import pkgutil

import distributed_machine_learning_tpu as pkg

PKG_ROOT = os.path.dirname(pkg.__file__)


def _iter_module_names():
    for mod in pkgutil.walk_packages(pkg.__path__, prefix=pkg.__name__ + "."):
        yield mod.name


def test_every_module_imports_on_cpu():
    assert os.environ.get("JAX_PLATFORMS") == "cpu"  # conftest pinned it
    # Compile-counter hook BEFORE the sweep: any jit tracing or backend
    # compilation triggered by an import is hidden startup cost — the
    # event deltas across the sweep must be zero.
    from distributed_machine_learning_tpu.compilecache import get_tracker

    tracker = get_tracker()
    traces_before = tracker.total_traces()
    compiles_before = tracker.total_backend_compiles()
    failures = []
    names = sorted(_iter_module_names())
    assert len(names) > 40  # the walk really covered the package
    assert f"{pkg.__name__}.ckpt.format" in names
    assert f"{pkg.__name__}.compilecache.aot" in names
    for name in names:
        try:
            importlib.import_module(name)
        except Exception as exc:  # noqa: BLE001 - collect, report all
            failures.append(f"{name}: {exc!r}")
    assert not failures, "\n".join(failures)
    traced = tracker.total_traces() - traces_before
    compiled = tracker.total_backend_compiles() - compiles_before
    assert traced == 0 and compiled == 0, (
        f"importing the package traced {traced} program(s) and compiled "
        f"{compiled} — import-time jit work is startup cost every process "
        f"pays; move it behind a function"
    )


def test_checkpoint_path_is_pickle_free():
    """One implementation, one allowlist: the ``pickle-checkpoint`` dmlint
    rule (analysis/rules.py) owns both the detection (AST, not regex) and
    the list of checkpoint-path modules; this test just points it at the
    package.  ``dml-tpu lint`` enforces the same rule outside pytest."""
    from distributed_machine_learning_tpu import analysis

    # Guard-list staleness: every allowlist pattern must still match at
    # least one real file (a renamed module must not silently fall out of
    # the pickle scope).
    for pat in analysis.CHECKPOINT_PATH_PATTERNS:
        root = os.path.join(PKG_ROOT, pat)
        hits = glob.glob(root) or glob.glob(root.rstrip("/") + "/*.py")
        assert hits, f"pickle allowlist is stale: {pat} matches nothing"

    rule = analysis.get_rule("pickle-checkpoint")
    result = analysis.lint_paths(
        [PKG_ROOT], rules=[rule], baseline_path=analysis.DEFAULT_BASELINE
    )
    assert result.files_checked > 40
    offenders = [f.format() for f in result.unsuppressed()]
    assert not offenders, (
        "pickle crept into the checkpoint path (the format must stay "
        "process/framework-portable):\n" + "\n".join(offenders)
    )


def test_sharded_format_writes_no_pickle_bytes(tmp_path):
    """Belt and braces beyond source scanning: no file of a written
    generation starts with a pickle protocol-2+ opcode stream."""
    import numpy as np

    from distributed_machine_learning_tpu.ckpt import format as fmt

    gen = str(tmp_path / "gen_000001")
    fmt.save_sharded(gen, {"w": np.ones((3, 2), np.float32), "meta": "x"})
    for name in os.listdir(gen):
        with open(os.path.join(gen, name), "rb") as f:
            head = f.read(2)
        assert head[:2] != b"\x80\x04" and head[:2] != b"\x80\x02", name
