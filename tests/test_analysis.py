"""Tier-1 gate for the analysis/ package (ISSUE 6).

Three layers of enforcement:

* **the lint gate** — dmlint over the whole installed package must report
  ZERO unsuppressed findings (and the checked-in baseline must be empty:
  grandfathering is a burn-down device, not a parking lot);
* **rule fidelity** — every rule fires on its historical bug pattern
  (``tests/analysis_fixtures/bad_*.py``, golden ``# EXPECT: <rule>``
  markers matched on rule AND line) and stays silent on the idiomatic
  twin (``clean_*.py``, zero findings under ALL rules);
* **lock order** — the runtime recorder (enabled suite-wide by conftest's
  ``DML_LOCK_ORDER=1``) sees a deliberately inverted acquisition as a
  cycle, and the union graph across the instrumented
  executor/cluster/serve/ckpt/dispatch locks stays acyclic.
"""

import ast
import collections
import os
import re
import threading

import pytest

import distributed_machine_learning_tpu as pkg
from distributed_machine_learning_tpu import analysis
from distributed_machine_learning_tpu.analysis import locks as locks_lib
from distributed_machine_learning_tpu.analysis.engine import load_context

PKG_ROOT = os.path.dirname(os.path.abspath(pkg.__file__))
FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")
RULE_NAMES = [r.name for r in analysis.ALL_RULES]

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([a-z\-,\s]+?)\s*$")


# --------------------------------------------------------------------------
# the gate
# --------------------------------------------------------------------------


def test_package_has_zero_unsuppressed_findings():
    result = analysis.lint_paths([PKG_ROOT])
    assert result.files_checked > 40  # the walk really covered the package
    assert not result.errors, result.errors
    live = result.unsuppressed()
    assert not live, "unsuppressed dmlint finding(s):\n" + "\n".join(
        f.format() for f in live
    )


def test_baseline_is_empty():
    """Satellite goal state: nothing grandfathered.  A PR that wants to
    baseline a new finding must consciously argue with this test —
    inline `# dmlint: disable=<rule> <reason>` is the sanctioned escape
    hatch for intentional exceptions."""
    from distributed_machine_learning_tpu.analysis.findings import (
        load_baseline,
    )

    entries = load_baseline(analysis.DEFAULT_BASELINE)
    assert entries == [], (
        f"baseline should be empty; fix or inline-suppress: {entries}"
    )


def test_lint_cli_exits_nonzero_on_findings(capsys):
    from distributed_machine_learning_tpu.__main__ import main

    bad = os.path.join(FIXTURES, "bad_wallclock_deadline.py")
    with pytest.raises(SystemExit) as exc:
        main(["lint", bad, "--baseline", "none"])
    assert exc.value.code == 1
    out = capsys.readouterr().out
    assert "wallclock-deadline" in out and "DML004" in out
    with pytest.raises(SystemExit) as exc:
        main(["lint", os.path.join(FIXTURES, "clean_wallclock_deadline.py"),
              "--baseline", "none"])
    assert exc.value.code == 0


# --------------------------------------------------------------------------
# rule fidelity: bad fixture fires exactly as marked; clean twin is silent
# --------------------------------------------------------------------------


def _expected_markers(path):
    """Multiset of (line, rule) from # EXPECT: comments."""
    expected = collections.Counter()
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            m = _EXPECT_RE.search(line)
            if m:
                for rule in m.group(1).split(","):
                    expected[(lineno, rule.strip())] += 1
    return expected


@pytest.mark.parametrize("rule_name", RULE_NAMES)
def test_rule_fires_on_historical_bug_pattern(rule_name):
    path = os.path.join(FIXTURES, f"bad_{rule_name.replace('-', '_')}.py")
    assert os.path.exists(path), f"missing fixture for {rule_name}"
    expected = _expected_markers(path)
    assert expected, f"{path} has no EXPECT markers"
    assert {r for _, r in expected} == {rule_name}, (
        "a bad fixture exercises exactly its own rule"
    )
    result = analysis.lint_paths([path], baseline_path=None)
    got = collections.Counter((f.line, f.rule) for f in result.findings)
    assert got == expected, (
        f"{rule_name}: expected {dict(expected)}, got {dict(got)}\n"
        + "\n".join(f.format() for f in result.findings)
    )


@pytest.mark.parametrize("rule_name", RULE_NAMES)
def test_rule_is_silent_on_idiomatic_twin(rule_name):
    path = os.path.join(FIXTURES, f"clean_{rule_name.replace('-', '_')}.py")
    assert os.path.exists(path), f"missing clean twin for {rule_name}"
    result = analysis.lint_paths([path], baseline_path=None)
    assert not result.findings, (
        f"false positive(s) on the idiomatic form:\n"
        + "\n".join(f.format() for f in result.findings)
    )


# --------------------------------------------------------------------------
# suppression + baseline mechanics
# --------------------------------------------------------------------------


def _lint_source(tmp_path, source, baseline_path=None):
    p = tmp_path / "case.py"
    p.write_text(source)
    return analysis.lint_paths([str(p)], baseline_path=baseline_path)


def test_inline_suppression_same_line(tmp_path):
    src = (
        "import time\n"
        "deadline = time.time() + 5  "
        "# dmlint: disable=wallclock-deadline test-only clock\n"
    )
    result = _lint_source(tmp_path, src)
    assert len(result.findings) == 1
    assert result.findings[0].suppressed
    assert not result.unsuppressed()


def test_inline_suppression_directive_line_above(tmp_path):
    src = (
        "import time\n"
        "# dmlint: disable=wallclock-deadline reason: fixture\n"
        "deadline = time.time() + 5\n"
    )
    result = _lint_source(tmp_path, src)
    assert result.findings and result.findings[0].suppressed


def test_suppression_for_other_rule_does_not_apply(tmp_path):
    src = (
        "import time\n"
        "deadline = time.time() + 5  # dmlint: disable=import-trace nope\n"
    )
    result = _lint_source(tmp_path, src)
    assert result.unsuppressed(), "wrong-rule suppression must not silence"


def test_baseline_roundtrip_absorbs_then_burns_down(tmp_path):
    src = "import time\ndeadline = time.time() + 5\n"
    p = tmp_path / "case.py"
    p.write_text(src)
    base = tmp_path / "baseline.json"
    first = analysis.lint_paths([str(p)], baseline_path=None)
    assert len(first.unsuppressed()) == 1
    analysis.save_baseline(str(base), first.unsuppressed())
    second = analysis.lint_paths([str(p)], baseline_path=str(base))
    assert not second.unsuppressed()
    assert second.findings[0].baselined
    # the fix lands: baseline entry goes stale harmlessly, nothing fires
    p.write_text("import time\ndeadline = time.monotonic() + 5\n")
    third = analysis.lint_paths([str(p)], baseline_path=str(base))
    assert not third.findings


def test_scope_marker_opts_file_into_scoped_rules(tmp_path):
    src = "# dmlint-scope: checkpoint-path\nimport pickle\n"
    result = _lint_source(tmp_path, src)
    assert any(f.rule == "pickle-checkpoint" for f in result.findings)
    # without the marker, an arbitrary file is out of the pickle scope
    result = _lint_source(tmp_path, "import pickle\n")
    assert not result.findings


# --------------------------------------------------------------------------
# lock-order recorder
# --------------------------------------------------------------------------


def test_inverted_acquisition_is_detected_as_cycle():
    """The acceptance fixture: two locks taken a->b on one code path and
    b->a on another (fresh recorder: the deliberate inversion must not
    poison the suite-wide graph)."""
    locks_lib.enable()  # conftest sets the env; make the invariant local
    rec = locks_lib.LockOrderRecorder()
    a = locks_lib.NamedLock("fix.a", recorder=rec)
    b = locks_lib.NamedLock("fix.b", recorder=rec)
    with a:
        with b:
            pass
    rec.assert_acyclic()  # one direction alone is fine

    def inverted():
        with b:
            with a:
                pass

    t = threading.Thread(target=inverted)
    t.start()
    t.join()
    with pytest.raises(locks_lib.LockOrderViolation) as exc:
        rec.assert_acyclic()
    msg = str(exc.value)
    assert "fix.a" in msg and "fix.b" in msg and "->" in msg
    assert rec.cycles()


def test_same_role_nesting_is_tracked_not_a_cycle():
    rec = locks_lib.LockOrderRecorder()
    outer = locks_lib.NamedLock("fix.role", recorder=rec)
    inner = locks_lib.NamedLock("fix.role", recorder=rec)
    with outer:
        with inner:
            pass
    assert rec.cycles() == []
    assert rec.self_edges.get("fix.role") == 1


def test_named_lock_backs_a_condition():
    lock = locks_lib.named_lock("fix.cond")
    cond = threading.Condition(lock)
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5.0)
            hits.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    # Let the waiter reach wait(); notify under the lock.
    import time

    deadline = time.monotonic() + 5.0
    while not hits and time.monotonic() < deadline:
        with cond:
            cond.notify_all()
        time.sleep(0.01)
    t.join(timeout=5.0)
    assert hits == [1]


def test_instrumented_subsystems_record_and_stay_acyclic(tmp_path):
    """Drive a small workload through each instrumented subsystem, then
    assert (a) the recorder saw their lock roles and (b) the union
    acquisition graph — including everything earlier tests recorded — has
    no cycle.  This is the tier-1 'acyclic across executor/cluster/serve/
    ckpt' acceptance; the rest of the suite keeps feeding the same global
    recorder."""
    import numpy as np

    assert locks_lib.enabled(), "conftest must enable DML_LOCK_ORDER"
    rec = locks_lib.get_recorder()

    # ckpt: async writer + metrics
    from distributed_machine_learning_tpu.ckpt.writer import AsyncCheckpointer

    w = AsyncCheckpointer(log=lambda msg: None)
    w.save(str(tmp_path / "ck.msgpack"), {"w": np.ones((2, 2), np.float32)})
    assert w.wait_until_finished(timeout=30)
    w.close()

    # serve: micro-batcher (Condition over a NamedLock) + circuit breaker
    from distributed_machine_learning_tpu.serve.batcher import MicroBatcher
    from distributed_machine_learning_tpu.serve.replica import CircuitBreaker

    mb = MicroBatcher(lambda x: x * 2, max_batch_size=4, max_latency_ms=1.0)
    fut = mb.submit(np.ones((1, 3), np.float32))
    assert fut.result(timeout=10) is not None
    mb.stop()
    br = CircuitBreaker(failure_threshold=1, recovery_s=60.0)
    assert br.allow()
    br.record_failure()
    assert not br.allow()

    # chaos: a seeded plan decision
    from distributed_machine_learning_tpu import chaos

    plan = chaos.FaultPlan(seed=3, write_error_rate=1.0)
    with pytest.raises(IOError):
        plan.on_storage_op("write", "exp/trial/checkpoint_1")
    assert plan.snapshot()["storage_write_errors"] == 1

    # tune: the in-memory storage backend's shared-namespace lock
    from distributed_machine_learning_tpu.tune.storage import MemoryStorage

    mem = MemoryStorage()
    mem.write_bytes("mem://fix/blob", b"bytes")
    assert mem.read_bytes("mem://fix/blob") == b"bytes"

    # dispatch + cluster + executor-side liveness primitives
    from distributed_machine_learning_tpu.utils import dispatch
    from distributed_machine_learning_tpu.tune import cluster
    from distributed_machine_learning_tpu import liveness

    with dispatch._LOCK:
        pass
    with cluster._SEEN_KEYS_LOCK:
        pass
    dog = liveness.DispatchWatchdog(1.0)
    dog.track("k")
    dog.beat("k")
    dog.expired()

    seen = rec.roles_seen
    for role in (
        "ckpt.writer", "ckpt.metrics", "serve.batcher.queue",
        "serve.batcher.stats", "serve.breaker", "chaos.plan", "dispatch",
        "cluster.seen_keys", "liveness.watchdog", "liveness.heartbeat",
        "tune.storage.mem",
    ):
        assert role in seen, f"lock role {role!r} never recorded"
    rec.assert_acyclic()
    # Same-role nesting would be an instance-order hazard the role graph
    # cannot see — the instrumented roles must not develop one silently.
    assert not any(
        rec.self_edges.get(r) for r in seen if not r.startswith("fix.")
    ), rec.self_edges


def test_recorder_snapshot_shape():
    rec = locks_lib.LockOrderRecorder()
    a = locks_lib.NamedLock("s.a", recorder=rec)
    b = locks_lib.NamedLock("s.b", recorder=rec)
    with a:
        with b:
            pass
    snap = rec.snapshot()
    assert snap["edges"] == ["s.a -> s.b"]
    assert set(snap["roles"]) == {"s.a", "s.b"}
    assert snap["cycles"] == []


# --------------------------------------------------------------------------
# cross-file rules (dmlint v2): the project context end to end
# --------------------------------------------------------------------------


def test_dml012_caught_across_a_file_boundary(tmp_path):
    """The acceptance case: the CALLER (one file) passes, the CALLEE
    (another file) donates — only the project call graph connects them."""
    (tmp_path / "callee.py").write_text(
        "import jax\n\n\n"
        "def donate_state(params, opt_state, key):\n"
        "    step = jax.jit(lambda p, o, k: (p, o), "
        "donate_argnums=(0, 1))\n"
        "    return step(params, opt_state, key)\n"
    )
    (tmp_path / "caller.py").write_text(
        "from callee import donate_state\n\n\n"
        "def run(params, opt_state, key):\n"
        "    new_p, new_o = donate_state(params, opt_state, key)\n"
        "    return float(params.mean())\n"
    )
    result = analysis.lint_paths([str(tmp_path)], baseline_path=None)
    hits = [f for f in result.findings if f.rule_id == "DML012"]
    assert len(hits) == 1
    assert hits[0].file.endswith("caller.py") and hits[0].line == 6
    assert "donate_state" in hits[0].message
    # the clean twin of the same shape: rebinding over the donated names
    (tmp_path / "caller.py").write_text(
        "from callee import donate_state\n\n\n"
        "def run(params, opt_state, key):\n"
        "    params, opt_state = donate_state(params, opt_state, key)\n"
        "    return float(params.mean())\n"
    )
    result = analysis.lint_paths([str(tmp_path)], baseline_path=None)
    assert not [f for f in result.findings if f.rule_id == "DML012"]


def test_dml013_skips_sites_dml003_already_owns(tmp_path):
    """One owner per site: a nondeterministic call INSIDE a chaos-scoped
    file is DML003's; DML013 reports only what the call graph reaches
    outside."""
    (tmp_path / "chaos.py").write_text(
        "import helpers\n\n\n"
        "class FaultPlan:\n"
        "    def on_storage_op(self, op, path):\n"
        "        return helpers.decide(op)\n"
    )
    (tmp_path / "helpers.py").write_text(
        "import time\n\n\n"
        "def decide(op):\n"
        "    return time.time() % 1.0 < 0.5\n"
    )
    result = analysis.lint_paths([str(tmp_path)], baseline_path=None)
    by_rule = collections.Counter(f.rule_id for f in result.findings)
    assert by_rule["DML013"] == 1
    hit = next(f for f in result.findings if f.rule_id == "DML013")
    assert hit.file.endswith("helpers.py")
    assert "FaultPlan.on_storage_op" in hit.message
    # the same call INSIDE chaos.py: DML003 fires there, DML013 must not
    (tmp_path / "chaos.py").write_text(
        "import time\n\n\n"
        "class FaultPlan:\n"
        "    def on_storage_op(self, op, path):\n"
        "        return time.time() % 1.0 < 0.5\n"
    )
    result = analysis.lint_paths([str(tmp_path)], baseline_path=None)
    chaos_hits = [
        f for f in result.findings if f.file.endswith("chaos.py")
    ]
    assert {f.rule_id for f in chaos_hits} == {"DML003"}


def test_dml014_lock_creator_method_is_construction_phase(tmp_path):
    """A second-phase constructor (handshake/open) that CREATES the
    guard lock may initialize the attributes it guards — nothing else
    can hold a lock that does not exist yet."""
    src = (
        "from distributed_machine_learning_tpu.analysis.locks import "
        "named_lock\n\n\n"
        "class Conn:\n"
        "    def open(self):\n"
        "        self._lock = named_lock('fix.conn')\n"
        "        self.buffer = []\n\n"
        "    def push(self, item):\n"
        "        with self._lock:\n"
        "            self.buffer.append(item)\n"
    )
    result = _lint_source(tmp_path, src)
    assert not [f for f in result.findings if f.rule_id == "DML014"]


def test_project_rule_findings_respect_inline_suppressions(tmp_path):
    src = (
        "from distributed_machine_learning_tpu.analysis.locks import "
        "named_lock\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = named_lock('fix.c')\n"
        "        self.n = 0\n\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n\n"
        "    def peek(self):\n"
        "        return self.n  "
        "# dmlint: disable=unguarded-shared-state test: atomic read\n"
    )
    result = _lint_source(tmp_path, src)
    hits = [f for f in result.findings if f.rule_id == "DML014"]
    assert len(hits) == 1 and hits[0].suppressed
    assert not result.unsuppressed()


# --------------------------------------------------------------------------
# CLI satellites: --changed and --format=sarif
# --------------------------------------------------------------------------


def _git(tmp_path, *args):
    import subprocess

    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=tmp_path, capture_output=True, text=True, check=True,
    )


def test_lint_changed_matches_full_run_exit_codes(tmp_path, capsys):
    from distributed_machine_learning_tpu.__main__ import main

    _git(tmp_path, "init", "-q")
    clean = tmp_path / "clean.py"
    clean.write_text(
        "import time\n\n\ndef age(start):\n"
        "    return time.monotonic() - start\n"
    )
    _git(tmp_path, "add", "clean.py")
    _git(tmp_path, "commit", "-qm", "clean")
    # a violation lands in the working tree: --changed and the full run
    # must agree (exit 1)
    hot = tmp_path / "hot.py"
    hot.write_text(
        "import time\n\n\ndef lease():\n"
        "    deadline = time.time() + 5\n    return deadline\n"
    )
    for argv in (
        ["lint", str(tmp_path), "--baseline", "none"],
        ["lint", str(tmp_path), "--changed", "--baseline", "none"],
    ):
        with pytest.raises(SystemExit) as exc:
            main(argv)
        assert exc.value.code == 1, argv
    out = capsys.readouterr().out
    assert "hot.py" in out and "clean.py" not in out
    # committed: nothing changed vs HEAD -> exit 0 without linting
    _git(tmp_path, "add", "hot.py")
    _git(tmp_path, "commit", "-qm", "hot")
    _git(tmp_path, "rm", "-q", "hot.py")
    _git(tmp_path, "commit", "-qm", "rm")
    with pytest.raises(SystemExit) as exc:
        main(["lint", str(tmp_path), "--changed", "--baseline", "none"])
    assert exc.value.code == 0
    assert "no .py files changed" in capsys.readouterr().out


def test_lint_changed_sees_cross_file_findings_in_changed_file(tmp_path,
                                                               capsys):
    """--changed parses the WHOLE tree (a cross-file rule needs the full
    call graph) but reports only from changed files: a caller edited to
    read a donated buffer is caught even though the donating helper is
    untouched."""
    from distributed_machine_learning_tpu.__main__ import main

    _git(tmp_path, "init", "-q")
    (tmp_path / "callee.py").write_text(
        "import jax\n\n\n"
        "def donate_state(params, opt_state, key):\n"
        "    step = jax.jit(lambda p, o, k: (p, o), "
        "donate_argnums=(0, 1))\n"
        "    return step(params, opt_state, key)\n"
    )
    caller = tmp_path / "caller.py"
    caller.write_text(
        "from callee import donate_state\n\n\n"
        "def run(params, opt_state, key):\n"
        "    params, opt_state = donate_state(params, opt_state, key)\n"
        "    return float(params.mean())\n"
    )
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")
    caller.write_text(
        "from callee import donate_state\n\n\n"
        "def run(params, opt_state, key):\n"
        "    new_p, new_o = donate_state(params, opt_state, key)\n"
        "    return float(params.mean())\n"
    )
    with pytest.raises(SystemExit) as exc:
        main(["lint", str(tmp_path), "--changed", "--baseline", "none"])
    assert exc.value.code == 1
    out = capsys.readouterr().out
    assert "DML012" in out and "caller.py" in out


def test_lint_format_sarif(tmp_path, capsys):
    from distributed_machine_learning_tpu.__main__ import main

    bad = os.path.join(FIXTURES, "bad_wallclock_deadline.py")
    with pytest.raises(SystemExit) as exc:
        main(["lint", bad, "--baseline", "none", "--format", "sarif"])
    assert exc.value.code == 1  # exit-code parity with the text run
    import json

    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "dmlint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"DML004", "DML012", "DML013", "DML014"} <= rule_ids
    results = run["results"]
    assert results and all(r["ruleId"] == "DML004" for r in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith(
        "bad_wallclock_deadline.py"
    )
    assert loc["region"]["startLine"] > 0
    assert not run["invocations"][0]["executionSuccessful"]
    # clean file: empty results, exit 0
    clean = os.path.join(FIXTURES, "clean_wallclock_deadline.py")
    with pytest.raises(SystemExit) as exc:
        main(["lint", clean, "--baseline", "none", "--format", "sarif"])
    assert exc.value.code == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"] == []


# --------------------------------------------------------------------------
# engine perf guard: one parse per file, shared across rules and runs
# --------------------------------------------------------------------------


def test_whole_package_lint_parses_each_file_once_and_caches():
    from distributed_machine_learning_tpu.analysis import engine

    engine.clear_context_cache()
    before = engine.parse_count()
    first = analysis.lint_paths([PKG_ROOT])
    parsed = engine.parse_count() - before
    # 14 rules (3 of them whole-project) over N files: N parses exactly
    assert parsed == first.files_checked, (parsed, first.files_checked)
    second = analysis.lint_paths([PKG_ROOT])
    assert engine.parse_count() - before == parsed  # cache: zero re-parses
    assert second.files_checked == first.files_checked


def test_whole_package_lint_stays_under_wall_clock_budget():
    """The tested perf budget (ISSUE 11): parsing every file once into
    the shared project context, then running every rule — cross-file
    ones included — must stay interactive.  Measured ~2.4s on the CI
    container; the budget leaves ~8x headroom for a loaded host before
    someone notices their pre-commit hook."""
    import time

    from distributed_machine_learning_tpu.analysis import engine

    engine.clear_context_cache()  # honest cold run
    t0 = time.monotonic()
    result = analysis.lint_paths([PKG_ROOT])
    dt = time.monotonic() - t0
    assert result.files_checked > 40
    assert dt < 20.0, f"whole-package lint took {dt:.1f}s (budget 20s)"


def test_full_jax_tier_run_is_inert_and_in_budget():
    """The jaxlint inertness contract (ISSUE 12): a full --jax run
    performs ZERO backend compiles and leaves ZERO device buffers
    behind, and stays inside the same 20s wall-clock budget as the AST
    tier.  Compiles are asserted from the compilecache tracker's event
    deltas (measured OUTSIDE the runner too, so the runner cannot grade
    its own homework); allocations from jax.live_arrays() deltas after
    the run releases its traced artifacts.  Measured ~4s / 0 compiles /
    0 live arrays on the CI container."""
    import gc
    import time

    import jax

    from distributed_machine_learning_tpu.compilecache.tracker import (
        get_tracker,
    )

    tracker = get_tracker()
    outer_before = tracker.snapshot()
    gc.collect()
    live_before = len(jax.live_arrays())
    t0 = time.monotonic()
    result = analysis.run_jax_checks()
    dt = time.monotonic() - t0
    gc.collect()
    outer_after = tracker.snapshot()

    assert not result.errors, result.errors
    # the runner's own measurement...
    assert result.inert["backend_compiles"] == 0, result.inert
    assert result.inert["backend_compiles_uncached"] == 0, result.inert
    assert result.inert["live_arrays"] <= 0, result.inert
    # ...and the independent outer one agree: nothing compiled, nothing
    # survives on device (other tests' garbage may have been collected
    # meanwhile, so <=, not ==).
    assert outer_after["backend_compiles"] == \
        outer_before["backend_compiles"]
    assert len(jax.live_arrays()) - live_before <= 0
    # the audit genuinely traced the programs (it is not inert because
    # it did nothing)
    assert result.inert["traces"] > 0
    assert dt < 20.0, f"full --jax run took {dt:.1f}s (budget 20s)"


# --------------------------------------------------------------------------
# engine hygiene
# --------------------------------------------------------------------------


def test_every_package_file_parses_for_the_linter():
    count = 0
    for path in analysis.iter_python_files([PKG_ROOT]):
        load_context(path)  # raises on syntax error
        count += 1
    assert count > 40


def test_rule_catalog_is_documented():
    """docs/static-analysis.md must name every rule (id + name): the doc
    IS the catalog, and a rule landing without docs is how suppression
    reasons rot."""
    doc = os.path.join(os.path.dirname(PKG_ROOT), "docs",
                       "static-analysis.md")
    assert os.path.exists(doc)
    text = open(doc).read()
    for rule in analysis.ALL_RULES:
        assert rule.rule_id in text, f"{rule.rule_id} missing from catalog"
        assert rule.name in text, f"{rule.name} missing from catalog"
