"""Tier-1 gate for the analysis/ package (ISSUE 6).

Three layers of enforcement:

* **the lint gate** — dmlint over the whole installed package must report
  ZERO unsuppressed findings (and the checked-in baseline must be empty:
  grandfathering is a burn-down device, not a parking lot);
* **rule fidelity** — every rule fires on its historical bug pattern
  (``tests/analysis_fixtures/bad_*.py``, golden ``# EXPECT: <rule>``
  markers matched on rule AND line) and stays silent on the idiomatic
  twin (``clean_*.py``, zero findings under ALL rules);
* **lock order** — the runtime recorder (enabled suite-wide by conftest's
  ``DML_LOCK_ORDER=1``) sees a deliberately inverted acquisition as a
  cycle, and the union graph across the instrumented
  executor/cluster/serve/ckpt/dispatch locks stays acyclic.
"""

import ast
import collections
import os
import re
import threading

import pytest

import distributed_machine_learning_tpu as pkg
from distributed_machine_learning_tpu import analysis
from distributed_machine_learning_tpu.analysis import locks as locks_lib
from distributed_machine_learning_tpu.analysis.engine import load_context

PKG_ROOT = os.path.dirname(os.path.abspath(pkg.__file__))
FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")
RULE_NAMES = [r.name for r in analysis.ALL_RULES]

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([a-z\-,\s]+?)\s*$")


# --------------------------------------------------------------------------
# the gate
# --------------------------------------------------------------------------


def test_package_has_zero_unsuppressed_findings():
    result = analysis.lint_paths([PKG_ROOT])
    assert result.files_checked > 40  # the walk really covered the package
    assert not result.errors, result.errors
    live = result.unsuppressed()
    assert not live, "unsuppressed dmlint finding(s):\n" + "\n".join(
        f.format() for f in live
    )


def test_baseline_is_empty():
    """Satellite goal state: nothing grandfathered.  A PR that wants to
    baseline a new finding must consciously argue with this test —
    inline `# dmlint: disable=<rule> <reason>` is the sanctioned escape
    hatch for intentional exceptions."""
    from distributed_machine_learning_tpu.analysis.findings import (
        load_baseline,
    )

    entries = load_baseline(analysis.DEFAULT_BASELINE)
    assert entries == [], (
        f"baseline should be empty; fix or inline-suppress: {entries}"
    )


def test_lint_cli_exits_nonzero_on_findings(capsys):
    from distributed_machine_learning_tpu.__main__ import main

    bad = os.path.join(FIXTURES, "bad_wallclock_deadline.py")
    with pytest.raises(SystemExit) as exc:
        main(["lint", bad, "--baseline", "none"])
    assert exc.value.code == 1
    out = capsys.readouterr().out
    assert "wallclock-deadline" in out and "DML004" in out
    with pytest.raises(SystemExit) as exc:
        main(["lint", os.path.join(FIXTURES, "clean_wallclock_deadline.py"),
              "--baseline", "none"])
    assert exc.value.code == 0


# --------------------------------------------------------------------------
# rule fidelity: bad fixture fires exactly as marked; clean twin is silent
# --------------------------------------------------------------------------


def _expected_markers(path):
    """Multiset of (line, rule) from # EXPECT: comments."""
    expected = collections.Counter()
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            m = _EXPECT_RE.search(line)
            if m:
                for rule in m.group(1).split(","):
                    expected[(lineno, rule.strip())] += 1
    return expected


@pytest.mark.parametrize("rule_name", RULE_NAMES)
def test_rule_fires_on_historical_bug_pattern(rule_name):
    path = os.path.join(FIXTURES, f"bad_{rule_name.replace('-', '_')}.py")
    assert os.path.exists(path), f"missing fixture for {rule_name}"
    expected = _expected_markers(path)
    assert expected, f"{path} has no EXPECT markers"
    assert {r for _, r in expected} == {rule_name}, (
        "a bad fixture exercises exactly its own rule"
    )
    result = analysis.lint_paths([path], baseline_path=None)
    got = collections.Counter((f.line, f.rule) for f in result.findings)
    assert got == expected, (
        f"{rule_name}: expected {dict(expected)}, got {dict(got)}\n"
        + "\n".join(f.format() for f in result.findings)
    )


@pytest.mark.parametrize("rule_name", RULE_NAMES)
def test_rule_is_silent_on_idiomatic_twin(rule_name):
    path = os.path.join(FIXTURES, f"clean_{rule_name.replace('-', '_')}.py")
    assert os.path.exists(path), f"missing clean twin for {rule_name}"
    result = analysis.lint_paths([path], baseline_path=None)
    assert not result.findings, (
        f"false positive(s) on the idiomatic form:\n"
        + "\n".join(f.format() for f in result.findings)
    )


# --------------------------------------------------------------------------
# suppression + baseline mechanics
# --------------------------------------------------------------------------


def _lint_source(tmp_path, source, baseline_path=None):
    p = tmp_path / "case.py"
    p.write_text(source)
    return analysis.lint_paths([str(p)], baseline_path=baseline_path)


def test_inline_suppression_same_line(tmp_path):
    src = (
        "import time\n"
        "deadline = time.time() + 5  "
        "# dmlint: disable=wallclock-deadline test-only clock\n"
    )
    result = _lint_source(tmp_path, src)
    assert len(result.findings) == 1
    assert result.findings[0].suppressed
    assert not result.unsuppressed()


def test_inline_suppression_directive_line_above(tmp_path):
    src = (
        "import time\n"
        "# dmlint: disable=wallclock-deadline reason: fixture\n"
        "deadline = time.time() + 5\n"
    )
    result = _lint_source(tmp_path, src)
    assert result.findings and result.findings[0].suppressed


def test_suppression_for_other_rule_does_not_apply(tmp_path):
    src = (
        "import time\n"
        "deadline = time.time() + 5  # dmlint: disable=import-trace nope\n"
    )
    result = _lint_source(tmp_path, src)
    assert result.unsuppressed(), "wrong-rule suppression must not silence"


def test_baseline_roundtrip_absorbs_then_burns_down(tmp_path):
    src = "import time\ndeadline = time.time() + 5\n"
    p = tmp_path / "case.py"
    p.write_text(src)
    base = tmp_path / "baseline.json"
    first = analysis.lint_paths([str(p)], baseline_path=None)
    assert len(first.unsuppressed()) == 1
    analysis.save_baseline(str(base), first.unsuppressed())
    second = analysis.lint_paths([str(p)], baseline_path=str(base))
    assert not second.unsuppressed()
    assert second.findings[0].baselined
    # the fix lands: baseline entry goes stale harmlessly, nothing fires
    p.write_text("import time\ndeadline = time.monotonic() + 5\n")
    third = analysis.lint_paths([str(p)], baseline_path=str(base))
    assert not third.findings


def test_scope_marker_opts_file_into_scoped_rules(tmp_path):
    src = "# dmlint-scope: checkpoint-path\nimport pickle\n"
    result = _lint_source(tmp_path, src)
    assert any(f.rule == "pickle-checkpoint" for f in result.findings)
    # without the marker, an arbitrary file is out of the pickle scope
    result = _lint_source(tmp_path, "import pickle\n")
    assert not result.findings


# --------------------------------------------------------------------------
# lock-order recorder
# --------------------------------------------------------------------------


def test_inverted_acquisition_is_detected_as_cycle():
    """The acceptance fixture: two locks taken a->b on one code path and
    b->a on another (fresh recorder: the deliberate inversion must not
    poison the suite-wide graph)."""
    locks_lib.enable()  # conftest sets the env; make the invariant local
    rec = locks_lib.LockOrderRecorder()
    a = locks_lib.NamedLock("fix.a", recorder=rec)
    b = locks_lib.NamedLock("fix.b", recorder=rec)
    with a:
        with b:
            pass
    rec.assert_acyclic()  # one direction alone is fine

    def inverted():
        with b:
            with a:
                pass

    t = threading.Thread(target=inverted)
    t.start()
    t.join()
    with pytest.raises(locks_lib.LockOrderViolation) as exc:
        rec.assert_acyclic()
    msg = str(exc.value)
    assert "fix.a" in msg and "fix.b" in msg and "->" in msg
    assert rec.cycles()


def test_same_role_nesting_is_tracked_not_a_cycle():
    rec = locks_lib.LockOrderRecorder()
    outer = locks_lib.NamedLock("fix.role", recorder=rec)
    inner = locks_lib.NamedLock("fix.role", recorder=rec)
    with outer:
        with inner:
            pass
    assert rec.cycles() == []
    assert rec.self_edges.get("fix.role") == 1


def test_named_lock_backs_a_condition():
    lock = locks_lib.named_lock("fix.cond")
    cond = threading.Condition(lock)
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5.0)
            hits.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    # Let the waiter reach wait(); notify under the lock.
    import time

    deadline = time.monotonic() + 5.0
    while not hits and time.monotonic() < deadline:
        with cond:
            cond.notify_all()
        time.sleep(0.01)
    t.join(timeout=5.0)
    assert hits == [1]


def test_instrumented_subsystems_record_and_stay_acyclic(tmp_path):
    """Drive a small workload through each instrumented subsystem, then
    assert (a) the recorder saw their lock roles and (b) the union
    acquisition graph — including everything earlier tests recorded — has
    no cycle.  This is the tier-1 'acyclic across executor/cluster/serve/
    ckpt' acceptance; the rest of the suite keeps feeding the same global
    recorder."""
    import numpy as np

    assert locks_lib.enabled(), "conftest must enable DML_LOCK_ORDER"
    rec = locks_lib.get_recorder()

    # ckpt: async writer + metrics
    from distributed_machine_learning_tpu.ckpt.writer import AsyncCheckpointer

    w = AsyncCheckpointer(log=lambda msg: None)
    w.save(str(tmp_path / "ck.msgpack"), {"w": np.ones((2, 2), np.float32)})
    assert w.wait_until_finished(timeout=30)
    w.close()

    # serve: micro-batcher (Condition over a NamedLock) + circuit breaker
    from distributed_machine_learning_tpu.serve.batcher import MicroBatcher
    from distributed_machine_learning_tpu.serve.replica import CircuitBreaker

    mb = MicroBatcher(lambda x: x * 2, max_batch_size=4, max_latency_ms=1.0)
    fut = mb.submit(np.ones((1, 3), np.float32))
    assert fut.result(timeout=10) is not None
    mb.stop()
    br = CircuitBreaker(failure_threshold=1, recovery_s=60.0)
    assert br.allow()
    br.record_failure()
    assert not br.allow()

    # chaos: a seeded plan decision
    from distributed_machine_learning_tpu import chaos

    plan = chaos.FaultPlan(seed=3, write_error_rate=1.0)
    with pytest.raises(IOError):
        plan.on_storage_op("write", "exp/trial/checkpoint_1")
    assert plan.snapshot()["storage_write_errors"] == 1

    # tune: the in-memory storage backend's shared-namespace lock
    from distributed_machine_learning_tpu.tune.storage import MemoryStorage

    mem = MemoryStorage()
    mem.write_bytes("mem://fix/blob", b"bytes")
    assert mem.read_bytes("mem://fix/blob") == b"bytes"

    # dispatch + cluster + executor-side liveness primitives
    from distributed_machine_learning_tpu.utils import dispatch
    from distributed_machine_learning_tpu.tune import cluster
    from distributed_machine_learning_tpu import liveness

    with dispatch._LOCK:
        pass
    with cluster._SEEN_KEYS_LOCK:
        pass
    dog = liveness.DispatchWatchdog(1.0)
    dog.track("k")
    dog.beat("k")
    dog.expired()

    seen = rec.roles_seen
    for role in (
        "ckpt.writer", "ckpt.metrics", "serve.batcher.queue",
        "serve.batcher.stats", "serve.breaker", "chaos.plan", "dispatch",
        "cluster.seen_keys", "liveness.watchdog", "liveness.heartbeat",
        "tune.storage.mem",
    ):
        assert role in seen, f"lock role {role!r} never recorded"
    rec.assert_acyclic()
    # Same-role nesting would be an instance-order hazard the role graph
    # cannot see — the instrumented roles must not develop one silently.
    assert not any(
        rec.self_edges.get(r) for r in seen if not r.startswith("fix.")
    ), rec.self_edges


def test_recorder_snapshot_shape():
    rec = locks_lib.LockOrderRecorder()
    a = locks_lib.NamedLock("s.a", recorder=rec)
    b = locks_lib.NamedLock("s.b", recorder=rec)
    with a:
        with b:
            pass
    snap = rec.snapshot()
    assert snap["edges"] == ["s.a -> s.b"]
    assert set(snap["roles"]) == {"s.a", "s.b"}
    assert snap["cycles"] == []


# --------------------------------------------------------------------------
# engine hygiene
# --------------------------------------------------------------------------


def test_every_package_file_parses_for_the_linter():
    count = 0
    for path in analysis.iter_python_files([PKG_ROOT]):
        load_context(path)  # raises on syntax error
        count += 1
    assert count > 40


def test_rule_catalog_is_documented():
    """docs/static-analysis.md must name every rule (id + name): the doc
    IS the catalog, and a rule landing without docs is how suppression
    reasons rot."""
    doc = os.path.join(os.path.dirname(PKG_ROOT), "docs",
                       "static-analysis.md")
    assert os.path.exists(doc)
    text = open(doc).read()
    for rule in analysis.ALL_RULES:
        assert rule.rule_id in text, f"{rule.rule_id} missing from catalog"
        assert rule.name in text, f"{rule.name} missing from catalog"
