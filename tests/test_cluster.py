"""Multi-host control plane: driver <-> worker supervisors over TCP.

SURVEY.md §2b D4/D5: the capability the reference delegated to Ray Core —
cluster trial placement, metric RPC, fault handling — exercised here with
real worker subprocesses on localhost (the same supervisor binary a TPU pod
host would run).
"""

from __future__ import annotations

import os
import sys
import threading
import time

import pytest

from distributed_machine_learning_tpu import tune
from distributed_machine_learning_tpu.tune.cluster import (
    resolve_trainable,
    run_distributed,
    start_local_workers,
)

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _worker_env():
    # Strip any TPU-claiming sitecustomize (e.g. an .axon_site entry) from the
    # workers' PYTHONPATH: worker supervisors in these tests are CPU-only, and
    # a per-process TPU-session claim would serialize/deadlock their startup.
    keep = [
        p
        for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    ]
    return {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.pathsep.join([TESTS_DIR] + keep),
    }


@pytest.fixture(scope="module")
def worker_pool():
    procs, addrs = start_local_workers(2, slots=2, env=_worker_env())
    yield addrs
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except Exception:
            p.kill()


def test_resolve_trainable_specs():
    fn = resolve_trainable("cluster_trainables:quadratic_trial")
    assert callable(fn)
    fn2 = resolve_trainable("os.path.join")
    assert fn2 is os.path.join
    assert resolve_trainable(fn) is fn


def test_distributed_sweep_completes(worker_pool, tmp_path):
    analysis = run_distributed(
        "cluster_trainables:quadratic_trial",
        {"x": tune.uniform(0.0, 6.0), "epochs": 4},
        metric="loss",
        mode="min",
        num_samples=8,
        workers=worker_pool,
        storage_path=str(tmp_path),
        name="dist_smoke",
        seed=3,
        verbose=0,
    )
    assert analysis.num_terminated() == 8
    best = analysis.best_config
    assert 0.0 <= best["x"] <= 6.0
    # Best trial should be the sampled x closest to the optimum at 3.0.
    xs = [t.config["x"] for t in analysis.trials]
    assert abs(best["x"] - 3.0) == min(abs(x - 3.0) for x in xs)
    # Per-epoch streaming: every trial has one result per epoch.
    for t in analysis.trials:
        assert len(t.results) == 4
        assert t.results[-1]["hostname"]


def test_distributed_asha_early_stops(worker_pool, tmp_path):
    from distributed_machine_learning_tpu.tune.schedulers import ASHAScheduler

    analysis = run_distributed(
        "cluster_trainables:quadratic_trial",
        {"x": tune.uniform(0.0, 6.0), "epochs": 8},
        metric="loss",
        mode="min",
        num_samples=8,
        workers=worker_pool,
        scheduler=ASHAScheduler(max_t=8, grace_period=1, reduction_factor=2),
        storage_path=str(tmp_path),
        name="dist_asha",
        seed=5,
        verbose=0,
    )
    assert analysis.num_terminated() == 8
    iters = [len(t.results) for t in analysis.trials]
    assert any(i < 8 for i in iters), f"ASHA never early-stopped: {iters}"
    assert any(i == 8 for i in iters)


def test_distributed_retry_restores_from_checkpoint(worker_pool, tmp_path):
    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir, exist_ok=True)
    analysis = run_distributed(
        "cluster_trainables:crash_once_trial",
        {"marker_dir": marker_dir},
        metric="loss",
        mode="min",
        num_samples=3,
        workers=worker_pool,
        max_failures=2,
        storage_path=str(tmp_path),
        name="dist_retry",
        verbose=0,
    )
    assert analysis.num_terminated() == 3
    for t in analysis.trials:
        assert t.num_failures == 1  # crashed once, then recovered
        epochs = [r["epoch"] for r in t.results]
        # epoch 1 reported pre-crash; retry restores from its checkpoint and
        # continues with 2, 3 rather than restarting at 1.
        assert epochs[0] == 1 and epochs[-1] == 3
        assert epochs.count(1) == 1


def test_distributed_pbt_exploits_and_restores(worker_pool, tmp_path):
    """PBT over the cluster: REQUEUE decisions stop a lagging trial, restore a
    donor checkpoint on a (possibly different) worker, and resume mid-stream —
    the full exploit/explore loop across the control plane."""
    from distributed_machine_learning_tpu.tune.schedulers import (
        PopulationBasedTraining,
    )

    barrier_dir = tmp_path / "barrier"
    barrier_dir.mkdir()
    pbt = PopulationBasedTraining(
        perturbation_interval=2,
        hyperparam_mutations={"rate": tune.uniform(0.01, 0.5)},
        quantile_fraction=0.5,
        seed=11,
    )
    analysis = run_distributed(
        "cluster_trainables:pbt_trial",
        {"rate": tune.uniform(0.01, 0.5), "epochs": 8,
         "barrier_dir": str(barrier_dir), "population": 4},
        metric="loss",
        mode="min",
        num_samples=4,
        workers=worker_pool,
        scheduler=pbt,
        storage_path=str(tmp_path),
        name="dist_pbt",
        seed=9,
        verbose=0,
    )
    assert analysis.num_terminated() == 4
    # Every trial must reach the final epoch despite stop/respawn cycles.
    assert all(t.results[-1]["epoch"] == 8 for t in analysis.trials)
    # PBT must have acted: the barrier-paced population guarantees every
    # trial's scores are comparable when the interval fires, so the bottom
    # trial is requeued by construction.  (Epoch-sequence heuristics are NOT
    # a reliable respawn detector: a laggard stopped at epoch k and restored
    # from a donor checkpoint also at epoch k re-reports the plain staircase.)
    assert pbt.debug_state()["num_perturbations"] >= 1, "PBT never requeued"
    # The exploit actually routed donor weights: some trial restored from a
    # checkpoint it did not write itself.
    restored = [t for t in analysis.trials if t.restore_path]
    assert any(t.trial_id not in t.restore_path for t in restored)


def test_worker_death_requeues_trials(tmp_path):
    procs, addrs = start_local_workers(2, slots=2, env=_worker_env())
    result = {}

    def drive():
        # Capture, don't swallow: a raise in here must fail the test with
        # ITS traceback, not an opaque KeyError on the result dict.
        try:
            result["analysis"] = run_distributed(
                "cluster_trainables:slow_trial",
                {"epochs": 10, "sleep_s": 0.2},
                metric="loss",
                mode="min",
                num_samples=4,
                workers=addrs,
                max_failures=3,
                storage_path=str(tmp_path),
                name="dist_death",
                verbose=0,
            )
        except BaseException:
            import traceback

            result["error"] = traceback.format_exc()

    # All 4 trials land immediately (2 slots x 2 workers); killing one worker
    # mid-flight forces its 2 trials to requeue onto the survivor.
    t = threading.Thread(target=drive)
    t.start()
    time.sleep(1.0)
    procs[0].kill()
    t.join(timeout=120)
    assert not t.is_alive(), "driver hung after worker death"
    assert "error" not in result, f"run_distributed raised:\n{result['error']}"
    analysis = result["analysis"]
    done = analysis.num_terminated()
    assert done == 4, f"only {done}/4 trials finished after worker death"
    assert any(t_.num_failures > 0 for t_ in analysis.trials)
    for p in procs[1:]:
        p.terminate()


def test_trial_time_limit_over_cluster(worker_pool, tmp_path):
    """Per-trial time limits apply to cluster trials at report boundaries."""
    analysis = run_distributed(
        "cluster_trainables:slow_trial",
        {"epochs": 30, "sleep_s": 0.2},
        metric="loss",
        mode="min",
        num_samples=2,
        workers=worker_pool,
        time_limit_per_trial_s=1.0,
        storage_path=str(tmp_path),
        name="dist_tl",
        verbose=0,
    )
    for t in analysis.trials:
        assert t.status.value == "TERMINATED"
        assert 1 <= t.training_iteration < 30


def test_jax_runs_on_worker(worker_pool, tmp_path):
    analysis = run_distributed(
        "cluster_trainables:jax_device_trial",
        {"x": tune.choice([1.0, 2.0])},
        metric="loss",
        mode="min",
        num_samples=2,
        workers=worker_pool,
        storage_path=str(tmp_path),
        name="dist_jax",
        verbose=0,
    )
    assert analysis.num_terminated() == 2
    for t in analysis.trials:
        assert "cpu" in t.results[-1]["device"].lower()


def test_distributed_resume(worker_pool, tmp_path):
    """run_distributed(resume=True): interrupted trials redispatch from
    their checkpoints, finished ones stay finished, sampling continues."""
    import json

    from distributed_machine_learning_tpu.tune.trial import TrialStatus

    first = run_distributed(
        "cluster_trainables:resumable_quadratic_trial",
        {"x": tune.uniform(0.0, 6.0), "epochs": 4},
        metric="loss",
        mode="min",
        num_samples=3,
        workers=worker_pool,
        storage_path=str(tmp_path),
        name="dist_resume",
        seed=5,
        verbose=0,
    )
    assert first.num_terminated() == 3
    root = first.root
    # Simulate a driver crash with trial_00002 mid-flight at epoch 2.
    state_path = os.path.join(root, "experiment_state.json")
    with open(state_path) as f:
        state = json.load(f)
    for t in state["trials"]:
        if t["trial_id"] == "trial_00002":
            t["status"] = "RUNNING"
    with open(state_path, "w") as f:
        json.dump(state, f)
    results_path = os.path.join(root, "trial_00002", "result.jsonl")
    with open(results_path) as f:
        lines = [l for l in f if l.strip()]
    with open(results_path, "w") as f:
        f.writelines(lines[:2])

    resumed = run_distributed(
        "cluster_trainables:resumable_quadratic_trial",
        {"x": tune.uniform(0.0, 6.0), "epochs": 4},
        metric="loss",
        mode="min",
        num_samples=4,
        workers=worker_pool,
        storage_path=str(tmp_path),
        name="dist_resume",
        seed=5,
        verbose=0,
        resume=True,
    )
    by_id = {t.trial_id: t for t in resumed.trials}
    assert len(by_id) == 4
    assert all(t.status == TrialStatus.TERMINATED for t in resumed.trials)
    assert by_id["trial_00002"].training_iteration == 4
    # A REAL checkpoint resume: only the 2 replayed pre-crash records remain
    # (the epoch-4 checkpoint survived, so the re-run had nothing to report).
    # A silent from-scratch re-run would show 4 records here.
    assert len(by_id["trial_00002"].results) == 2
    assert len(by_id["trial_00003"].results) == 4  # the newly sampled one


def test_hmac_authenticated_control_plane(tmp_path, monkeypatch):
    """With DML_CLUSTER_SECRET set on both sides, every frame is MACed and a
    sweep runs end-to-end; a driver with the WRONG secret is rejected at the
    hello (frames failing verification never reach pickle.loads)."""
    from distributed_machine_learning_tpu.tune.cluster import RemoteWorker

    secret_env = dict(_worker_env(), DML_CLUSTER_SECRET="s3cret")
    procs, addrs = start_local_workers(1, slots=2, env=secret_env)
    try:
        monkeypatch.setenv("DML_CLUSTER_SECRET", "s3cret")
        analysis = run_distributed(
            "cluster_trainables:quadratic_trial",
            {"x": tune.uniform(0.0, 6.0), "epochs": 2},
            metric="loss",
            mode="min",
            num_samples=2,
            workers=addrs,
            storage_path=str(tmp_path),
            name="dist_hmac",
            verbose=0,
        )
        assert analysis.num_terminated() == 2

        # Wrong secret: the worker's hello frame fails our MAC check.
        monkeypatch.setenv("DML_CLUSTER_SECRET", "wrong")
        with pytest.raises((ConnectionError, OSError)):
            RemoteWorker(addrs[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()


def test_distributed_stop_rules(worker_pool, tmp_path):
    """stop= has the tune.run surface on the cluster driver too: trials cut
    at the threshold across the control plane."""
    analysis = run_distributed(
        "cluster_trainables:quadratic_trial",
        {"x": tune.uniform(0.0, 6.0), "epochs": 6},
        metric="loss",
        mode="min",
        num_samples=3,
        workers=worker_pool,
        stop={"training_iteration": 2},
        storage_path=str(tmp_path),
        name="dist_stop",
        seed=11,
        verbose=0,
    )
    assert analysis.num_terminated() == 3
    assert all(len(t.results) == 2 for t in analysis.trials)


def test_distributed_callbacks_and_reporter(worker_pool, tmp_path, capsys):
    """run_distributed exposes the same observer surface as tune.run: every
    lifecycle hook fires on the driver thread, and verbose=2 attaches the
    live trial table."""

    class Recording(tune.Callback):
        def __init__(self):
            self.events = []

        def setup(self, root, metric, mode):
            self.events.append(("setup", metric, mode))

        def on_trial_start(self, trial):
            self.events.append(("start", trial.trial_id))

        def on_trial_result(self, trial, result):
            self.events.append(("result", trial.trial_id,
                                result.get("training_iteration")))

        def on_trial_complete(self, trial):
            self.events.append(("complete", trial.trial_id))

        def on_trial_error(self, trial, error):
            self.events.append(("error", trial.trial_id))

        def on_experiment_end(self, trials, wall):
            self.events.append(("end", len(trials)))

    cb = Recording()
    analysis = run_distributed(
        "cluster_trainables:quadratic_trial",
        {"x": tune.uniform(0.0, 6.0), "epochs": 3},
        metric="loss", mode="min", num_samples=4,
        workers=worker_pool,
        storage_path=str(tmp_path), name="dist_cb", seed=5,
        verbose=2,
    )
    assert analysis.num_terminated() == 4
    out = capsys.readouterr().out
    assert "Final result" in out and "best loss:" in out  # verbose=2 table

    # The explicit-callback path sees the full lifecycle.
    cb2 = Recording()
    analysis = run_distributed(
        "cluster_trainables:quadratic_trial",
        {"x": tune.uniform(0.0, 6.0), "epochs": 2},
        metric="loss", mode="min", num_samples=3,
        workers=worker_pool,
        storage_path=str(tmp_path), name="dist_cb2", seed=6,
        verbose=0, callbacks=[cb2],
    )
    assert analysis.num_terminated() == 3
    kinds = [e[0] for e in cb2.events]
    assert kinds[0] == "setup" and cb2.events[0] == ("setup", "loss", "min")
    assert kinds[-1] == "end"
    assert kinds.count("start") == 3
    assert kinds.count("complete") == 3
    assert kinds.count("result") == 6  # 3 trials x 2 epochs


def test_distributed_mesh_shape_leases_device_groups(tmp_path):
    """run_distributed(mesh_shape=...) stamps the mesh into every config
    and each dispatch hands the trial prod(mesh_shape) DISTINCT local
    devices (worker slot groups) — the cluster side of the partition-rule
    sharding tentpole (ISSUE 7)."""
    procs, addrs = start_local_workers(1, slots=2, env=_worker_env())
    try:
        analysis = run_distributed(
            "cluster_trainables:mesh_probe_trial",
            {"x": tune.uniform(0.0, 1.0)},
            metric="loss", mode="min", num_samples=3,
            workers=addrs, storage_path=str(tmp_path), name="mesh_cluster",
            seed=2, verbose=0,
            mesh_shape={"dp": 2, "tp": 2},
        )
        assert analysis.num_terminated() == 3
        for t in analysis.trials:
            assert t.config["mesh_shape"] == {"dp": 2, "tp": 2}
            last = t.last_result
            assert last["n_devices"] == 4
            assert last["n_distinct"] == 4
            assert last["mesh_shape"] == {"dp": 2, "tp": 2}
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
