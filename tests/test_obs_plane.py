"""The observability plane (obs/, ISSUE 13): tracing across thread /
process / cluster boundaries, the always-on flight recorder and its
dump-on-stall path, the unified metrics registry + head aggregation,
chaos coverage of the exporters, the trace CLI, and the disabled-path
overhead guard."""

import glob
import json
import os
import sys
import threading
import time

import pytest

from distributed_machine_learning_tpu import chaos, obs, tune
from distributed_machine_learning_tpu.tune.cluster import (
    run_distributed,
    start_local_workers,
)

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _fresh_obs_state():
    """Each test starts with tracing off and no ambient dump dir."""
    obs.shutdown()
    obs.set_dump_dir(None)
    yield
    obs.shutdown()
    obs.set_dump_dir(None)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class _Fam:
    def __init__(self):
        self.hits = 0

    def snapshot(self):
        return {"hits": self.hits}


def test_registry_families_and_counters():
    reg = obs.get_registry()
    fam = _Fam()
    reg.register_family("obs_test_fam", fam)
    try:
        fam.hits = 3
        snap = reg.snapshot()
        assert snap["families"]["obs_test_fam"] == {"hits": 3}
        base = reg.counters_snapshot()
        reg.add("obs_test_counter", 2)
        assert reg.delta_since(base)["obs_test_counter"] == 2
        flat = reg.scalar_snapshot()
        assert flat["obs_test_fam/hits"] == 3
    finally:
        reg.unregister_family("obs_test_fam")
    assert "obs_test_fam" not in reg.families()


def test_registry_broken_family_is_counted_not_fatal():
    reg = obs.get_registry()
    reg.register_family("obs_broken_fam", lambda: 1 / 0)
    try:
        before = reg.get("family_errors")
        snap = reg.snapshot()
        assert "obs_broken_fam" not in snap["families"]
        assert reg.get("family_errors") == before + 1
    finally:
        reg.unregister_family("obs_broken_fam")


def test_registry_stale_unregister_does_not_evict_newer():
    reg = obs.get_registry()
    old, new = _Fam(), _Fam()
    reg.register_family("obs_gen_fam", old)
    reg.register_family("obs_gen_fam", new)  # new run re-registers
    reg.unregister_family("obs_gen_fam", old)  # old run's teardown
    assert "obs_gen_fam" in reg.families()
    reg.unregister_family("obs_gen_fam", new)


def test_builtin_families_are_registered():
    # The six-family migration: the process singletons registered at
    # import; per-run families (liveness, pbt, injected_faults) register
    # when their owners exist.
    import distributed_machine_learning_tpu.data.pipeline  # noqa: F401

    fams = obs.get_registry().families()
    for name in ("checkpoint", "compile", "host_input"):
        assert name in fams, fams
    with chaos.active(chaos.FaultPlan(seed=1)):
        assert "injected_faults" in obs.get_registry().families()
    assert "injected_faults" not in obs.get_registry().families()


def test_aggregate_scalars_sums_across_sources():
    agg = obs.aggregate_scalars({
        "w1": {"a/x": 1, "a/y": 2.5, "skip": "str"},
        "w2": {"a/x": 3},
    })
    assert agg == {"a/x": 4, "a/y": 2.5}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_and_ordered():
    rec = obs.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("tick", {"i": i})
    events = rec.events()
    assert len(events) == 8
    assert [e["detail"]["i"] for e in events] == list(range(12, 20))


def test_flight_mirror_survives_without_dump(tmp_path):
    mirror = str(tmp_path / "mirror.jsonl")
    rec = obs.FlightRecorder(capacity=4, mirror_path=mirror)
    rec.record("phase", {"name": "claim"})
    rec.record("phase", {"name": "execute"})
    lines = [json.loads(ln) for ln in open(mirror) if ln.strip()]
    assert [ln["detail"]["name"] for ln in lines] == ["claim", "execute"]


def test_dump_includes_ring_spans_and_registry(tmp_path):
    obs.configure(trace_dir=str(tmp_path / "tr"), dump_dir=str(tmp_path))
    obs.event("before_dump", {"k": 1})
    with obs.span("open_phase", {"trial_id": "t0"}):
        path = obs.dump_flight_recorder("unit", extra={"why": "test"})
    assert path and os.path.exists(path)
    payload = json.load(open(path))
    assert payload["reason"] == "unit"
    assert any(e["kind"] == "before_dump" for e in payload["events"])
    assert any(
        stack and stack[-1]["name"] == "open_phase"
        for stack in payload["span_stacks"].values()
    )
    assert "families" in payload["registry"]
    assert payload["extra"] == {"why": "test"}


def test_dump_without_destination_is_noop():
    assert obs.dump_flight_recorder("nowhere") is None


# ---------------------------------------------------------------------------
# tracing core
# ---------------------------------------------------------------------------


def test_span_nesting_and_context(tmp_path):
    obs.configure(trace_dir=str(tmp_path), label="unit")
    with obs.span("outer", {"trial_id": "t1"}) as outer:
        assert obs.current_context() == outer.context
        with obs.span("inner") as inner:
            assert inner.parent_id == outer.span_id
            assert inner.trace_id == outer.trace_id
    obs.flush()
    records = obs.get_tracer().records()
    by_name = {r["name"]: r for r in records}
    assert by_name["inner"]["args"]["parent_id"] == (
        by_name["outer"]["args"]["span_id"]
    )
    # inner landed first (ended first), both in the JSONL sink
    lines = open(obs.get_tracer().path).read().strip().splitlines()
    assert len(lines) == 2


def test_explicit_parent_crosses_threads(tmp_path):
    obs.configure(trace_dir=str(tmp_path), label="unit")
    with obs.span("request") as req:
        ctx = obs.current_context()

        def worker():
            with obs.span("flush", parent=ctx):
                pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    records = {r["name"]: r for r in obs.get_tracer().records()}
    assert records["flush"]["args"]["parent_id"] == req.span_id


def test_exception_marks_span_and_unwinds(tmp_path):
    obs.configure(trace_dir=str(tmp_path), label="unit")
    with pytest.raises(ValueError):
        with obs.span("failing"):
            raise ValueError("boom")
    rec = obs.get_tracer().records()[-1]
    assert rec["name"] == "failing" and rec["args"]["error"] == "ValueError"
    assert obs.current_context() is None  # stack unwound


def test_merge_and_chrome_schema(tmp_path):
    obs.configure(trace_dir=str(tmp_path), label="unit")
    with obs.span("a", {"trial_id": "t"}):
        obs.add_complete("compile.backend", 0.001)
    obs.flush()
    out = obs.merge_trace_dir(str(tmp_path))
    data = json.load(open(out))
    assert set(data) >= {"traceEvents", "displayTimeUnit"}
    complete = [e for e in data["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"a", "compile.backend"}
    for e in complete:
        for key in ("name", "ph", "ts", "dur", "pid", "tid", "args"):
            assert key in e, e
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    # metadata events label process lanes
    assert any(e["ph"] == "M" for e in data["traceEvents"])


# ---------------------------------------------------------------------------
# disabled-path overhead guard (the always-on-instrumentation contract)
# ---------------------------------------------------------------------------


def test_disabled_span_perf_guard():
    assert not obs.tracing_enabled()
    # Best of three: CI machines stutter; a regression shifts ALL runs.
    best = min(
        (obs.disabled_path_overhead(iters=50_000) for _ in range(3)),
        key=lambda r: r["ns_per_span"],
    )
    strict = os.environ.get("DML_OBS_PERF_GUARD") == "1"
    ns_budget = 800.0 if strict else 1500.0
    assert best["ns_per_span"] <= ns_budget, best
    # "allocates nothing per span": net allocated blocks must not scale
    # with the span count (tiny constant jitter from interned state ok).
    assert best["net_blocks"] <= 16, best


# ---------------------------------------------------------------------------
# e2e: thread + process executors (acceptance criterion)
# ---------------------------------------------------------------------------


def _ckpt_trainable(config):
    for _ in range(2):
        tune.report(loss=config["x"] ** 2, checkpoint={"w": [1.0]})


def _assert_trial_trace(root, expect_multi_pid):
    data = json.load(open(os.path.join(root, "trace", "trace.json")))
    evs = [e for e in data["traceEvents"] if e.get("ph") == "X"]
    trace_ids = {e["args"].get("trace_id") for e in evs}
    assert len(trace_ids) == 1, trace_ids  # consistent across processes
    names = {e["name"] for e in evs}
    assert {"experiment", "trial.dispatch", "trial", "epoch",
            "ckpt.save"} <= names, names
    if expect_multi_pid:
        assert len({e["pid"] for e in evs}) >= 2
    exp = next(e for e in evs if e["name"] == "experiment")
    dispatch = {
        e["args"]["span_id"]: e for e in evs
        if e["name"] == "trial.dispatch"
    }
    trials = [e for e in evs if e["name"] == "trial"]
    assert len(dispatch) == 2 and len(trials) == 2
    for t in trials:
        parent = dispatch[t["args"]["parent_id"]]
        assert parent["args"]["parent_id"] == exp["args"]["span_id"]
        assert parent["args"]["trial_id"] == t["args"]["trial_id"]
    # epochs nest under their trial spans
    trial_ids = {t["args"]["span_id"] for t in trials}
    epochs = [e for e in evs if e["name"] == "epoch"]
    assert epochs and all(
        e["args"]["parent_id"] in trial_ids for e in epochs
    )
    return data


def test_traced_run_thread_executor_merges_chrome_trace(tmp_results):
    analysis = tune.run(
        _real_epoch_trainable, {"lr": tune.uniform(1e-4, 1e-2)},
        metric="loss", mode="min", num_samples=2,
        storage_path=tmp_results, name="obs_thread", verbose=0,
        trace=True,
    )
    root = os.path.join(tmp_results, "obs_thread")
    _assert_trial_trace(root, expect_multi_pid=False)
    state = json.load(open(os.path.join(root, "experiment_state.json")))
    assert state["obs"]["spans_recorded"] > 0
    assert state["obs"]["trace"].endswith("trace.json")
    assert analysis.best_config is not None
    # tracing is OFF again after the run
    assert not obs.tracing_enabled()


def _real_epoch_trainable(config):
    # Uses obs.span the way the built-in trainables do, so the e2e sees
    # driver->trial->epoch->ckpt spans without needing a jax model.
    for epoch in range(2):
        with obs.span("epoch", {"epoch": epoch}):
            time.sleep(0.01)
        tune.report(loss=config["lr"], checkpoint={"w": [1.0]})


def test_traced_run_process_executor_spans_cross_processes(tmp_results):
    tune.run(
        _real_epoch_trainable, {"lr": tune.uniform(1e-4, 1e-2)},
        metric="loss", mode="min", num_samples=2,
        storage_path=tmp_results, name="obs_proc", verbose=0,
        trace=True, trial_executor="process",
    )
    root = os.path.join(tmp_results, "obs_proc")
    _assert_trial_trace(root, expect_multi_pid=True)


# ---------------------------------------------------------------------------
# e2e: flight-recorder dump on stall names the hang site (acceptance)
# ---------------------------------------------------------------------------


def _hang_trainable(config):
    tune.report(loss=1.0)
    with obs.span("epoch", {"epoch": 1, "where": "hang_site"}):
        time.sleep(1.1)  # > deadline; no heartbeat — a silent dispatch
    tune.report(loss=0.5)


def test_stall_dumps_flight_recorder_with_hang_site(tmp_results):
    tune.run(
        _hang_trainable, {"x": tune.uniform(0, 1)},
        metric="loss", mode="min", num_samples=1,
        storage_path=tmp_results, name="obs_stall", verbose=0,
        trace=True, progress_deadline_s=0.3, progress_grace_s=0.2,
    )
    root = os.path.join(tmp_results, "obs_stall")
    dumps = glob.glob(os.path.join(root, "flightrec_*_stall_*.json"))
    assert dumps, os.listdir(root)
    payload = json.load(open(dumps[0]))
    # The tail of the dump carries the hang site: the stalled trial
    # thread's innermost open span is the epoch it hung inside.
    hang_stacks = [
        s for s in payload["span_stacks"].values()
        if s and s[-1]["name"] == "epoch"
        and s[-1]["attrs"].get("where") == "hang_site"
    ]
    assert hang_stacks, payload["span_stacks"]
    # ... and the ring shows the watchdog seeing the silence.
    kinds = [e["kind"] for e in payload["events"]]
    assert "watchdog_stall" in kinds
    state = json.load(open(os.path.join(root, "experiment_state.json")))
    assert state["liveness"]["stalls_detected"] >= 1
    assert state["obs"]["flight_dumps"] >= 1


# ---------------------------------------------------------------------------
# e2e: cluster dispatch carries the trace across the frame boundary
# ---------------------------------------------------------------------------


def _worker_env():
    keep = [
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    ]
    return {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.pathsep.join([TESTS_DIR] + keep),
    }


def test_cluster_dispatch_trace_ids_and_head_aggregation(tmp_path):
    procs, addrs = start_local_workers(1, slots=2, env=_worker_env())
    try:
        run_distributed(
            "cluster_trainables:quadratic_trial",
            {"x": tune.uniform(0.0, 6.0), "epochs": 3},
            metric="loss", mode="min", num_samples=2,
            workers=addrs, storage_path=str(tmp_path), name="obs_cluster",
            verbose=0, trace=True,
        )
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                p.kill()
    root = os.path.join(str(tmp_path), "obs_cluster")
    data = json.load(open(os.path.join(root, "trace", "trace.json")))
    evs = [e for e in data["traceEvents"] if e.get("ph") == "X"]
    assert len({e["args"].get("trace_id") for e in evs}) == 1
    assert len({e["pid"] for e in evs}) >= 2  # head + worker process
    dispatch = {
        e["args"]["span_id"]: e for e in evs
        if e["name"] == "trial.dispatch"
    }
    trials = [e for e in evs if e["name"] == "trial"]
    assert trials, sorted({e["name"] for e in evs})
    for t in trials:  # worker trial spans parent under head dispatch spans
        assert t["args"]["parent_id"] in dispatch
    # Head-node aggregation: the workers' registry snapshots summed.
    state = json.load(open(os.path.join(root, "experiment_state.json")))
    cluster = state["obs"]["cluster"]
    assert state["obs"]["cluster_workers"] == 1
    assert any(k.startswith("checkpoint/") for k in cluster), cluster
    assert cluster.get("obs/spans_recorded", 0) > 0


# ---------------------------------------------------------------------------
# chaos: a telemetry failure must never fail the run (satellite)
# ---------------------------------------------------------------------------


def _quadratic(config):
    for _ in range(3):
        tune.report(loss=(config["x"] - 2.0) ** 2, checkpoint={"x": [1.0]})


def test_trace_export_faults_absorbed_same_best_trial(tmp_results):
    space = {"x": tune.uniform(0.0, 6.0)}
    control = tune.run(
        _quadratic, space, metric="loss", mode="min", num_samples=4,
        seed=11, storage_path=tmp_results, name="obs_chaos_control",
        verbose=0, trace=True,
    )
    with chaos.active(chaos.FaultPlan(seed=3, trace_export_error_rate=1.0)):
        faulted = tune.run(
            _quadratic, space, metric="loss", mode="min", num_samples=4,
            seed=11, storage_path=tmp_results, name="obs_chaos_faulted",
            verbose=0, trace=True,
        )
        fired = chaos.active_plan().snapshot()
    assert faulted.best_config == control.best_config
    assert fired.get("trace_export_errors", 0) >= 1
    state = json.load(open(os.path.join(
        tmp_results, "obs_chaos_faulted", "experiment_state.json"
    )))
    # every export failed (rate 1.0): counted, run unaffected, no merge
    assert state["obs"]["export_failures"] >= 1
    assert "trace" not in state["obs"]


# ---------------------------------------------------------------------------
# CLI: export / merge / summarize (satellite)
# ---------------------------------------------------------------------------


def test_trace_cli_export_and_summarize(tmp_results, capsys):
    from distributed_machine_learning_tpu.__main__ import main

    tune.run(
        _real_epoch_trainable, {"lr": tune.uniform(1e-4, 1e-2)},
        metric="loss", mode="min", num_samples=2,
        storage_path=tmp_results, name="obs_cli", verbose=0, trace=True,
    )
    root = os.path.join(tmp_results, "obs_cli")
    main(["trace", "export", root])
    out_path = capsys.readouterr().out.strip()
    assert out_path.endswith("trace.json") and os.path.exists(out_path)

    state = json.load(open(os.path.join(root, "experiment_state.json")))
    trial_id = state["trials"][0]["trial_id"]
    main(["trace", "summarize", root, "--trial", trial_id, "--json"])
    doc = json.loads(capsys.readouterr().out)
    phases = {r["phase"]: r for r in doc["phases"]}
    assert {"trial.dispatch", "trial", "epoch"} <= set(phases), phases
    assert phases["epoch"]["count"] == 2
    assert phases["trial"]["total_ms"] >= phases["epoch"]["total_ms"]

    merged = os.path.join(root, "merged_again.json")
    main(["trace", "merge", root, "-o", merged])
    capsys.readouterr()
    assert json.load(open(merged))["traceEvents"]

    with pytest.raises(SystemExit) as exc:
        main(["trace", "export", os.path.join(root, "nothing_here")])
    assert exc.value.code == 1


# ---------------------------------------------------------------------------
# bench probe forensics (satellite): wedge -> trace_dump in the artifact
# ---------------------------------------------------------------------------


def test_probe_wedge_ships_flight_forensics(monkeypatch):
    import bench

    bench._PROBE_MEMO.clear()

    def fake_run_child(args, env, timeout_s):
        assert args == ["--child", "probe"]
        # The child got crash-safe forensics wiring from the parent...
        mirror = env["DML_OBS_FLIGHT_MIRROR"]
        assert env["DML_OBS_DUMP_DIR"]
        # ...and behaves like a wedge: reaches backend_claim, then hangs
        # until the SIGTERM (mirror survives, no dump = handler never ran).
        with open(mirror, "a") as f:
            for phase in ("jax_import", "backend_claim"):
                f.write(json.dumps({
                    "t_mono": 0.0, "t_wall": 0.0, "tid": 1,
                    "kind": "probe_phase", "detail": {"phase": phase},
                }) + "\n")
        return 124, "", "Platform 'axon' wedged at 0xdead", True

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    probe_info = {"attempts": []}
    probe_ok, tunnel_ok = bench._probe_tpu(
        lambda msg: None, probe_info, [(5, 0), (5, 0), (5, 0)]
    )
    bench._PROBE_MEMO.clear()
    assert not probe_ok and tunnel_ok
    sig = probe_info["probe_wedge_signature"]
    assert sig["attempts"] == 2  # repeated-wedge fast path intact
    assert os.path.exists(sig["trace_dump"])
    phases = [e["detail"]["phase"] for e in sig["trace_dump_tail"]]
    assert phases == ["jax_import", "backend_claim"], phases
