"""Package CLI (`python -m distributed_machine_learning_tpu`)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=120):
    # Overwriting PYTHONPATH with the repo root also drops the image's
    # .axon_site entry, so the child never claims the TPU tunnel.
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    })
    return subprocess.run(
        [sys.executable, "-m", "distributed_machine_learning_tpu"] + args,
        env=env, capture_output=True, text=True, timeout=timeout,
    )


def test_info_prints_device_summary():
    proc = _run(["info"])
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["backend"] == "cpu"
    assert out["local_devices"] == 4
    assert out["process_count"] == 1


def test_help_and_unknown_command():
    proc = _run(["--help"], timeout=30)
    assert proc.returncode == 0
    assert "worker" in proc.stdout and "info" in proc.stdout
    proc = _run(["frobnicate"], timeout=30)
    assert proc.returncode == 2


def test_worker_help_forwards_to_cluster_cli():
    proc = _run(["worker", "--help"], timeout=30)
    assert proc.returncode == 0
    assert "--join" in proc.stdout and "--port" in proc.stdout


def test_export_orbax_subcommand(tmp_path):
    import numpy as np
    import pytest

    pytest.importorskip("orbax.checkpoint")  # optional dependency

    from distributed_machine_learning_tpu.tune.checkpoint import (
        checkpoint_path,
        save_checkpoint,
    )

    src = checkpoint_path(str(tmp_path), 1)
    save_checkpoint(src, {"params": {"w": np.ones(3)}})
    out_dir = str(tmp_path / "orbax_out")
    proc = _run(["export-orbax", src, out_dir])
    assert proc.returncode == 0, proc.stderr
    assert "exported" in proc.stdout
    assert os.path.isdir(out_dir)

    proc = _run(["export-orbax", "only-one-arg"], timeout=60)
    assert proc.returncode == 2


def test_export_orbax_friendly_errors(tmp_path):
    import pytest

    pytest.importorskip("orbax.checkpoint")
    # Missing checkpoint: one-line error, exit 1, no traceback.
    proc = _run(["export-orbax", str(tmp_path / "nope.msgpack"),
                 str(tmp_path / "o")], timeout=60)
    assert proc.returncode == 1
    assert "error:" in proc.stderr and "Traceback" not in proc.stderr


def test_probe_subcommand_cpu():
    """probe: bounded accelerator health check. On the CPU test platform it
    reports an executed computation and exits 1 (no accelerator)."""
    import json

    proc = _run(["probe", "--timeout", "90"])
    assert proc.returncode == 1, proc.stderr
    res = json.loads(proc.stdout.strip().splitlines()[-1])
    assert res["platform"] == "cpu" and res["executed"] is True


def test_probe_times_out_on_wedged_backend():
    """A backend that hangs at init must yield exit 124 within the bound,
    not a hung shell (the failure mode bench.py's probe exists for)."""
    import json
    import subprocess

    env = dict(os.environ)
    env.update({"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})
    # Wedge the child deterministically: a sitecustomize that sleeps at
    # interpreter start stands in for a dead tunnel claim.
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        with open(os.path.join(d, "sitecustomize.py"), "w") as f:
            # Sleep only in `python -c` children (the probe's worker), not
            # in the `-m` CLI parent that shares this PYTHONPATH.
            f.write(
                "import sys, time\n"
                "if sys.argv and sys.argv[0] == '-c':\n"
                "    time.sleep(120)\n"
            )
        env["PYTHONPATH"] = d + os.pathsep + REPO
        proc = subprocess.run(
            [sys.executable, "-m", "distributed_machine_learning_tpu",
             "probe", "--timeout", "5"],
            env=env, capture_output=True, text=True, timeout=120,
        )
    assert proc.returncode == 124, (proc.stdout, proc.stderr)
    res = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "hung" in res["error"]


def test_probe_crashed_child_is_not_cpu_only():
    """A crashing probe child exits 2 — distinct from 'healthy CPU-only'
    (1), so pod-health scripts can't misread a broken env (code review
    r4)."""
    import subprocess
    import tempfile

    env = dict(os.environ)
    with tempfile.TemporaryDirectory() as d:
        with open(os.path.join(d, "sitecustomize.py"), "w") as f:
            # site.py swallows ordinary exceptions from sitecustomize;
            # os._exit reliably kills the child like a hard crash would.
            f.write(
                "import sys, os\n"
                "if sys.argv and sys.argv[0] == '-c':\n"
                "    sys.stderr.write('broken backend install\\n')\n"
                "    os._exit(17)\n"
            )
        env["PYTHONPATH"] = d + os.pathsep + REPO
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "distributed_machine_learning_tpu",
             "probe", "--timeout", "60"],
            env=env, capture_output=True, text=True, timeout=120,
        )
    assert proc.returncode == 2, (proc.stdout, proc.stderr)


def test_analyze_subcommand(tmp_path):
    """`analyze` rehydrates a finished experiment: recorded metric/mode are
    picked up from experiment_state.json, --json is machine-readable, and
    the human view prints the final table."""
    from distributed_machine_learning_tpu import tune

    def trainable(config):
        for _ in range(2):
            tune.report(loss=config["x"] ** 2)

    tune.run(
        trainable, {"x": tune.uniform(1.0, 2.0)},
        metric="loss", mode="min", num_samples=3,
        storage_path=str(tmp_path), name="cli_exp", verbose=0,
    )
    root = os.path.join(str(tmp_path), "cli_exp")

    proc = _run(["analyze", root, "--json"])
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["metric"] == "loss" and out["mode"] == "min"  # recorded
    assert out["num_terminated"] == 3
    assert 1.0 <= out["best_config"]["x"] <= 2.0
    assert "wall_clock_s" in out and "device_utilization" in out

    proc = _run(["analyze", root])
    assert proc.returncode == 0, proc.stderr
    assert "Final result" in proc.stdout
    assert "best loss:" in proc.stdout
    assert "best config:" in proc.stdout
    # Progress/runtime restored from the state file, not zeroed.
    for line in proc.stdout.splitlines():
        if line.strip().startswith("trial_"):
            cols = line.split()
            assert cols[2] == "2", line   # iter column: 2 reports

    # Typo'd PATH is diagnosed first — never "pass --metric" advice.
    proc = _run(["analyze", str(tmp_path / "nope")])
    assert proc.returncode == 1
    assert "no experiment directory" in proc.stderr

    # Typo'd METRIC errors in both output modes (exit 0 with an all-dash
    # table would pass scripted `analyze && ...` checks silently).
    for extra in (["--json"], []):
        proc = _run(["analyze", root, "--metric", "typo_metric"] + extra)
        assert proc.returncode == 1, extra
        assert "typo_metric" in proc.stderr
        assert "Traceback" not in proc.stderr


def test_loop_status_subcommand(tmp_path):
    """`loop status` reads a journal (file or out_dir), prints the
    episode trail + counters, flags open episodes, and --json emits the
    raw documents; unreadable paths get a one-liner, not a stack dump."""
    doc = {
        "episode": 2, "state": "retraining", "trace_id": "abc123",
        "data": {"warm_start": "/ckpts/gen_0007"},
        "history": [
            {"state": "detected", "at_unix": 100.0},
            {"state": "retraining", "at_unix": 101.5,
             "warm_start": "/ckpts/gen_0007"},
        ],
        "completed_episodes": 1, "promotions": 1, "rollbacks": 0,
    }
    with open(tmp_path / "loop.json", "w") as f:
        json.dump(doc, f)
    with open(tmp_path / "experiment_state.json", "w") as f:
        json.dump({"loop": {"episodes": 2, "promotions": 1,
                            "rollbacks": 0, "resumes": 1,
                            "gate_rejects": 0, "aborts": 0}}, f)

    # Directory form resolves to <dir>/loop.json.
    proc = _run(["loop", "status", str(tmp_path)], timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "episode 2: retraining" in proc.stdout
    assert "OPEN" in proc.stdout          # non-terminal -> resume hint
    assert "abc123" in proc.stdout
    assert "warm_start=/ckpts/gen_0007" in proc.stdout
    assert "resumes=1" in proc.stdout

    # --json round-trips both documents.
    proc = _run(["loop", "status", str(tmp_path / "loop.json"), "--json"],
                timeout=60)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["journal"]["state"] == "retraining"
    assert out["counters"]["resumes"] == 1

    proc = _run(["loop", "status", str(tmp_path / "missing.json")],
                timeout=60)
    assert proc.returncode == 1
    assert "cannot read journal" in proc.stderr
    assert "Traceback" not in proc.stderr


def test_store_subcommand(tmp_path):
    """`store {stats,verify,gc}`: dedup-aware stats, dry-run-by-default
    GC (nothing deleted until --run), and verify exiting 1 with the
    corrupt digest named."""
    from distributed_machine_learning_tpu import store as store_lib

    root = str(tmp_path / ".cas")
    cas = store_lib.get_store(root)
    keep = cas.put_blob(b"keep me" * 64)
    cas.put_blob(b"keep me" * 64)  # dedup hit, no new blob
    cas.put_blob(b"drop me" * 64)  # never referenced -> GC fodder
    manifest = cas.put_manifest({
        "kind": "demo",
        store_lib.MANIFEST_CHUNKS_KEY: [keep],
    })
    cas.set_ref("demo-ref", manifest)

    proc = _run(["store", "stats", root, "--json"], timeout=60)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["blobs"] == 3  # keep + drop + the manifest blob
    assert out["refs"] == 1

    # A served directory resolves to its .cas sibling, same as writers.
    proc = _run(["store", "stats", str(tmp_path), "--json"], timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["root"].endswith(".cas")

    # GC defaults to a dry run: reports the unreachable blob, deletes
    # nothing.
    proc = _run(["store", "gc", root, "--json"], timeout=60)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["dry_run"] is True
    assert out["collected"] == 1 and out["retained"] == 2
    assert json.loads(_run(["store", "stats", root, "--json"],
                           timeout=60).stdout)["blobs"] == 3

    proc = _run(["store", "gc", root, "--run", "--json"], timeout=60)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["dry_run"] is False and out["collected"] == 1
    assert json.loads(_run(["store", "stats", root, "--json"],
                           timeout=60).stdout)["blobs"] == 2

    proc = _run(["store", "verify", root], timeout=60)
    assert proc.returncode == 0, proc.stderr

    # Bit-rot a live blob: verify names the digest and exits 1.
    blob_path = os.path.join(root, "blobs", keep[:2], keep)
    with open(blob_path, "wb") as f:
        f.write(b"rotten")
    proc = _run(["store", "verify", root], timeout=60)
    assert proc.returncode == 1
    assert keep in proc.stdout
    assert "Traceback" not in proc.stderr
