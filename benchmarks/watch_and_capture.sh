#!/bin/bash
# Wait for the TPU tunnel to answer a probe, then run the full capture
# session (run_all_tpu.sh). For bad-tunnel days: leave this running and
# the measurement session starts itself the moment the backend recovers.
#
#   bash benchmarks/watch_and_capture.sh [max_wait_minutes]
#
# Each probe claims the tunnel briefly (one claimant at a time — do not
# run this alongside another TPU job). Probe cadence ~2.5 min keeps the
# claim pressure low; a wedged far side ignores us either way.
set -u
max_min=${1:-300}
cd "$(dirname "$0")/.."
deadline=$(( $(date +%s) + max_min * 60 ))

while [ "$(date +%s)" -lt "$deadline" ]; do
  echo "[watch] probe at $(date +%H:%M:%S)"
  # probe exits 0 only when an accelerator executed a computation. The
  # outer bound must exceed the probe's own worst case (80s child timeout
  # + 15s SIGTERM + 15s SIGINT grace, PLUS cold package import before the
  # probe even starts) or we'd kill the probe mid-escalation and orphan a
  # tunnel-holding grandchild.
  if timeout --signal=TERM 180 python -m distributed_machine_learning_tpu \
      probe --timeout 80 >/dev/null 2>&1; then
    echo "[watch] tunnel is back at $(date +%H:%M:%S); starting capture"
    # Let the far side release the probe's claim before the capture's
    # first child claims (a claim raced against a lagging release can
    # wedge — the very failure this script exists to recover from).
    sleep 15
    exec bash "${CAPTURE_SCRIPT:-benchmarks/run_all_tpu.sh}"
  fi
  sleep 150
done
echo "[watch] gave up after ${max_min} minutes"
exit 1
