# Shared helpers for the one-shot TPU capture scripts (run_all_tpu.sh,
# run_round5_remainder.sh). Sourced, not executed. Requires $out to be
# set by the caller. These encode the tunnel discipline from the
# 2026-07-31 wedge postmortem (benchmarks/RESULTS.md): SIGTERM-only
# (never SIGKILL a tunnel holder), 15s cool-down between claimants so a
# claim never races a lagging far-side release, and a bounded probe gate
# so a dead tunnel skips a step in ~3 min instead of burning its whole
# timeout hung at backend init.

run() {
  name=$1; shift
  echo "=== $name: $* (log: $out/$name.log)" | tee -a "$out/summary.txt"
  timeout --signal=TERM --kill-after=0 "$TIMEOUT" "$@" \
    > "$out/$name.log" 2>&1
  rc=$?
  tail -3 "$out/$name.log" | tee -a "$out/summary.txt"
  echo "--- $name rc=$rc" | tee -a "$out/summary.txt"
  sleep 15
}

# Probe gate for tunnel-claiming steps: rc=0 only when an accelerator
# executed a computation (rc=1 healthy-but-CPU-only, rc=124 hung).
gate() {
  name=$1
  timeout --signal=TERM 180 python -m distributed_machine_learning_tpu \
    probe --timeout 80 >/dev/null 2>&1
  rc=$?
  if [ "$rc" -eq 0 ]; then
    sleep 15  # let the probe's claim release before the step claims
    return 0
  fi
  echo "--- $name SKIPPED: probe rc=$rc (0=chip, 1=cpu-only, 124=hung)" \
    | tee -a "$out/summary.txt"
  return 1
}
