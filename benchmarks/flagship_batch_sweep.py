"""One-off: flagship MFU vs batch size on the real chip.

The driver-artifact flagship (bench.py FLAGSHIP) measured MFU 0.243 at
batch 8 (2026-07-31 capture).  The MXU wants a bigger M dimension; this
sweeps batch {8, 16, 32} at the same shape to find the best-MFU config
before promoting it to FLAGSHIP.  Run from the repo root with the default
(tunnel) env; one claimant at a time (memory: axon-tunnel-environment).
"""

import functools
import json
import sys
import time

sys.path.insert(0, ".")


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from bench import _median  # same timing statistic as FLAGSHIP's capture
    from distributed_machine_learning_tpu.models import build_model
    from distributed_machine_learning_tpu.ops.flops import (
        device_peak_flops,
        train_step_flops,
    )

    S, F = 2048, 16
    cfg = {
        "model": "transformer", "d_model": 512, "num_heads": 8,
        "num_layers": 4, "dim_feedforward": 2048, "dropout": 0.0,
        "attention_type": "flash", "compute_dtype": "bfloat16",
        "max_seq_length": S,
    }
    peak = device_peak_flops(jax.devices()[0], compute_dtype="bfloat16")
    for B in (8, 16, 32):
        model = build_model(dict(cfg))
        rng = jax.random.PRNGKey(0)
        # dmlint: disable=blocking-transfer-in-loop fresh shape per swept batch size (one staging per config, off the timed path)
        x = jnp.asarray(np.random.RandomState(0).randn(B, S, F), jnp.float32)
        # dmlint: disable=blocking-transfer-in-loop fresh shape per swept batch size (off the timed path)
        y = jnp.asarray(np.random.RandomState(1).randn(B, 1), jnp.float32)
        params = model.init({"params": rng, "dropout": rng}, x,
                            deterministic=True)["params"]
        tx = optax.adam(1e-3)
        opt_state = tx.init(params)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, x, y):
            def loss_of(p):
                preds = model.apply({"params": p}, x, deterministic=True)
                return jnp.mean((preds.astype(jnp.float32) - y) ** 2)
            loss, grads = jax.value_and_grad(loss_of)(params)
            updates, opt_state2 = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state2, loss

        t0 = time.time()
        params, opt_state, loss = step(params, opt_state, x, y)
        float(loss)
        compile_s = time.time() - t0
        cells = []
        for _ in range(6):
            t0 = time.time()
            for _ in range(5):
                params, opt_state, loss = step(params, opt_state, x, y)
            float(loss)
            cells.append((time.time() - t0) / 5)
        step_s = _median(cells)
        cells.sort()
        flops = train_step_flops(cfg, B, S, F)
        print(json.dumps({
            "batch": B, "step_s": round(step_s, 5),
            "spread": [round(cells[0], 5), round(cells[-1], 5)],
            "compile_s": round(compile_s, 1),
            "mfu": round(flops / step_s / peak, 4) if peak else None,
            "tflops": round(flops / step_s / 1e12, 2),
        }), flush=True)


if __name__ == "__main__":
    main()
