#!/bin/bash
# Remainder of the 2026-08-01 capture session: the two steps the mid-run
# tunnel wedge ate (sharded_resnet, refdata) plus an instrumented re-run
# of the bohb variant with FULL child logs kept
# (DML_BENCH_CHILD_LOG_DIR) so a repeat of the 09:10 UTC stall is
# diagnosable: the kept stderr shows the warmup timestamps and the
# per-30s trial table right up to the wedge. Same discipline as
# run_all_tpu.sh (shared helpers: sequential, SIGTERM-only, cool-down
# between claimants).
set -u
ts=$(date +%H%M%S)
out="/tmp/tpu_r5rem_${ts}"
mkdir -p "$out"
cd "$(dirname "$0")/.."
. benchmarks/_capture_lib.sh
export DML_BENCH_CHILD_LOG_DIR="$out/children"

gate bohb && TIMEOUT=2400 run bohb python bench.py --variant bohb_transformer
gate resnet && TIMEOUT=2400 run resnet python bench.py --variant sharded_resnet
gate refdata && TIMEOUT=1800 run refdata python examples/hpo_reference_data.py
# Fresh full bench last: banks a capture that includes the XL ceiling
# probe (mfu_xl), added after the 08:30 session's suite ran.
gate bench && TIMEOUT=4800 run bench python bench.py

echo "remainder complete: $out" | tee -a "$out/summary.txt"
