#!/bin/bash
# Remainder of the 2026-07-31 capture: the steps the tunnel wedge ate
# (bohb/resnet full-scale variants), the flagship batch-size MFU sweep,
# and one bench.py under the new rng auto default. Same discipline as
# run_all_tpu.sh: sequential, SIGTERM-only, cool-down between claimants.
set -u
ts=$(date +%H%M%S)
out="/tmp/tpu_remainder_${ts}"
mkdir -p "$out"
cd "$(dirname "$0")/.."

run() {
  name=$1; shift
  echo "=== $name: $* (log: $out/$name.log)" | tee -a "$out/summary.txt"
  timeout --signal=TERM --kill-after=0 "$TIMEOUT" "$@" \
    > "$out/$name.log" 2>&1
  rc=$?
  tail -3 "$out/$name.log" | tee -a "$out/summary.txt"
  echo "--- $name rc=$rc" | tee -a "$out/summary.txt"
  sleep 15
}

TIMEOUT=900  run flagship_batch python benchmarks/flagship_batch_sweep.py
TIMEOUT=1800 run variant_resnet python bench.py --variant sharded_resnet
TIMEOUT=2400 run variant_bohb python bench.py --variant bohb_transformer
TIMEOUT=3600 run bench_rbg_default python bench.py

echo "remainder complete: $out" | tee -a "$out/summary.txt"
