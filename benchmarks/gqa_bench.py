"""Microbenchmark: native grouped-query attention vs the repeat path.

Measures the flash Pallas kernel at num_kv_heads in {H, H/2, H/4, 1} two
ways per cell:

* ``native``  — k/v passed at kv_heads (the kernels stream the shared kv
  block per query head; dK/dV accumulate grouped in VMEM scratch);
* ``repeat``  — k/v ``jnp.repeat``-ed to full heads first (what the layer
  did before round 4: the repeated tensor is materialized in HBM, costing
  a write+read of (group-1)/group extra kv bytes plus the memory).

The delta is GQA's kernel-side kv-bandwidth/memory saving (VERDICT r3
next #4 asks for this measured on the chip). Prints one JSON line per
(seq, kv_heads, mode, direction) so runs are diffable.

Run on the TPU:      python benchmarks/gqa_bench.py
Run on CPU (smoke):  JAX_PLATFORMS=cpu python benchmarks/gqa_bench.py --seqs 256 --cells 2 --interpret
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# A CPU smoke run must not claim the single TPU tunnel: the .axon_site
# sitecustomize on PYTHONPATH claims it at interpreter start (and a dead
# tunnel then hangs this process before main() runs). Re-exec clean.
if (
    os.environ.get("JAX_PLATFORMS") == "cpu"
    and ".axon_site" in os.environ.get("PYTHONPATH", "")
):
    _env = dict(os.environ)
    _env["PYTHONPATH"] = os.pathsep.join(
        p for p in _env["PYTHONPATH"].split(os.pathsep)
        if p and ".axon_site" not in p
    )
    os.execve(sys.executable, [sys.executable] + sys.argv, _env)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

# ONE timing harness for every microbench in this directory — the forced
# f32 scalar readback in attention_bench._sync is load-bearing through the
# tunneled backend (RESULTS.md), so it must not fork.
from attention_bench import _sync  # noqa: E402


def measure(fn, args, cells: int, steps: int) -> dict:
    _sync(fn(*args))  # compile outside the timer
    times = []
    for _ in range(cells):
        t0 = time.time()
        for _ in range(steps):
            out = fn(*args)
        _sync(out)
        times.append((time.time() - t0) / steps)
    times.sort()
    return {
        "ms": round(times[len(times) // 2] * 1e3, 3),
        "ms_spread": [round(times[0] * 1e3, 3), round(times[-1] * 1e3, 3)],
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seqs", type=int, nargs="+", default=[2048, 4096])
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--cells", type=int, default=5)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--interpret", action="store_true",
                   help="Pallas interpreter (CPU smoke)")
    args = p.parse_args()

    from distributed_machine_learning_tpu.ops.pallas_attention import (
        flash_attention,
    )

    B, H, D = args.batch, args.heads, args.head_dim
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    dev = jax.devices()[0]
    print(f"# {dev.platform} {getattr(dev, 'device_kind', '?')} "
          f"B{B} H{H} D{D} {args.dtype}", file=sys.stderr)

    for S in args.seqs:
        rng = np.random.default_rng(0)
        # dmlint: disable=blocking-transfer-in-loop fresh shape per swept config (one staging per configuration, off the timed path)
        q = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype)
        kv_counts = sorted({H, H // 2, H // 4, 1} - {0}, reverse=True)
        for Hkv in kv_counts:
            # dmlint: disable=blocking-transfer-in-loop fresh shape per swept config (off the timed path)
            k = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), dtype)
            # dmlint: disable=blocking-transfer-in-loop fresh shape per swept config (off the timed path)
            v = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), dtype)
            group = H // Hkv

            def native_fwd(q, k, v):
                return flash_attention(q, k, v, interpret=args.interpret)

            def repeat_fwd(q, k, v):
                kr = jnp.repeat(k, group, axis=2)
                vr = jnp.repeat(v, group, axis=2)
                return flash_attention(q, kr, vr, interpret=args.interpret)

            def grad_of(fwd):
                return jax.grad(
                    lambda q, k, v: jnp.sum(
                        fwd(q, k, v).astype(jnp.float32) ** 2
                    ),
                    argnums=(0, 1, 2),
                )

            modes = {"native": native_fwd}
            if group > 1:
                modes["repeat"] = repeat_fwd
            for mode, fwd in modes.items():
                fj = jax.jit(fwd)
                row = measure(fj, (q, k, v), args.cells, args.steps)
                print(json.dumps({
                    "seq": S, "kv_heads": Hkv, "mode": mode,
                    "direction": "fwd", **row,
                }), flush=True)
                gj = jax.jit(grad_of(fwd))
                row = measure(gj, (q, k, v), args.cells, args.steps)
                print(json.dumps({
                    "seq": S, "kv_heads": Hkv, "mode": mode,
                    "direction": "fwd+bwd", **row,
                }), flush=True)


if __name__ == "__main__":
    main()
