#!/bin/bash
# One-shot TPU measurement session: run every benchmark sequentially (the
# tunnel admits ONE claimant at a time; see memory/axon-tunnel-environment)
# and tee outputs under /tmp/tpu_capture_<ts>/. Run from the repo root with
# the default (tunnel) environment:
#
#   bash benchmarks/run_all_tpu.sh
#
# Each child python process claims and releases the tunnel itself
# (bench.py re-execs sanitized and spawns tunnel children; the micro-
# benches claim directly). If a step hangs, it is SIGTERMed — never
# SIGKILL, which can take the relay down.
set -u
ts=$(date +%H%M%S)
out="/tmp/tpu_capture_${ts}"
mkdir -p "$out"
cd "$(dirname "$0")/.."
# run()/gate() + the wedge-postmortem tunnel discipline live in the
# shared lib (one place to adjust cool-downs/probe bounds for every
# capture script).
. benchmarks/_capture_lib.sh

# Headline bench first (the driver artifact path): probes, single-claim
# suite (flagship MFU + both-dtype sweeps with warm repeats), torch
# baseline. 4800 > bench.py's own worst case (probe window +
# SUITE_TIMEOUT_S + RESUME_TIMEOUT_S + torch + settle/gaps) so a slow
# run emits its JSON instead of dying to this outer SIGTERM.
TIMEOUT=4800 run bench python bench.py

# Same sweep with threefry dropout streams forced: measures the tax the
# default hardware-RNG ("auto" -> rbg on TPU, ops/rng.py) avoids. Gated:
# the comparison is only interesting on-chip, and bench.py's own probe
# schedule would burn ~8 min against a tunnel that died during the
# previous step.
gate bench_threefry && TIMEOUT=4800 run bench_threefry env DML_BENCH_RNG_IMPL=threefry python bench.py

# GQA kv-bandwidth: native grouped kv vs repeat, fwd and fwd+bwd.
gate gqa && TIMEOUT=1800 run gqa python benchmarks/gqa_bench.py

# Attention kernel sweep (regression-diffable vs RESULTS.md).
gate attn && TIMEOUT=1800 run attn python benchmarks/attention_bench.py

# BASELINE configs 3-5 at full scale (each probes + CPU-falls-back on its
# own, but the gate spares a dead tunnel three more 2-attempt probe
# windows' worth of claim pressure).
gate variant_pbt && TIMEOUT=2400 run variant_pbt python bench.py --variant pbt_cnn
gate variant_bohb && TIMEOUT=2400 run variant_bohb python bench.py --variant bohb_transformer
gate variant_resnet && TIMEOUT=2400 run variant_resnet python bench.py --variant sharded_resnet

# C1 interop on-chip (VERDICT r4 next #8): the full 20-hp driver on a
# generated reference-format {columns, data} .npy pair — 12 trials x 4
# epochs, bounded so the multi-architecture compiles fit one window.
gate refdata && TIMEOUT=1800 run refdata python examples/hpo_reference_data.py

echo "capture complete: $out" | tee -a "$out/summary.txt"
