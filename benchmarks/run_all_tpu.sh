#!/bin/bash
# One-shot TPU measurement session: run every benchmark sequentially (the
# tunnel admits ONE claimant at a time; see memory/axon-tunnel-environment)
# and tee outputs under /tmp/tpu_capture_<ts>/. Run from the repo root with
# the default (tunnel) environment:
#
#   bash benchmarks/run_all_tpu.sh
#
# Each child python process claims and releases the tunnel itself
# (bench.py re-execs sanitized and spawns tunnel children; the micro-
# benches claim directly). If a step hangs, it is SIGTERMed — never
# SIGKILL, which can take the relay down.
set -u
ts=$(date +%H%M%S)
out="/tmp/tpu_capture_${ts}"
mkdir -p "$out"
cd "$(dirname "$0")/.."

run() {
  name=$1; shift
  echo "=== $name: $* (log: $out/$name.log)" | tee -a "$out/summary.txt"
  timeout --signal=TERM --kill-after=0 "$TIMEOUT" "$@" \
    > "$out/$name.log" 2>&1
  rc=$?
  tail -3 "$out/$name.log" | tee -a "$out/summary.txt"
  echo "--- $name rc=$rc" | tee -a "$out/summary.txt"
  # Give the far side time to release the previous claimant's grant
  # before the next step claims (claims raced against a lagging release
  # can wedge — 2026-07-31 postmortem in ../benchmarks/RESULTS.md).
  sleep 15
}

# Headline bench first (the driver artifact path): probes, both-dtype
# sweeps with warm repeats, flagship MFU, torch baseline.
TIMEOUT=3600 run bench python bench.py

# Same sweep with threefry dropout streams forced: measures the tax the
# default hardware-RNG ("auto" -> rbg on TPU, ops/rng.py) avoids.
TIMEOUT=2400 run bench_threefry env DML_BENCH_RNG_IMPL=threefry python bench.py

# GQA kv-bandwidth: native grouped kv vs repeat, fwd and fwd+bwd.
TIMEOUT=1800 run gqa python benchmarks/gqa_bench.py

# Attention kernel sweep (regression-diffable vs RESULTS.md).
TIMEOUT=1800 run attn python benchmarks/attention_bench.py

# BASELINE configs 3-5 at full scale.
TIMEOUT=2400 run variant_pbt python bench.py --variant pbt_cnn
TIMEOUT=2400 run variant_bohb python bench.py --variant bohb_transformer
TIMEOUT=2400 run variant_resnet python bench.py --variant sharded_resnet

echo "capture complete: $out" | tee -a "$out/summary.txt"
