"""Microbenchmark: attention kernels on one chip, across sequence lengths.

Compares the framework's attention implementations (`ops/attention.py`,
`ops/pallas_attention.py`) — XLA softmax ("scaled_dot_product"), the Pallas
flash kernel ("flash"), lax.scan blockwise, and O(n) linear attention — on
forward and forward+backward wall time. Prints one JSON line per
(kernel, seq_len, dtype) so regressions are diffable run to run.

Run on the TPU:      python benchmarks/attention_bench.py
Run on CPU (smoke):  JAX_PLATFORMS=cpu python benchmarks/attention_bench.py --seqs 128 256
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# A CPU smoke run must not claim the single TPU tunnel: the .axon_site
# sitecustomize on PYTHONPATH claims it at interpreter start (and a dead
# tunnel then hangs this process before main() runs). Re-exec clean.
if (
    os.environ.get("JAX_PLATFORMS") == "cpu"
    and ".axon_site" in os.environ.get("PYTHONPATH", "")
):
    _env = dict(os.environ)
    _env["PYTHONPATH"] = os.pathsep.join(
        p for p in _env["PYTHONPATH"].split(os.pathsep)
        if p and ".axon_site" not in p
    )
    os.execve(sys.executable, [sys.executable] + sys.argv, _env)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def build(kernel: str, causal: bool):
    from distributed_machine_learning_tpu.ops.attention import (
        blockwise_attention,
        dot_product_attention,
        linear_attention,
    )

    if kernel == "xla_softmax":
        def fn(q, k, v):
            mask = None
            if causal:
                S = q.shape[1]
                mask = jnp.tril(jnp.ones((S, S), bool))[None, None]
            return dot_product_attention(q, k, v, mask=mask)
        return fn
    if kernel == "flash_pallas":
        from distributed_machine_learning_tpu.ops.pallas_attention import (
            flash_attention,
        )

        return lambda q, k, v: flash_attention(q, k, v, causal=causal)
    if kernel == "blockwise":
        return lambda q, k, v: blockwise_attention(
            q, k, v, block_size=min(256, q.shape[1]), causal=causal
        )
    if kernel == "linear":
        return lambda q, k, v: linear_attention(q, k, v, causal=causal)
    raise ValueError(kernel)


def _sync(out):
    """Force completion with a device->host readback of one scalar.

    ``block_until_ready`` alone is not trustworthy through proxied/tunneled
    backends (observed: it returned immediately and "timed" seq-4096
    attention at an impossible 12,000 TFLOP/s); fetching a value cannot
    complete before the computation has."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def timed(fn, *args, iters=10):
    _sync(fn(*args))  # compile outside the timer
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.time() - t0) / iters


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--seqs", type=int, nargs="+",
                   default=[512, 1024, 2048, 4096])
    p.add_argument("--dtypes", nargs="+", default=["float32", "bfloat16"])
    p.add_argument("--causal", action="store_true")
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args(argv)

    platform = jax.devices()[0].platform
    kernels = ["xla_softmax", "blockwise", "linear"]
    if platform == "tpu":
        kernels.insert(1, "flash_pallas")  # Mosaic compiles on TPU only

    for dtype_name in args.dtypes:
        dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32
        for S in args.seqs:
            rng = np.random.default_rng(0)
            mk = lambda: jnp.asarray(
                rng.normal(size=(args.batch, S, args.heads, args.head_dim)),
                dtype,
            )
            q, k, v = mk(), mk(), mk()
            for kernel in kernels:
                fn = build(kernel, args.causal)
                fwd = jax.jit(fn)

                def loss(q, k, v):
                    return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

                bwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                try:
                    fwd_s = timed(fwd, q, k, v, iters=args.iters)
                    bwd_s = timed(bwd, q, k, v, iters=args.iters)
                except Exception as exc:  # noqa: BLE001 - record, keep going
                    print(json.dumps({
                        "kernel": kernel, "seq": S, "dtype": dtype_name,
                        "error": repr(exc)[:200],
                    }), flush=True)
                    continue
                # Softmax attention fwd FLOPs: 2 matmuls of 2*B*H*S^2*D;
                # causal does ~half (kernels skip fully-masked blocks).
                flops = 4.0 * args.batch * args.heads * S * S * args.head_dim
                if args.causal:
                    flops *= 0.5
                print(json.dumps({
                    "kernel": kernel, "seq": S, "dtype": dtype_name,
                    "platform": platform, "causal": args.causal,
                    "fwd_ms": round(fwd_s * 1e3, 3),
                    "fwd_bwd_ms": round(bwd_s * 1e3, 3),
                    "fwd_tflops": round(flops / fwd_s / 1e12, 2),
                }), flush=True)


if __name__ == "__main__":
    main()
