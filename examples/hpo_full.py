"""Production-parity HPO driver: the reference's full 20-hyperparameter sweep.

Counterpart of `/root/reference/ray-tune-hpo-regression.py:465-480` (C21 in
SURVEY.md §2a): windowed wearable-sensor regression, a custom transformer with
every architecture knob searchable, ASHA early stopping, and Bayesian search —
written in this framework's DSL with the reference's latent bugs fixed:

* ``dim_feedforward`` really is ``d_model x {2,3,4}`` — the reference's
  ``tune.sample_from(lambda: ... tune.choice(...))`` returned a sampler
  object, not an int (`:383`); here ``sample_from`` resolves against the
  sampled config.
* ``d_model % num_heads == 0`` is enforced as a joint ``Constraint`` — the
  reference could sample e.g. d_model=320, heads=32 and crash (never checked).
* ``batch_size`` / ``max_seq_length`` actually take effect (dead knobs in the
  reference: loaders were fixed at batch 32 / window 96, `:456,:446`).
* per-epoch reporting makes ASHA live (the reference reported once at trial
  end, `:373`, so ASHA never cut anything).

The real patient ``.npy`` files are private, so the default data source is
the synthetic glucose-like workload in the same shape; pass ``--features`` /
``--labels`` to run on real ``{columns, data}`` .npy dumps like the
reference's (`:414-418,:423-459`).

Run (CPU dev box):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/hpo_full.py --num-samples 8 --num-epochs 2 --fast
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_machine_learning_tpu import tune  # noqa: E402
from distributed_machine_learning_tpu.data import glucose_like_data  # noqa: E402

# The reference pins its loaders at window length 96 (`:446`); every data
# path in this example produces seq-96 windows.
WINDOW_SEQ_LEN = 96


def build_search_space(args) -> tune.SearchSpace:
    """The reference's 20 hyperparameters (`:379-400`), resolvable + valid."""
    space = {
        "model": "transformer",
        # -- architecture ----------------------------------------------------
        "num_heads": tune.choice([2, 4, 8, 16, 32]),
        "num_layers": tune.choice([2, 4, 6, 8, 12, 16]),
        "d_model": tune.choice([64, 128, 192, 256, 320, 512]),
        "dim_feedforward": tune.sample_from(
            lambda cfg: cfg["d_model"] * cfg["ff_multiplier"]
        ),
        "ff_multiplier": tune.choice([2, 3, 4]),
        "attention_type": tune.choice(
            ["scaled_dot_product", "multi_head_attention", "linear_attention"]
        ),
        "key_dim_scaling": tune.choice([1.0, 0.5, 0.25]),
        # Beyond the reference's 20: grouped-query attention (kv heads per
        # query group — the kernels consume grouped kv natively) and rotary
        # vs additive positions. kv_divider picks a divisor of num_heads so
        # every sample is valid.
        "num_kv_heads": tune.sample_from(
            lambda cfg: max(1, cfg["num_heads"] // cfg["kv_divider"])
        ),
        "kv_divider": tune.choice([1, 2, 4]),
        "position_encoding": tune.choice(["sincos", "rope"]),
        "attn_kernel_size": tune.choice([3, 5, 7]),
        "depthwise_separable_conv": tune.choice([True, False]),
        "shared_weights": tune.choice([True, False]),
        "stochastic_depth_rate": tune.uniform(0.0, 0.2),
        "dropout": tune.loguniform(0.01, 0.5),
        "max_seq_length": tune.choice([50, 100, 200, 500, 1000, 2000]),
        # -- optimization ----------------------------------------------------
        "learning_rate": tune.loguniform(1e-5, 5e-2),
        "weight_decay": tune.loguniform(1e-6, 1e-1),
        "batch_size": tune.choice([16, 32, 64, 128, 256]),
        "warmup_steps": tune.choice([100, 500, 1000, 2000]),
        "total_steps": tune.choice([10_000, 20_000, 50_000, 100_000]),
        "loss_function": tune.choice(["mse", "mae", "huber", "mape"]),
        "gradient_clipping": tune.uniform(0.0, 1.0),
        "optimizer": tune.choice(["adam", "adamw", "sgd", "rmsprop"]),
        # -- budget ----------------------------------------------------------
        "num_epochs": args.num_epochs,
        "seed": tune.randint(0, 1_000_000),
    }
    if args.fast:  # minute-scale smoke settings for dev boxes / CI
        space.update({
            "num_heads": tune.choice([2, 4]),
            "num_layers": tune.choice([1, 2]),
            "d_model": tune.choice([32, 64]),
            "max_seq_length": WINDOW_SEQ_LEN,
            "batch_size": 32,
            "warmup_steps": 10,
        })
        space.pop("total_steps")  # let the trainable derive it from epochs
    return tune.SearchSpace(
        space,
        constraints=[
            tune.Constraint(
                lambda cfg: cfg["d_model"] % cfg["num_heads"] == 0,
                description="d_model divisible by num_heads",
            ),
            tune.Constraint(
                # The depthwise FF path projects back to d_model; its kernel
                # size must fit the sequence.
                lambda cfg: cfg["attn_kernel_size"] < cfg["max_seq_length"],
                description="attention kernel fits the sequence",
            ),
            tune.Constraint(
                # The PE table must cover the data's window length (96 for
                # the reference-format window grid): the reference crashes
                # on this combo too (its torch PE slices pe[:, :seq] from a
                # max_seq_length-long table, a broadcast error when seq >
                # max_seq_length) — here the sampler simply never proposes
                # it, so a bounded run spends its whole budget on valid
                # trials.
                lambda cfg: (cfg["position_encoding"] != "sincos"
                             or cfg["max_seq_length"] >= WINDOW_SEQ_LEN),
                description="sincos PE table covers the data window length",
            ),
        ],
    )


def load_data(args):
    if args.features and args.labels:
        from distributed_machine_learning_tpu.data import (
            load_dataframe_from_npy,
            make_regression_dataset,
        )

        return make_regression_dataset(
            load_dataframe_from_npy(args.features),
            load_dataframe_from_npy(args.labels),
            interval=WINDOW_SEQ_LEN,
            stride=WINDOW_SEQ_LEN,
        )
    return glucose_like_data(
        num_steps=args.data_steps, num_features=args.num_features
    )


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--features", help=".npy features dump (optional)")
    parser.add_argument("--labels", help=".npy labels dump (optional)")
    parser.add_argument("--num-samples", type=int, default=50)
    parser.add_argument("--num-epochs", type=int, default=20)
    parser.add_argument("--data-steps", type=int, default=50_000)
    parser.add_argument("--num-features", type=int, default=16)
    parser.add_argument("--storage", default="~/dml_tpu_results")
    parser.add_argument("--fast", action="store_true",
                        help="shrink arch choices to minute-scale")
    parser.add_argument("--search", choices=["bayesopt", "random", "tpe"],
                        default="bayesopt")
    args = parser.parse_args(argv)

    train, val = load_data(args)
    space = build_search_space(args)

    if args.search == "bayesopt":
        # GP over the continuous subspace, random for categoricals — the
        # deliberate mixed-space strategy (the reference's BayesOptSearch
        # could not handle its own categorical-heavy space).
        from distributed_machine_learning_tpu.tune.search import BayesOptSearch

        search_alg = BayesOptSearch(random_search_steps=10)
    elif args.search == "tpe":
        from distributed_machine_learning_tpu.tune.search import TPESearch

        search_alg = TPESearch()
    else:
        search_alg = None

    analysis = tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        space,
        metric="validation_mape",
        mode="min",
        num_samples=args.num_samples,
        scheduler=tune.ASHAScheduler(
            max_t=args.num_epochs, grace_period=1, reduction_factor=2
        ),
        search_alg=search_alg,
        storage_path=args.storage,
        name="hpo_full",
    )
    print("Best hyperparameters found:\n", analysis.best_config)
    print("Best validation_mape:",
          analysis.best_result.get("validation_mape"))
    return analysis


if __name__ == "__main__":
    main()
