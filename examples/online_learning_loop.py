"""Self-healing serving driver: drift -> retrain -> guarded promotion.

The loop/ subsystem end to end on CPU virtual devices (ISSUE 17):

1. a briefly-trained incumbent is exported and served
   (:class:`serve.PredictionServer`), with a :class:`loop.DriftMonitor`
   attached to the serving plane — every ``/predict`` request feeds the
   monitor one feature summary and one prediction summary;
2. clean traffic scores quiet; then the WORLD changes
   (``chaos.apply_drift``: a covariate shift plus a label shift) and the
   monitor's windowed robust-z trips its debounced trigger;
3. ``controller.poll()`` consumes the trigger and runs one journaled
   episode: warm-start fine-tune on the drifted window, holdout quality
   gate, zero-downtime hot swap, probation over LIVE traffic ->
   ``promoted`` — and the drift baseline re-learns the new normal;
4. a deliberately-broken candidate (params scaled 8x) then goes through
   the SAME guard (``promote_with_probation`` — dmlint DML019 flags any
   promotion that bypasses it): probation catches the regression and
   ``serve/swap.rollback`` restores the retained prior, zero compiles;
5. acceptance: zero requests dropped, zero serving-path compiles across
   BOTH promotions and the rollback, journal terminal states + /metrics
   counters printed.

Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/online_learning_loop.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from distributed_machine_learning_tpu import chaos, loop, serve  # noqa: E402
from distributed_machine_learning_tpu.models import build_model  # noqa: E402
from distributed_machine_learning_tpu.serve.export import (  # noqa: E402
    BUNDLE_VERSION,
    write_bundle,
)
from distributed_machine_learning_tpu.tune._regression_program import (  # noqa: E402
    detect_call_convention,
)

SEQ, FEAT = 4, 3
_W = np.array([0.7, -0.4, 1.1], np.float32)
CONFIG = {"model": "mlp", "hidden_sizes": [8], "seed": 3}

# The world after step 2: a feature shift the incumbent never saw, plus a
# label shift so retraining is genuinely necessary (not just re-centering).
DRIFT = {"at_request": 0, "feature_shift": 2.5,
         "label_scale": 1.0, "label_shift": 0.5, "seed": 11}


def make_xy(n, seed, drifted=False):
    r = np.random.default_rng(seed)
    x = r.standard_normal((n, SEQ, FEAT)).astype(np.float32)
    y = (x[:, -2:, :] @ _W).mean(axis=1, keepdims=True)
    if drifted:
        x, y = chaos.apply_drift(DRIFT, x, y)
    return x.astype(np.float32), y.astype(np.float32)


def data_fn(kind):
    """The controller's labeled-feedback windows — post-drift world."""
    seeds = {"train": 100, "holdout": 200, "probation": 300}
    return make_xy(48, seeds[kind], drifted=True)


def _get(url):
    return json.loads(urllib.request.urlopen(url).read())


def feed(base, n, seed0, drifted=False):
    """``n`` POST /predict requests; returns (mean served MAPE, sent)."""
    apes, sent = [], 0
    for i in range(n):
        xb, yb = make_xy(4, seed0 + i, drifted)
        req = urllib.request.Request(
            f"{base}/predict",
            data=json.dumps({"instances": xb.tolist()}).encode(),
            headers={"Content-Type": "application/json"},
        )
        preds = np.asarray(
            json.loads(urllib.request.urlopen(req).read())["predictions"],
            np.float32,
        )
        sent += 1
        apes.append(float(np.mean(
            np.abs(yb - preds.reshape(yb.shape)) / (np.abs(yb) + 1e-8)
        )))
    return float(np.mean(apes)), sent


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--storage", default=None,
                        help="loop root (default: a temp dir)")
    args = parser.parse_args(argv)
    root = args.storage or tempfile.mkdtemp(prefix="dml_tpu_loop_")

    # -- 1. incumbent: brief fit on the pre-drift world, export, serve -------
    x, y = make_xy(64, 1)
    model = build_model(CONFIG)
    probe, _ = detect_call_convention(model, x[:1])
    variables = {"params": probe["params"]}
    if "batch_stats" in probe:
        variables["batch_stats"] = probe["batch_stats"]
    variables, info = loop.fine_tune(
        CONFIG, variables, x, y, epochs=8, learning_rate=0.05, seed=0
    )
    incumbent_dir = os.path.join(root, "incumbent")
    write_bundle(incumbent_dir, {
        "bundle_version": BUNDLE_VERSION, "config": CONFIG,
        "precision": "f32",
    }, variables)
    server = serve.PredictionServer(
        serve.load_bundle(incumbent_dir), port=0, num_replicas=2,
        max_bucket=16,
    )
    server.warmup(make_xy(1, 0)[0])
    host, port = server.start()
    base = f"http://{host}:{port}"
    print(f"serving incumbent at {base} (val_mape={info['val_mape']:.3f})")

    # -- 2. wire the loop ------------------------------------------------------
    drift = loop.DriftMonitor(window=24, z_threshold=4.0, sustain=4)
    server.metrics.attach_drift(drift)
    controller = loop.SelfHealingController(
        server, loop.LoopJournal(os.path.join(root, "loop.json")),
        drift, data_fn, root,
        loop.LoopConfig(retrain_epochs=5, probation_batches=4),
    )
    total_sent = 0

    # -- 3. quiet traffic, then the world shifts -------------------------------
    clean_mape, sent = feed(base, 40, seed0=1000)
    total_sent += sent
    assert controller.poll() is None, "stationary traffic must not trigger"
    drift_mape, sent = feed(base, 40, seed0=2000, drifted=True)
    total_sent += sent
    m = _get(f"{base}/metrics")
    print(f"drift: served MAPE {clean_mape:.3f} -> {drift_mape:.3f}, "
          f"scores={{features: {m['drift']['score_features']}, "
          f"predictions: {m['drift']['score_predictions']}}}, "
          f"triggers={m['drift']['triggers']}")
    assert m["drift"]["triggers"] == 1

    # -- 4. one journaled episode: retrain -> gate -> swap -> probation --------
    outcome = controller.poll()
    assert outcome is not None and outcome["state"] == "promoted", outcome
    healed_mape, sent = feed(base, 40, seed0=3000, drifted=True)
    total_sent += sent
    print(f"episode {outcome['episode']}: {outcome['state']} "
          f"(probation MAPE {outcome['probation_mape']:.3f} vs incumbent "
          f"{outcome['incumbent_mape']:.3f}); served MAPE now "
          f"{healed_mape:.3f}")
    assert healed_mape < drift_mape

    # -- 5. a broken candidate through the SAME guard -> auto-rollback ---------
    import jax

    bad = dict(variables)
    bad["params"] = jax.tree.map(
        lambda a: np.asarray(a) * 8.0, variables["params"]
    )
    bad_dir = os.path.join(root, "bad_candidate")
    write_bundle(bad_dir, {
        "bundle_version": BUNDLE_VERSION, "config": CONFIG,
        "precision": "f32",
    }, bad)
    verdict = controller.promote_with_probation(bad_dir)
    assert verdict["state"] == "rolled_back", verdict
    after_mape, sent = feed(base, 20, seed0=4000, drifted=True)
    total_sent += sent
    print(f"bad candidate: {verdict['state']} (probation MAPE "
          f"{verdict['probation_mape']:.3f} > threshold "
          f"{verdict['threshold']:.3f}); served MAPE back to "
          f"{after_mape:.3f}")

    # -- 6. acceptance ---------------------------------------------------------
    metrics = _get(f"{base}/metrics")
    state = json.load(open(os.path.join(root, "experiment_state.json")))
    print(json.dumps({
        "requests_sent": total_sent,
        "requests_total": metrics["requests_total"],
        "swaps_total": metrics["swap"]["swaps_total"],
        "rollbacks_total": metrics["swap"]["rollbacks_total"],
        "swap_history_depth": metrics["swap"]["history_depth"],
        "new_programs_since_warmup":
            metrics["compile"]["new_programs_since_warmup"],
        "loop": {k: state["loop"][k] for k in
                 ("episodes", "promotions", "rollbacks", "gate_rejects")},
    }, indent=2))
    assert metrics["requests_total"] == total_sent, "dropped requests"
    assert metrics["compile"]["new_programs_since_warmup"] == 0, (
        "a promotion or rollback compiled on the serving path"
    )
    assert state["loop"]["promotions"] == 1
    assert state["loop"]["rollbacks"] == 1
    controller.close()
    drift.close()
    server.close()
    print("OK: drift healed by a journaled retrain episode; a regressing "
          "candidate was auto-rolled-back; zero drops, zero compiles")


if __name__ == "__main__":
    main()
