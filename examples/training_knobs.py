"""Round-up of the training knobs: warm start, stoppers, mixed precision,
gradient accumulation, RoPE/GQA — one sweep using them all.

Run (CPU mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/training_knobs.py

On TPU drop the overrides; set compute_dtype="bfloat16" for MXU-bound
model sizes (measured 1.4-1.6x at d_model >= 512 — benchmarks/RESULTS.md;
tiny models are faster in f32).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_machine_learning_tpu import tune  # noqa: E402
from distributed_machine_learning_tpu.data import (  # noqa: E402
    dummy_regression_data,
)


def main():
    train, val = dummy_regression_data(
        num_samples=512, seq_len=24, num_features=8
    )
    analysis = tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        {
            "model": "transformer",
            "d_model": tune.choice([16, 32]),
            "num_heads": 4,
            # GQA as a searchable knob: full MHA vs grouped vs multi-query.
            "num_kv_heads": tune.choice([1, 2, 4]),
            "num_layers": 2,
            "dim_feedforward": tune.sample_from(
                lambda cfg: cfg["d_model"] * 2
            ),
            # RoPE: relative positions, no max-length table.
            "position_encoding": "rope",
            "optimizer": tune.choice(["adamw", "lion"]),
            "learning_rate": tune.loguniform(1e-4, 1e-2),
            # 4x the effective batch at 1x the activation memory.
            "accumulate_grad_batches": 4,
            "num_epochs": 10,
            "batch_size": 16,
        },
        metric="validation_loss",
        mode="min",
        num_samples=8,
        # Known-good config runs first; the searcher learns from it.
        points_to_evaluate=[
            {"d_model": 32, "num_kv_heads": 4, "optimizer": "adamw",
             "learning_rate": 3e-3}
        ],
        # Converged trials stop early — scheduler-independent.
        stop=tune.TrialPlateauStopper(
            "validation_loss", std=1e-3, num_results=3, grace_period=3
        ),
        scheduler=tune.ASHAScheduler(
            max_t=10, grace_period=2, reduction_factor=2
        ),
        callbacks=[tune.TensorBoardCallback()],  # per-trial TB runs
        storage_path=os.environ.get("DML_RESULTS", "/tmp/dml_examples"),
        name="training_knobs",
        verbose=1,
    )
    print("best config:", {
        k: analysis.best_config[k]
        for k in ("d_model", "num_kv_heads", "optimizer", "learning_rate")
    })
    print("best validation_loss:",
          round(analysis.best_result["validation_loss"], 4))
    model, variables = analysis.best_model()
    preds = model.apply(variables, val.x[:4], deterministic=True)
    print("reloaded best model, preds:", preds.shape)


if __name__ == "__main__":
    main()
