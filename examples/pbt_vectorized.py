"""Vectorized PBT/PB2 driver: one population, one chip, exploit = one gather.

BASELINE.json config 3 requires PBT exercising checkpoint mutate/restore;
``tune.run`` covers the stop-and-respawn variant.  This driver shows the
TPU-shaped one: the vmapped population IS the PBT population, exploit copies
top-quantile rows' params + optimizer state into bottom-quantile rows with a
single device-side gather, and explore rewrites per-row learning-rate /
weight-decay inside the injected optimizer hyperparams — no respawns, no
checkpoint round-trips, no recompiles.  Combined here with multi-epoch
dispatch (one round trip per perturbation interval) and population
checkpointing (``resume=True`` continues after a preemption).  Pass
``--scheduler pb2`` to swap PBT's random perturbation for PB2's GP-UCB
explore (its GP observes every epoch via the same population stream).

Run (CPU virtual devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pbt_vectorized.py
On a TPU host, drop the env overrides; add ``--devices all`` to shard the
population over every local chip.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_machine_learning_tpu import tune  # noqa: E402
from distributed_machine_learning_tpu.data import glucose_like_data  # noqa: E402


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--num-samples", type=int, default=16)
    parser.add_argument("--num-epochs", type=int, default=12)
    parser.add_argument("--perturbation-interval", type=int, default=3)
    parser.add_argument("--storage", default="~/dml_tpu_results")
    parser.add_argument("--name", default=None)
    parser.add_argument("--resume", action="store_true",
                        help="continue an interrupted run (requires --name)")
    parser.add_argument("--devices", default="one",
                        choices=["one", "all"],
                        help="'all' shards the population over local devices")
    parser.add_argument("--scheduler", default="pbt",
                        choices=["pbt", "pb2"],
                        help="pb2 = GP-UCB explore (Population Based "
                             "Bandits) instead of PBT's random walk")
    args = parser.parse_args(argv)

    import jax

    train, val = glucose_like_data(num_steps=60_000, num_features=16)
    space = {
        "model": "transformer",
        "d_model": 64,
        "num_heads": 4,
        "num_layers": 2,
        "dim_feedforward": 128,
        "dropout": 0.1,
        "learning_rate": tune.loguniform(1e-5, 1e-2),
        "weight_decay": tune.loguniform(1e-6, 1e-3),
        "seed": tune.randint(0, 1_000_000),
        "num_epochs": args.num_epochs,
        "batch_size": 32,
        "max_seq_length": 128,
        "loss_function": "mse",
    }
    sched_cls = tune.PB2 if args.scheduler == "pb2" else (
        tune.PopulationBasedTraining
    )
    pbt = sched_cls(
        perturbation_interval=args.perturbation_interval,
        hyperparam_mutations={
            "learning_rate": tune.loguniform(1e-5, 1e-2),
            "weight_decay": tune.loguniform(1e-6, 1e-3),
        },
        quantile_fraction=0.25,
        seed=1,
    )
    analysis = tune.run_vectorized(
        space,
        train_data=train,
        val_data=val,
        metric="validation_mape",
        mode="min",
        num_samples=args.num_samples,
        scheduler=pbt,
        devices=jax.local_devices() if args.devices == "all" else None,
        epochs_per_dispatch=args.perturbation_interval,
        checkpoint_every_epochs=args.perturbation_interval,
        storage_path=args.storage,
        name=args.name or f"pbt_vec_{int(time.time())}",
        resume=args.resume,
    )
    exploits = sum(
        1 for t in analysis.trials for r in t.results
        if "pbt_exploited_from" in r
    )
    print(f"perturbations: {pbt.debug_state()['num_perturbations']} "
          f"({exploits} exploit records)")
    print("best config:", analysis.best_config)
    print("best validation_mape:",
          round(analysis.best_result["validation_mape"], 4))
    return analysis


if __name__ == "__main__":
    main()
