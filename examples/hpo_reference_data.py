"""C1 interop, end to end: the full 20-hp driver on a REFERENCE-FORMAT file.

The reference trains from pickled ``{columns, data}`` ``.npy`` dumps with its
literal 81-column schema (`/root/reference/config.py:2-78`, loaded at
`ray-tune-hpo-regression.py:414-418`).  The real patient files are private,
so this script synthesizes a byte-compatible pair from raw sensor streams via
``build_feature_frame(schema="reference")`` — the reference's exact column
names, 9-window grid, and ``Is_Weekend`` flag — writes them exactly as the
reference stores its own, and then runs ``examples/hpo_full.py``'s driver on
them UNCHANGED (``get_dataset`` auto-detects the schema).  Proves a reference
user can point this framework at their existing data files and run the full
production sweep (VERDICT r4 next #8).

Bounded by default (12 trials x 4 epochs) so it lands inside one tunnel
window on-chip; prints ONE JSON line with trials/hour + best config.

Run (CPU dev box):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/hpo_reference_data.py --num-samples 4 \
        --num-epochs 2 --rows-windows 24 --fast
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (the package)
sys.path.insert(0, _HERE)                   # examples/ (for `import hpo_full`)

if (os.environ.get("JAX_PLATFORMS") == "cpu"
        and ".axon_site" in os.environ.get("PYTHONPATH", "")
        and not os.environ.get("_DML_REEXECED")):
    # An explicit CPU run on the TPU image must not import jax under the
    # .axon_site sitecustomize: the axon plugin registers anyway, hangs at
    # tunnel init, and can wedge the one-claimant tunnel.  Re-exec with
    # the repo's sanitized CPU env (same helper bench.py's children use).
    from __graft_entry__ import _sanitized_cpu_env

    env = dict(_sanitized_cpu_env(8), _DML_REEXECED="1")
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def generate_reference_pair(out_dir: str, windows: int, patient: str) -> None:
    """Write ``<patient>_features.npy`` / ``<patient>_labels.npy`` in the
    reference's storage format (pickled {columns, data} dicts)."""
    import numpy as np
    import pandas as pd

    from distributed_machine_learning_tpu.data.features import (
        LABEL_COLUMN,
        build_feature_frame,
    )

    rows = 96 * windows  # one label window per 96 minutes (interval=96)
    rng = np.random.RandomState(11)
    idx = pd.date_range("2024-01-05 22:00", periods=rows, freq="min")
    raw = pd.DataFrame(
        {
            "heart_rate": 70 + 8 * rng.randn(rows),
            "sleep": (rng.rand(rows) > 0.6).astype(float),
            "intensity": rng.rand(rows) * 3,
            "steps": rng.poisson(5, rows).astype(float),
        },
        index=idx,
    )
    frame = build_feature_frame(raw, schema="reference")
    # Learnable target: a smooth function of the raw channels plus noise —
    # glucose-like positive values so validation_mape is well-behaved.
    hr = raw["heart_rate"].to_numpy()
    labels = pd.DataFrame({
        LABEL_COLUMN: (100.0 + 0.8 * (hr - 70.0)
                       + 6.0 * raw["intensity"].to_numpy()
                       + 2.0 * rng.randn(rows)).astype(np.float32)
    })

    os.makedirs(out_dir, exist_ok=True)
    for df, name in ((frame, "features"), (labels, "labels")):
        np.save(
            os.path.join(out_dir, f"{patient}_{name}.npy"),
            {"columns": list(df.columns),
             "data": df.to_numpy(dtype=np.float32)},
        )


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out-dir", default="/tmp/dml_reference_data")
    parser.add_argument("--patient", default="MMCS0002")
    parser.add_argument("--rows-windows", type=int, default=200,
                        help="number of 96-minute label windows to generate")
    parser.add_argument("--num-samples", type=int, default=12)
    parser.add_argument("--num-epochs", type=int, default=4)
    parser.add_argument("--storage", default="/tmp/dml_reference_results")
    parser.add_argument("--search", default="bayesopt",
                        choices=["bayesopt", "random", "tpe"])
    parser.add_argument("--fast", action="store_true",
                        help="shrink arch choices to minute-scale")
    args = parser.parse_args(argv)

    generate_reference_pair(args.out_dir, args.rows_windows, args.patient)
    features = os.path.join(args.out_dir, f"{args.patient}_features.npy")
    labels = os.path.join(args.out_dir, f"{args.patient}_labels.npy")

    import hpo_full

    t0 = time.time()
    analysis = hpo_full.main([
        "--features", features,
        "--labels", labels,
        "--num-samples", str(args.num_samples),
        "--num-epochs", str(args.num_epochs),
        "--storage", args.storage,
        "--search", args.search,
    ] + (["--fast"] if args.fast else []))
    wall = time.time() - t0

    import jax

    done = analysis.num_terminated()
    print(json.dumps({
        "metric": "hpo_full_reference_format_npy",
        "trials_per_hour": round(done * 3600.0 / wall, 2),
        "done": done,
        "wall_s": round(wall, 1),
        "backend": jax.devices()[0].platform,
        "best_validation_mape": analysis.best_result.get("validation_mape"),
        "best_config": {
            k: v for k, v in (analysis.best_config or {}).items()
            if isinstance(v, (int, float, str))
        },
        "data": {"features": features, "labels": labels,
                 "windows": args.rows_windows, "schema": "reference-81col"},
    }))
    return analysis


if __name__ == "__main__":
    main()
