"""Quantized serving driver: sweep -> f32 export -> int8 sibling ->
zero-downtime promotion.

The quant/ pipeline end to end on CPU virtual devices (ISSUE 16):

1. a small HPO sweep finds a best trial;
2. ``serve.export_bundle`` freezes the f32 winner, then
   ``quant.quantize_bundle`` writes its calibrated int8 sibling — the
   manifest records ``precision``, the per-leaf scale digest, the byte
   compression, and the MEASURED ``quality_delta_mape`` vs the parent;
3. a :class:`serve.PredictionServer` starts on the f32 bundle, warms its
   bucket grid, and takes traffic;
4. ``hot_swap`` promotes the int8 sibling mid-traffic — the int8
   dequant-fused programs warm off-path, no request drops, and
   ``/metrics`` flips to ``precision: int8`` with the audited delta;
5. acceptance: zero programs compiled after warmup (across BOTH
   precisions — precision is program identity, the swap pre-compiled
   the int8 grid), and the served int8 answers stay within the
   manifest's delta of the f32 answers on the calibration batch.

Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/serve_quantized.py --requests 200
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from distributed_machine_learning_tpu import quant, serve, tune  # noqa: E402
from distributed_machine_learning_tpu.data import (  # noqa: E402
    dummy_regression_data,
)


def _get(url):
    return json.loads(urllib.request.urlopen(url).read())


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--requests", type=int, default=200)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--num-samples", type=int, default=4)
    parser.add_argument("--storage", default=None,
                        help="experiment/bundle root (default: a temp dir)")
    args = parser.parse_args(argv)
    root = args.storage or tempfile.mkdtemp(prefix="dml_tpu_quant_")

    # -- 1. sweep ------------------------------------------------------------
    train, val = dummy_regression_data(
        num_samples=512, seq_len=12, num_features=6, seed=3
    )
    analysis = tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        {"model": "mlp",
         "hidden_sizes": tune.choice([[32], [64], [32, 16]]),
         "learning_rate": tune.loguniform(1e-3, 1e-2),
         "num_epochs": 3, "batch_size": 64, "seed": 0},
        metric="validation_loss", mode="min",
        num_samples=args.num_samples,
        storage_path=root, name="quant_sweep", verbose=0,
    )
    print(f"best trial: {analysis.best_trial.trial_id}")

    # -- 2. export f32 parent + calibrated int8 sibling ----------------------
    f32_dir = os.path.join(root, "bundle_f32")
    serve.export_bundle(analysis, f32_dir)
    calibration = np.asarray(val.x[:64], np.float32)
    int8_dir = quant.quantize_bundle(
        f32_dir, os.path.join(root, "bundle_int8"), "int8", calibration
    )
    b8 = serve.load_bundle(int8_dir)
    q = b8.manifest["quant"]
    print(f"int8 sibling: {int8_dir}")
    print(f"  compression={q['compression']}x  "
          f"quality_delta_mape={b8.quality_delta_mape:.5f}  "
          f"quantized_leaves={q['quantized_leaves']}/{q['total_leaves']}")

    # -- 3. serve the f32 parent ---------------------------------------------
    bundle = serve.load_bundle(f32_dir)
    server = serve.PredictionServer(
        bundle, port=0, num_replicas=args.replicas,
        max_batch_size=32, max_bucket=64, max_queue=512,
    )
    server.warmup(np.asarray(val.x[:1], np.float32))
    host, port = server.start()
    base = f"http://{host}:{port}"
    print(f"serving at {base} "
          f"(precision={_get(f'{base}/metrics')['precision']})")

    # -- 4. traffic, with the promotion landing mid-stream -------------------
    rng = np.random.default_rng(0)
    sizes = rng.choice([1, 2, 3, 5, 8, 13], size=args.requests)
    swap_at = args.requests // 2
    for i, n in enumerate(sizes):
        if i == swap_at:
            # dmlint: disable=unguarded-promotion quality is pre-audited, not probation-watched: the manifest carries the MEASURED quality_delta_mape vs the f32 parent and step 5 re-verifies the served delta against it
            event = serve.hot_swap(
                server.replicas, b8,
                sample=np.asarray(val.x[:1], np.float32),
            )
            print(f"  promoted int8 mid-traffic: "
                  f"swapped={event['replicas_swapped']} "
                  f"in {event['duration_s']}s")
        x = np.asarray(val.x[:n], np.float32)
        req = urllib.request.Request(
            f"{base}/predict",
            data=json.dumps({"instances": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"},
        )
        body = json.loads(urllib.request.urlopen(req).read())
        assert len(body["predictions"]) == int(n)

    # -- 5. acceptance --------------------------------------------------------
    metrics = _get(f"{base}/metrics")
    print(json.dumps({
        "precision": metrics["precision"],
        "quality_delta_mape": metrics["quality_delta_mape"],
        "requests": metrics["requests_total"],
        "latency_ms_p99": metrics["latency_ms_p99"],
        "swaps_total": metrics["swap"]["swaps_total"],
        "new_programs_since_warmup":
            metrics["compile"]["new_programs_since_warmup"],
    }, indent=2))
    assert metrics["precision"] == "int8"
    assert metrics["compile"]["new_programs_since_warmup"] == 0, (
        "traffic compiled a program — the swap should have warmed the "
        "int8 grid off-path"
    )
    # Quality: served int8 vs served-era f32 on the calibration batch
    # stays within the manifest's measured delta (plus fusion margin).
    f32_pred = serve.InferenceEngine(bundle, max_bucket=64).predict(
        calibration
    )
    int8_pred = server.replicas.predict(calibration)
    mape = float(np.mean(
        np.abs(int8_pred - f32_pred) / (np.abs(f32_pred) + 1e-8)
    ))
    bound = b8.quality_delta_mape * 1.5 + 1e-3
    print(f"served int8 vs f32 MAPE: {mape:.5f} "
          f"(manifest delta {b8.quality_delta_mape:.5f}, bound {bound:.5f})")
    assert mape <= bound
    server.close()
    print("OK: promoted to int8 with zero drops, zero compiles, "
          "bounded quality delta")


if __name__ == "__main__":
    main()
