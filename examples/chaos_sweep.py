"""Chaos smoke: an HPO sweep that survives injected faults, provably.

Runs the same tiny sweep twice — once clean, once under a seeded
``chaos.FaultPlan`` injecting transient storage write failures, one
corrupted checkpoint, and two trial crashes — and checks both runs pick
the SAME best config.  Then serves the winner on two replicas and kills
one mid-traffic to show failover + the circuit breaker recovering.

Runs on virtual CPU devices (see README):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/chaos_sweep.py
"""

import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_machine_learning_tpu import chaos, serve, tune  # noqa: E402
from distributed_machine_learning_tpu.data import dummy_regression_data


def run_sweep(storage, name):
    train, val = dummy_regression_data(
        num_samples=200, seq_len=8, num_features=4
    )
    return tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        {
            "model": "mlp",
            "hidden_sizes": (32,),
            "learning_rate": tune.loguniform(1e-3, 1e-1),
            "num_epochs": 5,
            "batch_size": 32,
            "lr_schedule": "constant",
        },
        metric="validation_loss",
        mode="min",
        num_samples=6,
        max_failures=2,
        seed=0,
        storage_path=storage,
        name=name,
        verbose=0,
    ), val


def main():
    storage = tempfile.mkdtemp(prefix="chaos_sweep_")

    print("== fault-free sweep ==")
    baseline, val = run_sweep(storage, "fault_free")
    print(f"best: {baseline.best_trial.trial_id} "
          f"loss={baseline.best_result['validation_loss']:.5f}")

    print("\n== same sweep under injected faults ==")
    plan = chaos.FaultPlan(
        seed=7,
        write_error_rate=0.15,                       # flaky shared storage
        trial_crashes=[("trial_00001", 4),           # preemptions
                       ("trial_00003", 3)],
        corrupt_path_substrings=[                    # bitrot on a restore
            "trial_00001/checkpoints/ckpt_000003.msgpack"
        ],
    )
    with chaos.active(plan):
        chaotic, _ = run_sweep(storage, "faulted")
    print(f"best: {chaotic.best_trial.trial_id} "
          f"loss={chaotic.best_result['validation_loss']:.5f}")
    print(f"injected: {plan.snapshot()}")
    same = chaotic.best_config == baseline.best_config
    print(f"same best config as fault-free run: {same}")
    assert same, "chaos run diverged from the fault-free run"

    print("\n== serve the winner, kill a replica mid-traffic ==")
    bundle_dir = f"{storage}/bundle"
    baseline.export_bundle(bundle_dir)
    serve_plan = chaos.FaultPlan(seed=4, replica_kills=[(25, -1)])
    srv = serve.PredictionServer(
        serve.load_bundle(bundle_dir), port=0, num_replicas=2,
        max_latency_ms=10, max_bucket=16,
        breaker_failure_threshold=1, breaker_recovery_s=0.2,
        fault_plan=serve_plan,
    )
    x = np.asarray(val.x[:4], np.float32)
    srv.warmup(x[:1])
    srv.start()
    ok = 0
    for _ in range(60):
        deadline = time.time() + 10.0
        while True:
            try:
                srv.replicas.predict(x, timeout=5.0)
                ok += 1
                break
            except Exception:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)
    stats = srv.replicas.breaker_stats()
    print(f"answered {ok}/60 requests; kills="
          f"{serve_plan.snapshot().get('replica_kills', 0)}, "
          f"breaker opens={stats['opens_total']}, "
          f"restarts={srv.replicas.restarts}")
    srv.close()
    assert ok == 60, "some requests were never answered"
    print("\nchaos smoke passed")


if __name__ == "__main__":
    main()
