"""In-device PBT sweep: the whole population trains, ranks, exploits, and
explores inside ONE compiled program (ISSUE 9).

Where ``examples/pbt_vectorized.py`` shows device-side exploit with a host
round-trip per perturbation interval, this driver shows the generation
scan: ``pbt_mode="compiled"`` (the ``"auto"`` default picks it whenever
the scheduler allows) folds quantile ranking, the exploit gather, and the
PRNG-driven lr/wd explore into a ``lax.scan`` over generations — host
dispatches for the whole sweep drop from ``num_epochs/interval`` to
``ceil(num_epochs/chunk)``, typically **one**.  The script prints the
``experiment_state.json["pbt"]`` counter block (mode, generations,
exploits, explores, host_dispatches) so you can see the in-device proof.

``--objective quality_latency_params`` turns on multi-objective exploit
ranking: the quality metric is scalarized by measured step latency and
eval_shape-priced parameter count, every record carries the scalarized
``pbt_objective`` metric, and passing ``--select-objective`` makes
best-trial selection use it — the winning row is then the best
*deployable* model, not merely the most accurate.

Run (CPU):
    JAX_PLATFORMS=cpu python examples/pbt_sweep.py
On a TPU host, drop the override; the same program compiles for the MXU.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_machine_learning_tpu import tune  # noqa: E402
from distributed_machine_learning_tpu.data import glucose_like_data  # noqa: E402


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--num-samples", type=int, default=16)
    parser.add_argument("--num-epochs", type=int, default=12)
    parser.add_argument("--perturbation-interval", type=int, default=3)
    parser.add_argument("--pbt-mode", default="compiled",
                        choices=["auto", "compiled", "boundary"],
                        help="boundary = the per-interval host round-trip "
                             "(same decisions, bit for bit — for A/B "
                             "debugging)")
    parser.add_argument("--objective", default="quality",
                        choices=["quality", "quality_latency",
                                 "quality_latency_params"],
                        help="multi-objective exploit ranking "
                             "(quality x latency x params)")
    parser.add_argument("--select-objective", action="store_true",
                        help="rank the experiment's best trial on the "
                             "scalarized pbt_objective record metric")
    parser.add_argument("--storage", default="~/dml_tpu_results")
    parser.add_argument("--name", default=None)
    args = parser.parse_args(argv)

    train, val = glucose_like_data(num_steps=60_000, num_features=16)
    space = {
        "model": "transformer",
        "d_model": 64,
        "num_heads": 4,
        "num_layers": 2,
        "dim_feedforward": 128,
        "dropout": 0.1,
        "learning_rate": tune.loguniform(1e-5, 1e-2),
        "weight_decay": tune.loguniform(1e-6, 1e-3),
        "seed": tune.randint(0, 1_000_000),
        "num_epochs": args.num_epochs,
        "batch_size": 32,
        "max_seq_length": 128,
        "loss_function": "mse",
    }
    pbt = tune.PopulationBasedTraining(
        metric="validation_mape",
        mode="min",
        perturbation_interval=args.perturbation_interval,
        hyperparam_mutations={
            "learning_rate": tune.loguniform(1e-5, 1e-2),
            "weight_decay": tune.loguniform(1e-6, 1e-3),
        },
        quantile_fraction=0.25,
        seed=1,
        objective=args.objective,
    )
    select_metric = (
        "pbt_objective"
        if args.select_objective and args.objective != "quality"
        else "validation_mape"
    )
    t0 = time.time()
    analysis = tune.run_vectorized(
        space,
        train_data=train,
        val_data=val,
        metric=select_metric,
        mode="min",
        num_samples=args.num_samples,
        scheduler=pbt,
        pbt_mode=args.pbt_mode,
        storage_path=args.storage,
        name=args.name or f"pbt_sweep_{int(time.time())}",
    )
    wall = time.time() - t0
    with open(os.path.join(analysis.root, "experiment_state.json")) as f:
        block = json.load(f).get("pbt", {})
    print(f"\npbt counter block ({wall:.1f}s wall):")
    for key in ("mode", "objective", "interval", "generations", "exploits",
                "explores", "host_dispatches"):
        print(f"  {key:>16}: {block.get(key)}")
    exploits = sum(
        1 for t in analysis.trials for r in t.results
        if "pbt_exploited_from" in r
    )
    print(f"exploit records in the result stream: {exploits}")
    print("best config:", analysis.best_config)
    print(f"best {select_metric}:",
          round(analysis.best_result[select_metric], 4))
    return analysis


if __name__ == "__main__":
    main()
