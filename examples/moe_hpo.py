"""HPO over a mixture-of-experts transformer.

Beyond-reference capability demo: the search space spans dense vs MoE
feed-forwards and the MoE-specific knobs (experts, top-k, capacity), with
ASHA early-stopping the losers. Expert parameter stacks shard over the
``ep`` mesh axis automatically when a trial spans multiple devices
(`parallel/sharding.py`); on single-device trials the same config runs
unsharded — one search space covers both.

Run (8 virtual CPU devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/moe_hpo.py
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_machine_learning_tpu import tune  # noqa: E402
from distributed_machine_learning_tpu.data import dummy_regression_data  # noqa: E402


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--num-samples", type=int, default=8)
    parser.add_argument("--num-epochs", type=int, default=4)
    parser.add_argument("--storage", default="~/dml_tpu_results")
    args = parser.parse_args(argv)

    train, val = dummy_regression_data(
        num_samples=512, seq_len=24, num_features=8, seed=0
    )
    space = {
        "model": "transformer",
        "d_model": tune.choice([32, 64]),
        "num_heads": 4,
        "num_layers": 2,
        "dim_feedforward": tune.sample_from(lambda c: c["d_model"] * 2),
        "feedforward_type": tune.choice(["linear", "moe"]),
        # MoE-only knobs; inert for dense trials (same pattern as the
        # reference's conditional hyperparameters).
        "num_experts": tune.choice([4, 8]),
        "expert_top_k": tune.choice([1, 2]),
        "capacity_factor": 1.25,
        "learning_rate": tune.loguniform(1e-4, 1e-2),
        "weight_decay": tune.loguniform(1e-6, 1e-3),
        "dropout": 0.1,
        "num_epochs": args.num_epochs,
        "batch_size": 64,
        "max_seq_length": 32,
    }
    t0 = time.time()
    analysis = tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        space,
        metric="validation_loss",
        mode="min",
        num_samples=args.num_samples,
        scheduler=tune.ASHAScheduler(
            max_t=args.num_epochs, grace_period=1, reduction_factor=2
        ),
        storage_path=args.storage,
        name=f"moe_hpo_{int(t0)}",
    )
    best = analysis.best_config
    print(f"\nbest config ({time.time() - t0:.0f}s): "
          f"ff={best['feedforward_type']}"
          + (f" experts={best['num_experts']} top_k={best['expert_top_k']}"
             if best["feedforward_type"] == "moe" else "")
          + f" d_model={best['d_model']} lr={best['learning_rate']:.2e}")
    print("best validation_loss:",
          round(analysis.best_result["validation_loss"], 5))


if __name__ == "__main__":
    main()
