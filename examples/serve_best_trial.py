"""Serving driver: sweep -> export the winner -> answer live traffic.

The deployment end of the pipeline (ROADMAP north star: the tuned model
must SERVE, not just exist).  End to end on CPU virtual devices:

1. a small HPO sweep finds a best trial (checkpointed every epoch);
2. ``serve.export_bundle`` freezes it into a self-describing bundle
   (params + config + feature schema);
3. a :class:`serve.PredictionServer` loads the bundle into N device-pinned
   replicas behind the continuous (inflight) batcher, with the replica
   autoscaler armed, pre-compiles the padded-batch bucket grid, and
   serves ``/predict`` ``/healthz`` ``/metrics`` ``/admin/swap``;
4. the driver fires ``--requests`` HTTP requests at mixed batch sizes and
   verifies the acceptance bar: ZERO new compiled programs after warmup
   (every size lands in a warm bucket) and p50/p99 latency in /metrics;
5. a zero-downtime hot swap promotes a re-exported bundle into the live
   ReplicaSet — zero dropped requests, zero serving-path compiles.

Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/serve_best_trial.py --requests 1000
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from distributed_machine_learning_tpu import serve, tune  # noqa: E402
from distributed_machine_learning_tpu.data import (  # noqa: E402
    dummy_regression_data,
)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--requests", type=int, default=1000)
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--num-samples", type=int, default=4)
    parser.add_argument("--max-batch-size", type=int, default=32)
    parser.add_argument("--max-latency-ms", type=float, default=2.0)
    parser.add_argument("--storage", default=None,
                        help="experiment/bundle root (default: a temp dir)")
    args = parser.parse_args(argv)
    root = args.storage or tempfile.mkdtemp(prefix="dml_tpu_serve_")

    # -- 1. sweep ------------------------------------------------------------
    train, val = dummy_regression_data(
        num_samples=512, seq_len=12, num_features=6, seed=3
    )
    analysis = tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        {"model": "mlp",
         "hidden_sizes": tune.choice([[32], [64], [32, 16]]),
         "learning_rate": tune.loguniform(1e-3, 1e-2),
         "num_epochs": 3, "batch_size": 64, "seed": 0},
        metric="validation_loss", mode="min",
        num_samples=args.num_samples,
        storage_path=root, name="serve_sweep", verbose=0,
    )
    print(f"best trial: {analysis.best_trial.trial_id} "
          f"config={analysis.best_config}")

    # -- 2. export -----------------------------------------------------------
    bundle_dir = os.path.join(root, "bundle")
    serve.export_bundle(analysis, bundle_dir)
    bundle = serve.load_bundle(bundle_dir)
    print(f"bundle: {bundle_dir} (model={bundle.model_family}, "
          f"{len(bundle.feature_names)} feature columns)")

    # -- 3. serve ------------------------------------------------------------
    server = serve.PredictionServer(
        bundle, port=0, num_replicas=args.replicas,
        max_batch_size=args.max_batch_size,
        max_latency_ms=args.max_latency_ms, max_bucket=64,
        # Continuous batching is the default; bound the queue and arm the
        # autoscaler so a burst scales out instead of queueing unbounded.
        max_queue=512,
        autoscale=serve.AutoscaleConfig(
            min_replicas=args.replicas,
            max_replicas=args.replicas + 2,
            up_queue_depth=64,
        ),
    )
    warm = server.warmup(np.asarray(val.x[:1], np.float32))
    host, port = server.start()
    base = f"http://{host}:{port}"
    print(f"serving at {base}; warm programs={warm['programs']}")

    # -- 4. traffic + acceptance checks --------------------------------------
    rng = np.random.default_rng(0)
    sizes = rng.choice([1, 2, 3, 5, 8, 13, 21], size=args.requests)
    rows = 0
    for i, n in enumerate(sizes):
        x = np.asarray(val.x[:n], np.float32)
        req = urllib.request.Request(
            f"{base}/predict",
            data=json.dumps({"instances": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"},
        )
        body = json.loads(urllib.request.urlopen(req).read())
        rows += len(body["predictions"])
        if (i + 1) % max(args.requests // 4, 1) == 0:
            print(f"  {i + 1}/{args.requests} requests...")

    metrics = json.loads(urllib.request.urlopen(f"{base}/metrics").read())
    health = json.loads(urllib.request.urlopen(f"{base}/healthz").read())
    print(json.dumps({
        "requests": metrics["requests_total"],
        "rows": metrics["rows_total"],
        "latency_ms_p50": metrics["latency_ms_p50"],
        "latency_ms_p99": metrics["latency_ms_p99"],
        "requests_per_s": metrics["requests_per_s"],
        "batch_fill_ratio": metrics["batcher_batch_fill_ratio"],
        "replicas_healthy": metrics["num_healthy"],
        "programs": metrics["compile"]["programs"],
        "new_programs_since_warmup":
            metrics["compile"]["new_programs_since_warmup"],
        "status": health["status"],
    }, indent=2))

    fresh = metrics["compile"]["new_programs_since_warmup"]
    assert fresh == 0, (
        f"{fresh} programs compiled AFTER warmup — bucketing failed to "
        f"absorb live batch sizes"
    )
    assert health["status"] == "ok"
    # Round-trip spot check: the served numbers ARE the model's numbers.
    x = np.asarray(val.x[:5], np.float32)
    served = server.replicas.predict(x)
    model, variables = analysis.best_model()
    direct = np.asarray(model.apply(variables, x, deterministic=True))
    np.testing.assert_allclose(served, direct, rtol=1e-5, atol=1e-6)
    print("OK: zero recompiles after warmup; served == model.apply")

    # -- 5. zero-downtime hot swap -------------------------------------------
    # Promote "the next model" (here: the same winner re-exported) into
    # the live set: warmed off-path through the AOT caches, then each
    # slot drains-and-switches — no request dropped, nothing compiled.
    next_dir = os.path.join(root, "bundle_next")
    serve.export_bundle(analysis, next_dir)
    # dmlint: disable=unguarded-promotion mechanics demo: the "next model" IS the incumbent re-exported (bit-identical params), and the allclose below is the quality check — probation would watch a model we just proved identical
    event = server.replicas.hot_swap(serve.load_bundle(next_dir))
    after = json.loads(urllib.request.urlopen(f"{base}/metrics").read())
    assert after["swap"]["swaps_total"] == 1
    assert after["compile"]["new_programs_since_warmup"] == 0
    np.testing.assert_allclose(server.replicas.predict(x), direct,
                               rtol=1e-5, atol=1e-6)
    print(f"OK: hot swap in {event['duration_s']}s, zero post-swap "
          f"compiles; autoscale trajectory: "
          f"{[e['replicas'] for e in after['autoscale']['events']]}")
    server.close()
    return metrics


if __name__ == "__main__":
    main()
