"""Smoke-test HPO driver: the reference's sample workflow, end to end.

Counterpart of `/root/reference/ray-tune-hpo-regression-sample.py:152-172`
(C22 in SURVEY.md §2a): dummy ``(1000, 50, 10)`` sequence-regression data, a
simple transformer, a 6-hyperparameter space (`-sample.py:140-147`), ASHA on
``validation_loss``, 10 trials, best config logged and printed.  Runs on CPU
virtual devices in about a minute — the de-facto integration test, exactly
as the reference used its sample script (SURVEY.md §4).

Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/hpo_smoke.py
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_machine_learning_tpu import tune  # noqa: E402
from distributed_machine_learning_tpu.data import dummy_regression_data  # noqa: E402
from distributed_machine_learning_tpu.utils.logging import (  # noqa: E402
    add_file_handler,
    get_logger,
)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--num-samples", type=int, default=10)
    parser.add_argument("--num-epochs", type=int, default=5)
    parser.add_argument("--storage", default="~/dml_tpu_results")
    parser.add_argument(
        "--log-file",
        default=os.path.join(
            os.path.expanduser("~"), f"dml_tpu_smoke_run_{int(time.time())}.log"
        ),
        help="file log, parity with the reference's timestamped log "
        "(`-sample.py:16-23`) minus its hard-coded home path",
    )
    args = parser.parse_args(argv)

    add_file_handler(args.log_file)
    logger = get_logger("hpo_smoke", level=logging.INFO)
    logger.info("Starting the HPO smoke workflow...")

    train, val = dummy_regression_data(
        num_samples=1000, seq_len=50, num_features=10
    )
    logger.info("Dummy data: train=%d val=%d", len(train), len(val))

    # The reference's 6-hyperparameter sample space (`-sample.py:140-147`).
    search_space = {
        "model": "simple_transformer",
        "d_model": tune.choice([32, 64, 128]),
        "num_heads": tune.choice([2, 4]),
        "num_layers": tune.choice([1, 2, 3]),
        "dropout": tune.uniform(0.1, 0.5),
        "learning_rate": tune.loguniform(1e-4, 1e-2),
        "weight_decay": tune.loguniform(1e-6, 1e-2),
        "num_epochs": args.num_epochs,
        "batch_size": 32,
        "max_seq_length": 64,
    }

    analysis = tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        tune.SearchSpace(
            search_space,
            constraints=[tune.Constraint(
                lambda cfg: cfg["d_model"] % cfg["num_heads"] == 0,
                description="d_model divisible by num_heads",
            )],
        ),
        metric="validation_loss",
        mode="min",
        num_samples=args.num_samples,
        scheduler=tune.ASHAScheduler(
            max_t=args.num_epochs, grace_period=1, reduction_factor=2
        ),
        storage_path=args.storage,
        name="hpo_smoke",
    )

    best_config = analysis.best_config
    logger.info("Best hyperparameters found: %s", best_config)
    print("Best hyperparameters found:\n", best_config)
    return analysis


if __name__ == "__main__":
    main()
