"""Long-context training demo: sequence parallelism two ways.

The reference caps sequences at a 2000-entry PE table on one device
(`/root/reference/ray-tune-hpo-regression.py:26,388`); here the sequence
dimension shards over the ``sp`` mesh axis so context length scales with
the mesh. This driver trains the flagship transformer on a long synthetic
sequence twice — with ring attention (ppermute K/V rotation) and with
Ulysses (all_to_all head/seq reshuffle) — and reports per-step wall time
for each, plus a parity check between the two.

Run (8 virtual CPU devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context.py
On a real slice, drop the env overrides and raise --seq-len.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from distributed_machine_learning_tpu.models import build_model  # noqa: E402
from distributed_machine_learning_tpu.ops.losses import get_loss  # noqa: E402
from distributed_machine_learning_tpu.ops.optimizers import (  # noqa: E402
    make_optimizer,
)
from distributed_machine_learning_tpu.parallel import (  # noqa: E402
    make_mesh,
    make_sharded_train_step,
)


def train_steps(mode: str, mesh, x, y, steps: int, args):
    model = build_model({
        "model": "transformer",
        "d_model": args.d_model,
        "num_heads": args.num_heads,
        # Grouped-query attention: kv stays at num_kv_heads through the
        # kernels and around the ring (per-hop payload / group factor).
        # Ulysses also rides grouped when num_kv_heads divides the sp
        # split (the default 4 over sp=4 does); otherwise it broadcasts.
        "num_kv_heads": args.num_kv_heads,
        "num_layers": args.num_layers,
        "dim_feedforward": args.d_model * 2,
        "max_seq_length": args.seq_len,
        # Rotary positions: relative, no PE-table length cap.
        "position_encoding": "rope",
        "seq_axis": "sp",
        "seq_parallel_mode": mode,
        "mesh": mesh,
        "compute_dtype": "bfloat16" if args.bf16 else None,
        "remat": args.remat,
        "dropout": 0.0,
    })
    tx = make_optimizer("adamw", learning_rate=1e-3, weight_decay=1e-4)
    init_fn, step_fn = make_sharded_train_step(
        model, tx, get_loss("mse"), mesh
    )
    with mesh:
        params, opt = init_fn(jax.random.key(0), x)
        # Warmup step includes compile; timed steps are pure execute.
        params, opt, loss = step_fn(params, opt, x, y, jax.random.key(1))
        jax.block_until_ready(loss)
        t0 = time.time()
        for i in range(steps):
            params, opt, loss = step_fn(params, opt, x, y, jax.random.key(i))
        jax.block_until_ready(loss)
    return (time.time() - t0) / steps, float(loss), params


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seq-len", type=int, default=512)
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--num-heads", type=int, default=8)
    parser.add_argument("--num-kv-heads", type=int, default=4)
    parser.add_argument("--bf16", action="store_true")
    parser.add_argument("--remat", action="store_true",
                        help="recompute encoder blocks in the backward "
                             "(memory for FLOPs — longer contexts fit)")
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--dp", type=int, default=2)
    parser.add_argument("--sp", type=int, default=4)
    parser.add_argument("--steps", type=int, default=5)
    args = parser.parse_args(argv)

    mesh = make_mesh(
        {"dp": args.dp, "sp": args.sp}, jax.devices()[: args.dp * args.sp]
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.normal(size=(args.batch, args.seq_len, 8)), jnp.float32
    )
    y = jnp.asarray(rng.normal(size=(args.batch, 1)), jnp.float32)

    print(f"mesh dp={args.dp} sp={args.sp}, seq_len={args.seq_len}")
    results = {}
    for mode in ("ring", "ulysses"):
        step_s, loss, params = train_steps(mode, mesh, x, y, args.steps, args)
        results[mode] = (step_s, loss)
        print(f"{mode:8s}: {step_s * 1e3:8.1f} ms/step   loss={loss:.4f}")
    # Same model, same data, same seed: the two strategies must agree.
    drift = abs(results["ring"][1] - results["ulysses"][1])
    print(f"loss drift between strategies after {args.steps} steps: {drift:.2e}")


if __name__ == "__main__":
    main()
