"""Out-of-core training: a dataset BIGGER than the device budget.

Sets a tiny virtual device budget (the same `DML_CPU_DEVICE_BUDGET_BYTES`
knob tier-1 uses), builds a dataset that provably exceeds it, and shows:

1. resident staging FAILS the budget check (`ResidentOverBudgetError`) —
   the dataset genuinely cannot live on the device;
2. the same trial trains to completion with `input_mode="auto"` — the
   double-buffered prefetch ring stages chunk *k+1* on a producer thread
   while the device consumes donated chunk *k*;
3. streaming is exact: a resident run of the same seed (under a raised
   budget) finishes with BIT-identical params;
4. the `host_input` counter block (prefetch hits, producer/consumer
   waits, overlap efficiency) printed from `experiment_state.json`.

Runs on virtual CPU devices (see README):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/streaming_large_dataset.py
"""

import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET = 512 << 10  # 512 KiB virtual device budget
os.environ["DML_CPU_DEVICE_BUDGET_BYTES"] = str(BUDGET)

import jax  # noqa: E402

from distributed_machine_learning_tpu import tune  # noqa: E402
from distributed_machine_learning_tpu.data import (  # noqa: E402
    dummy_regression_data,
)
from distributed_machine_learning_tpu.data import pipeline  # noqa: E402


def sweep(storage, name, **overrides):
    train, val = dummy_regression_data(
        num_samples=4000, seq_len=8, num_features=8
    )
    config = {
        "model": "mlp", "hidden_sizes": (32,), "learning_rate": 1e-2,
        "batch_size": 64, "num_epochs": 3, "lr_schedule": "constant",
        "checkpoint_freq": 3, **overrides,
    }
    return train, val, tune.run(
        tune.with_parameters(
            tune.train_regressor, train_data=train, val_data=val
        ),
        config,
        metric="validation_loss", num_samples=1, seed=0,
        storage_path=storage, name=name, verbose=0,
    )


def main():
    storage = tempfile.mkdtemp(prefix="dml_streaming_")
    train, val = dummy_regression_data(
        num_samples=4000, seq_len=8, num_features=8
    )
    nbytes = pipeline.staged_nbytes(train, val, np.float32)
    print(f"dataset: {nbytes / 2**20:.2f} MiB, "
          f"virtual device budget: {BUDGET / 2**20:.2f} MiB")

    # 1) resident staging provably cannot hold it
    try:
        train.as_jax(enforce_budget=True)
        raise SystemExit("expected ResidentOverBudgetError")
    except pipeline.ResidentOverBudgetError as exc:
        print(f"resident staging refused: {exc}\n")

    # 2) streaming trains it (auto-engaged by the budget)
    _, _, analysis = sweep(storage, "streaming_demo")
    trial = analysis.trials[0]
    print(f"streamed trial finished: {trial.training_iteration} epochs, "
          f"input_mode={trial.last_result['input_mode']}, "
          f"val_loss={trial.last_result['validation_loss']:.4f}")

    # 3) exactness: a resident run of the same seed (budget raised) ends
    #    with bit-identical params
    os.environ["DML_CPU_DEVICE_BUDGET_BYTES"] = str(1 << 30)
    _, _, resident = sweep(storage, "resident_control")
    os.environ["DML_CPU_DEVICE_BUDGET_BYTES"] = str(BUDGET)
    from distributed_machine_learning_tpu.tune.checkpoint import (
        find_latest_checkpoint,
        load_checkpoint,
    )

    def final_params(a):
        path, _ = find_latest_checkpoint(os.path.join(
            a.root, a.trials[0].trial_id, "checkpoints"
        ))
        return jax.tree.leaves(load_checkpoint(path)["params"])

    same = all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(final_params(analysis), final_params(resident))
    )
    print(f"streaming params bit-identical to resident control: {same}")
    assert same

    # 4) the host_input counter block is part of the artifact
    state = json.load(open(os.path.join(analysis.root,
                                        "experiment_state.json")))
    print("\nhost_input block (experiment_state.json):")
    print(json.dumps(state["host_input"], indent=2, sort_keys=True))


if __name__ == "__main__":
    main()
