"""Parallelism: device meshes, sharded train steps, multi-core trials."""
