"""Parallelism: device meshes, sharded train steps, multi-core trials.

Strategies (SURVEY.md §2c — every row the reference lacks, built TPU-first):
data (``dp``), tensor (``tp``, `sharding.py`), sequence (``sp`` — ring in
`ring_attention.py`, all-to-all in `ulysses.py`), expert (``ep``,
`models/moe.py` + `sharding.py`), and pipeline (``pp``, `pipeline.py`).
"""

from distributed_machine_learning_tpu.parallel.mesh import (
    auto_mesh,
    batch_sharding,
    make_mesh,
    mesh_devices,
    replicated,
)
from distributed_machine_learning_tpu.parallel import multihost
from distributed_machine_learning_tpu.parallel.partition import (
    clean_spec,
    make_shard_and_gather_fns,
    match_partition_rules,
    rules_fingerprint,
    shardings_from_rules,
)
from distributed_machine_learning_tpu.parallel.pipeline import (
    make_stacked_stage_fn,
    pipeline_apply,
    stage_param_shardings,
)
from distributed_machine_learning_tpu.parallel.ring_attention import ring_attention
from distributed_machine_learning_tpu.parallel.sharding import (
    TRANSFORMER_TP_RULES,
    param_shardings,
    shard_params,
)
from distributed_machine_learning_tpu.parallel.train_step import (
    make_data_parallel_eval,
    make_fused_epoch_step,
    make_sharded_train_step,
    resolve_remat_policy,
)
from distributed_machine_learning_tpu.parallel.ulysses import ulysses_attention

__all__ = [
    "auto_mesh",
    "multihost",
    "batch_sharding",
    "make_mesh",
    "mesh_devices",
    "replicated",
    "make_stacked_stage_fn",
    "pipeline_apply",
    "stage_param_shardings",
    "ring_attention",
    "ulysses_attention",
    "TRANSFORMER_TP_RULES",
    "param_shardings",
    "shard_params",
    "clean_spec",
    "make_shard_and_gather_fns",
    "match_partition_rules",
    "rules_fingerprint",
    "shardings_from_rules",
    "make_data_parallel_eval",
    "make_fused_epoch_step",
    "make_sharded_train_step",
    "resolve_remat_policy",
]
