"""Parameter/activation sharding rules (Megatron-style tensor parallelism).

Rules map flax param paths to PartitionSpecs over the (dp, sp, tp) mesh:

* attention q/k/v DenseGeneral kernels  [d_model, heads, head_dim] -> shard
  heads on ``tp`` (each core owns a head group; attention is embarrassingly
  parallel over heads, no collective inside the core attention op);
* attention out kernel [heads, head_dim, d_model] -> shard heads on ``tp``
  (row-parallel; XLA inserts the psum on the output);
* feed-forward in kernel [d_model, dim_ff] -> column-parallel on ``tp``;
  feed-forward out kernel [dim_ff, d_model] -> row-parallel on ``tp``;
* embeddings/projections/norms/heads -> replicated.

This is the standard 1D-TP recipe (shard the two big matmuls of each block
column-then-row so only one reduce per block is needed); XLA GSPMD propagates
the activation shardings and places the collectives on ICI.
"""

from __future__ import annotations

import re
from typing import Any, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec) — first match wins. Paths look like
# "layer_0/attention/query/kernel" (flax param tree joined with '/').
TRANSFORMER_TP_RULES: Tuple[Tuple[str, P], ...] = (
    (r".*attention/(query|key|value)/kernel$", P(None, "tp", None)),
    (r".*attention/(query|key|value)/bias$", P("tp", None)),
    (r".*attention/out/kernel$", P("tp", None, None)),
    (r".*attention/out/bias$", P()),
    (r".*ff/Dense_0/kernel$", P(None, "tp")),   # column parallel
    (r".*ff/Dense_0/bias$", P("tp")),
    (r".*ff/Dense_1/kernel$", P("tp", None)),   # row parallel
    (r".*ff/Dense_1/bias$", P()),
    (r".*ff/pointwise/kernel$", P(None, None, "tp")),
    (r".*ff/pointwise/bias$", P("tp")),
    (r".*ff/out_proj/kernel$", P("tp", None)),
    (r".*ff/out_proj/bias$", P()),
    # MoE expert stacks (models/moe.py): expert dim over 'ep', and the
    # per-expert matmul dims over 'tp' (column-parallel in, row-parallel
    # out) — experts and attention-head groups shard over different axes,
    # so ep x tp runs expert-parallel and tensor-parallel together.
    (r".*ff/w_in$", P("ep", None, "tp")),
    (r".*ff/b_in$", P("ep", "tp")),
    (r".*ff/w_out$", P("ep", "tp", None)),
    (r".*ff/b_out$", P("ep", None)),
    (r".*ff/router/.*", P()),  # router is tiny; replicate
    (r".*", P()),  # everything else replicated
)


def _path_str(path) -> str:
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(parts)


def partition_spec_for(path: str, rules=TRANSFORMER_TP_RULES) -> P:
    for pattern, spec in rules:
        if re.fullmatch(pattern, path):
            return spec
    return P()


def param_shardings(params: Any, mesh: Mesh, rules=TRANSFORMER_TP_RULES):
    """A pytree of NamedShardings matching ``params``' structure."""

    def assign(path, leaf):
        spec = partition_spec_for(_path_str(path), rules)
        # Drop axes the mesh doesn't have / that exceed the leaf's rank.
        cleaned = []
        for i, axis in enumerate(spec):
            if i >= leaf.ndim:
                break
            cleaned.append(axis if axis in (None,) or axis in mesh.axis_names else None)
        # Avoid sharding a dim the axis size doesn't divide.
        final = []
        for i, axis in enumerate(cleaned):
            if axis is not None and leaf.shape[i] % mesh.shape[axis] != 0:
                axis = None
            final.append(axis)
        return NamedSharding(mesh, P(*final))

    return jax.tree_util.tree_map_with_path(assign, params)


def shard_params(params: Any, mesh: Mesh, rules=TRANSFORMER_TP_RULES):
    """device_put the param pytree according to the rules."""
    shardings = param_shardings(params, mesh, rules)
    return jax.device_put(params, shardings)


def opt_state_shardings(opt_shape: Any, p_shardings: Any, mesh: Mesh):
    """Shardings for an optimizer-state pytree: param-mirroring subtrees
    (adam mu/nu, momentum traces, ...) inherit the param's sharding; scalars
    and counts are replicated on the mesh.

    Needed because ``jit(tx.init)`` without ``out_shardings`` is free to
    place outputs on a single device, which silently drops the TP layout of
    the moments AND produces mixed committed placements that later jits
    reject.  Matching is by key-path suffix: a leaf at
    ``(..., 'mu', 'layer_0', 'kernel')`` matches the param at
    ``('layer_0', 'kernel')``.
    """
    flat_params = {
        tuple(repr(k) for k in path): sh
        for path, sh in jax.tree_util.tree_flatten_with_path(p_shardings)[0]
    }
    replicated = NamedSharding(mesh, P())

    def assign(path, leaf):
        spath = tuple(repr(k) for k in path)
        for i in range(len(spath)):
            match = flat_params.get(spath[i:])
            if match is not None:
                # Guard: the matched spec must fit the leaf's rank (a spec
                # may be shorter than the rank, never longer).
                if len(match.spec) <= getattr(leaf, "ndim", 0):
                    return match
                return replicated
        return replicated

    return jax.tree_util.tree_map_with_path(assign, opt_shape)
