"""Parameter/activation sharding over the (dp, sp, tp, ep) mesh.

Since the partition-rule layer landed (``parallel/partition.py``), this
module is the thin param/opt-state surface over it: rule lists live in
``models/partition_rules.py`` (one table per model family — the
``TRANSFORMER_TP_RULES`` name re-exports the transformer table), matching
and spec-cleaning are :func:`partition.match_partition_rules` /
:func:`partition.clean_spec` (``re.search`` semantics, first match wins,
scalars never partition).

The transformer recipe itself is unchanged (standard 1D TP): shard
attention q/k/v heads and the FF column/row pair over ``tp`` so each block
needs one reduce; XLA GSPMD propagates activation shardings and places the
collectives on ICI.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.parallel.partition import (
    clean_spec,
    match_partition_rules,
    path_str as _path_str,
    shardings_from_rules,
)

# The transformer family table: the 1D-TP recipe over attention heads +
# the FF column/row pair, MoE expert stacks over 'ep' x 'tp', wide
# head/input projections sharded where divisible.  ``re.search``
# semantics, first match wins.  Canonical home is HERE (the parallel
# layer owns no model imports); ``models/partition_rules.py`` re-exports
# it as the "transformer" entry of the per-family registry.
TRANSFORMER_TP_RULES = (
    (r"attention/(query|key|value)/kernel$", P(None, "tp", None)),
    (r"attention/(query|key|value)/bias$", P("tp", None)),
    (r"attention/out/kernel$", P("tp", None, None)),
    (r"attention/out/bias$", P()),
    (r"ff/Dense_0/kernel$", P(None, "tp")),   # column parallel
    (r"ff/Dense_0/bias$", P("tp")),
    (r"ff/Dense_1/kernel$", P("tp", None)),   # row parallel
    (r"ff/Dense_1/bias$", P()),
    (r"ff/pointwise/kernel$", P(None, None, "tp")),
    (r"ff/pointwise/bias$", P("tp")),
    (r"ff/out_proj/kernel$", P("tp", None)),
    (r"ff/out_proj/bias$", P()),
    # MoE expert stacks (models/moe.py): expert dim over 'ep', and the
    # per-expert matmul dims over 'tp' (column-parallel in, row-parallel
    # out) — experts and attention-head groups shard over different axes,
    # so ep x tp runs expert-parallel and tensor-parallel together.
    (r"ff/w_in$", P("ep", None, "tp")),
    (r"ff/b_in$", P("ep", "tp")),
    (r"ff/w_out$", P("ep", "tp", None)),
    (r"ff/b_out$", P("ep", None)),
    (r"ff/router/", P()),  # router is tiny; replicate
    # Wide head/input projections (the sharded flagship's d_model-sized
    # matmuls) shard their d_model dim when divisible; clean_spec
    # replicates them on meshes where they don't.
    (r"head/Dense_0/kernel$", P("tp", None)),
    # The head FUNNEL below Dense_0 (128->64->32->16->1) is fixed-size at
    # any d_model — O(10 KB) replicated at flagship scale.  The explicit
    # rule records the decision so the jaxlint coverage audit (DML101)
    # can tell "deliberately replicated" from "fell through the
    # catch-all" (its first run flagged exactly these leaves).
    (r"head/Dense_[1-9]\d*/(kernel|bias)$", P()),
    (r"input_projection/kernel$", P(None, "tp")),
    (r".*", P()),  # everything else replicated
)


def partition_spec_for(path: str, rules=TRANSFORMER_TP_RULES) -> P:
    """First-match spec for one ``'/'``-joined param path (search
    semantics; unmatched -> replicated)."""
    from distributed_machine_learning_tpu.parallel.partition import (
        _pattern_matches,
    )

    for pattern, spec in rules:
        if _pattern_matches(pattern, path):
            return spec
    return P()


def param_shardings(params: Any, mesh: Mesh, rules=TRANSFORMER_TP_RULES):
    """A pytree of NamedShardings matching ``params``' structure (rule
    specs cleaned per leaf: missing mesh axes, excess rank, and
    non-dividing dims fall back to replication)."""
    return shardings_from_rules(params, mesh, rules)


def shard_params(params: Any, mesh: Mesh, rules=TRANSFORMER_TP_RULES):
    """device_put the param pytree according to the rules."""
    shardings = param_shardings(params, mesh, rules)
    return jax.device_put(params, shardings)


def param_partition_specs(params: Any, rules=TRANSFORMER_TP_RULES):
    """Raw (uncleaned) PartitionSpec pytree for ``params`` — what ckpt/
    indexes and compile keys record."""
    return match_partition_rules(rules, params)


def opt_state_shardings(opt_shape: Any, p_shardings: Any, mesh: Mesh):
    """Shardings for an optimizer-state pytree: param-mirroring subtrees
    (adam mu/nu, momentum traces, ...) inherit the param's sharding; scalars
    and counts are replicated on the mesh.

    Needed because ``jit(tx.init)`` without ``out_shardings`` is free to
    place outputs on a single device, which silently drops the TP layout of
    the moments AND produces mixed committed placements that later jits
    reject.  Matching is by key-path suffix: a leaf at
    ``(..., 'mu', 'layer_0', 'kernel')`` matches the param at
    ``('layer_0', 'kernel')``.
    """
    flat_params = {
        tuple(repr(k) for k in path): sh
        for path, sh in jax.tree_util.tree_flatten_with_path(p_shardings)[0]
    }
    replicated = NamedSharding(mesh, P())

    def assign(path, leaf):
        spath = tuple(repr(k) for k in path)
        for i in range(len(spath)):
            match = flat_params.get(spath[i:])
            if match is not None:
                # Guard: the matched spec must fit the leaf's rank (a spec
                # may be shorter than the rank, never longer).
                if len(match.spec) <= getattr(leaf, "ndim", 0):
                    return match
                return replicated
        return replicated

    return jax.tree_util.tree_map_with_path(assign, opt_shape)


__all__ = [
    "TRANSFORMER_TP_RULES",
    "partition_spec_for",
    "param_shardings",
    "param_partition_specs",
    "shard_params",
    "opt_state_shardings",
    "clean_spec",
    "_path_str",
]
