"""Pipeline parallelism: GPipe-style microbatch pipelining over a ``pp`` axis.

Beyond-parity capability (the reference trains single-device models only —
SURVEY.md §2c): layer stages are sharded over the ``pp`` mesh axis and
microbatches stream through them, so a model deeper than one chip's HBM
trains with every stage busy once the pipeline fills.

Design, TPU-first:

* The schedule is data-flow, not control-flow: one ``lax.scan`` over
  ``M + P - 1`` ticks, where at tick ``t`` stage ``s`` processes microbatch
  ``t - s`` (a bubble of ``P - 1`` ticks at each end — GPipe).  All stages
  execute every tick under SPMD; out-of-range ticks compute on don't-care
  data and their results are masked out.  No data-dependent Python control
  flow — the whole pipeline is one XLA program.
* Activations hop stage-to-stage with ``jax.lax.ppermute`` — one
  nearest-neighbor ICI transfer per tick, the same primitive (and torus
  layout) ring attention rides.
* Stage parameters are ONE stacked pytree: leaves have leading dim
  ``num_stages``, sharded ``P("pp")`` (`stage_param_shardings`), so each
  device holds only its stage's slice.  Stage bodies see the slice with the
  leading dim dropped.
* Differentiable end to end: ``ppermute`` and ``scan`` have transpose
  rules, so ``jax.grad`` through ``pipeline_apply`` yields the standard
  GPipe backward schedule (reverse bubble) with no extra machinery.

``pipeline_apply`` is the generic engine; ``make_stacked_stage_fn`` adapts a
flax layer module into a stage body that scans its share of a stacked-layer
parameter tree (the nn.scan layout the shared-weights transformer already
uses), which is how a transformer encoder stack pipelines.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.parallel.ring_attention import _shard_map


def _pipeline_local(
    stage_params: Any,
    x_mb: jnp.ndarray,
    *,
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis_name: str,
) -> jnp.ndarray:
    """Per-device body. ``stage_params`` leaves are [1, ...] (this stage's
    slice); ``x_mb`` is the local [M, mb/dp, ...] microbatch stack (only
    stage 0 reads it). Returns local [M, mb/dp, ...] outputs."""
    params = jax.tree_util.tree_map(lambda l: l[0], stage_params)
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    M = x_mb.shape[0]
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]  # stage s -> s+1

    def tick(carry, t):
        prev_out, y_acc = carry
        # Activation arriving from the previous stage this tick.
        incoming = jax.lax.ppermute(prev_out, axis_name, fwd_perm)
        mb_idx = jnp.clip(t, 0, M - 1)
        x_in = jnp.where(stage == 0, x_mb[mb_idx], incoming)
        out = stage_fn(params, x_in)
        # The last stage finished microbatch t - (P - 1) this tick.
        widx = t - (n_stages - 1)
        valid = (widx >= 0) & (widx < M)
        y_new = jax.lax.dynamic_update_index_in_dim(
            y_acc, out, jnp.clip(widx, 0, M - 1), 0
        )
        y_acc = jnp.where(valid, y_new, y_acc)
        return (out, y_acc), None

    mb_shape = x_mb.shape[1:]
    out_shape = jax.eval_shape(
        stage_fn, params, jax.ShapeDtypeStruct(mb_shape, x_mb.dtype)
    )
    zero_out = jnp.zeros(out_shape.shape, out_shape.dtype)
    y0 = jnp.zeros((M,) + out_shape.shape, out_shape.dtype)
    (_, y), _ = jax.lax.scan(
        tick, (zero_out, y0), jnp.arange(M + n_stages - 1)
    )
    # Only the last stage holds real outputs; replicate them across 'pp'.
    y = jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y))
    return jax.lax.psum(y, axis_name)


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "pp",
    num_microbatches: Optional[int] = None,
    batch_axis: Optional[str] = "dp",
) -> jnp.ndarray:
    """Run ``x`` through ``num_stages`` pipelined applications of ``stage_fn``.

    stage_params: pytree whose leaves have leading dim ``num_stages`` (the
    mesh's ``axis_name`` size), stacked in stage order and sharded over
    ``axis_name`` (see ``stage_param_shardings``).
    x: [B, ...] global batch; it is split into ``num_microbatches`` equal
    microbatches along dim 0 (M defaults to the stage count — the classic
    GPipe minimum for full utilization; more microbatches shrink the
    relative bubble).
    When the mesh also has ``batch_axis`` (dp), each microbatch's in-batch
    dim shards over it — dp x pp compose: dp rows pipeline disjoint batch
    slices instead of redundantly recomputing the same ones.
    Returns stage_fn^P(x) of shape [B, ...] — as if the stages ran
    sequentially on the whole batch.
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis_name!r}: {mesh.axis_names}")
    n_stages = mesh.shape[axis_name]
    M = int(num_microbatches or n_stages)
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(
            f"batch {B} not divisible by num_microbatches {M}"
        )
    baxis = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    if baxis and (B // M) % mesh.shape[baxis] != 0:
        raise ValueError(
            f"microbatch size {B // M} not divisible by {baxis} axis size "
            f"{mesh.shape[baxis]}"
        )
    leaves = jax.tree_util.tree_leaves(stage_params)
    if leaves and leaves[0].shape[0] != n_stages:
        raise ValueError(
            f"stage_params leading dim {leaves[0].shape[0]} != pipeline "
            f"stages {n_stages} (mesh axis {axis_name!r})"
        )

    x_mb = x.reshape(M, B // M, *x.shape[1:])
    x_spec = P(None, baxis)
    param_specs = jax.tree_util.tree_map(
        lambda l: P(axis_name, *([None] * (l.ndim - 1))), stage_params
    )
    fn = _shard_map(
        partial(_pipeline_local, stage_fn=stage_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
    )
    y = fn(stage_params, x_mb)
    return y.reshape(B, *y.shape[2:])


def stage_param_shardings(stage_params: Any, mesh: Mesh, axis_name: str = "pp"):
    """NamedShardings placing each stage's parameter slice on its device:
    leading (stage) dim over ``axis_name``, everything else replicated."""
    return jax.tree_util.tree_map(
        lambda l: NamedSharding(
            mesh, P(axis_name, *([None] * (l.ndim - 1)))
        ),
        stage_params,
    )


def make_stacked_stage_fn(
    layer_apply: Callable[[Any, jnp.ndarray], jnp.ndarray],
) -> Callable[[Any, jnp.ndarray], jnp.ndarray]:
    """Adapt a single-layer apply into a stage body over stacked layers.

    ``layer_apply(layer_params, x) -> x`` is scanned over the stage's local
    stack of layer params (leaves [layers_per_stage, ...]) — so a pipeline
    of P stages x K layers each runs a P*K-layer network whose parameter
    tree is stacked once on the layer dimension, exactly the layout
    ``nn.scan``'s shared-weights transformer uses for its single shared
    layer (models/transformer.py).
    """

    def stage_fn(stage_stack, x):
        def body(h, layer_params):
            return layer_apply(layer_params, h), None

        out, _ = jax.lax.scan(body, x, stage_stack)
        return out

    return stage_fn
