"""Compatibility shim: the multi-host SPMD runtime grew into its own
subsystem (``distributed_machine_learning_tpu/multihost/`` — ISSUE 14).

Every helper that lived here (``initialize``, ``multihost_mesh``,
``global_batch_array``, ``barrier``, ``broadcast_from_coordinator``,
``is_coordinator``, ``describe``) now lives in
:mod:`distributed_machine_learning_tpu.multihost.runtime`, alongside the
new deadline-gated barrier, per-host staging, checkpoint-safe snapshots,
and process-topology identity.  Import from
``distributed_machine_learning_tpu.multihost`` in new code.
"""

from distributed_machine_learning_tpu.multihost.runtime import (  # noqa: F401
    BarrierTimeout,
    barrier,
    broadcast_from_coordinator,
    describe,
    global_batch_array,
    host_snapshot,
    initialize,
    is_coordinator,
    multihost_mesh,
    process_topology,
    spanning_mesh,
    stage_global,
)
