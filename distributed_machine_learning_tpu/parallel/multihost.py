"""Multi-host SPMD: jax.distributed runtime + DCN/ICI-aware meshes.

The reference's multi-node story is Ray's gRPC control plane with zero
collectives (SURVEY.md §1 L3, §5 "distributed communication backend" — no
NCCL/MPI anywhere). The TPU-native framework splits that capability in two:

* **HPO control plane** — driver↔worker TCP supervisors
  (`tune/cluster.py`): many independent trials, metrics/decisions over DCN.
* **One model over many hosts** — THIS module: every host runs the same
  jitted program, `jax.distributed` wires the XLA runtime together, and
  collectives ride ICI inside a slice / DCN across slices. This is the
  NCCL/MPI-equivalent layer, done the XLA way: you never call a collective
  yourself — you annotate shardings on a mesh from `multihost_mesh()` and
  XLA inserts/schedules them.

Mesh layout rule (the "How to Scale Your Model" recipe): put ``dp``
(gradient all-reduce once per step — latency-tolerant) across hosts on DCN,
and the chatty axes (``tp``/``sp``/``ep`` — per-layer collectives) inside a
host/slice on ICI. ``multihost_mesh`` encodes exactly that via
``mesh_utils.create_hybrid_device_mesh``.

Single-process (tests, one chip, CPU meshes) every function degrades to a
sensible no-op/local equivalent, so the same training script runs unchanged
from a laptop CPU mesh to a multi-host pod — launch it once per host with
the coordinator env set (or under a cluster manager jax auto-detects).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> bool:
    """Join (or skip joining) the jax.distributed runtime. Idempotent.

    Args default from the standard env (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID`` — also set by TPU pod
    metadata, which ``jax.distributed.initialize()`` auto-detects with no
    args). Returns True when a multi-process runtime is active after the
    call, False for the single-process fallback (no coordinator configured
    and none auto-detectable). Call BEFORE any other jax API touches the
    backend — device enumeration pins the runtime.
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    num_processes = (
        num_processes if num_processes is not None
        else int(env_np) if env_np else None
    )
    process_id = (
        process_id if process_id is not None
        else int(env_pid) if env_pid else None
    )
    in_managed_cluster = any(
        os.environ.get(k)
        for k in ("TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS",
                  "CLOUD_TPU_TASK_ID")
    )
    if coordinator_address is None and not in_managed_cluster:
        return False  # single-process: nothing to join
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    return jax.process_count() > 1


def is_coordinator() -> bool:
    """Process 0 — the one that should write checkpoints/logs/results."""
    return jax.process_index() == 0


def multihost_mesh(
    *, tp: int = 1, sp: int = 1, ep: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Global mesh over every process's devices, DCN/ICI-aware.

    ``dp`` fills whatever tp/sp/ep leave over. Multi-process: ``dp`` spans
    hosts (its once-per-step gradient reduction tolerates DCN latency) and
    tp/sp/ep must fit INSIDE one process's devices so their per-layer
    collectives stay on ICI — sizes that straddle hosts raise.
    Single-process: plain mesh over the local devices (axis order dp, sp,
    ep, tp — tp last = ICI-adjacent, same convention as mesh.auto_mesh).
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    n_procs = jax.process_count()
    used = tp * sp * ep
    if len(devices) % used != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible by tp*sp*ep={used}"
        )
    dp = len(devices) // used
    axis_names = ("dp", "sp", "ep", "tp")
    if n_procs == 1:
        arr = np.array(devices).reshape(dp, sp, ep, tp)
        return Mesh(arr, axis_names)

    per_host = len(devices) // n_procs
    if used > per_host or per_host % used != 0:
        raise ValueError(
            f"tp*sp*ep={used} must divide one host's {per_host} devices: "
            f"tensor/sequence/expert collectives are per-layer traffic and "
            f"must stay on ICI, not DCN (put dp across hosts instead)"
        )
    from jax.experimental import mesh_utils

    ici_dp = per_host // used
    n_slices = len({getattr(d, "slice_index", None) for d in devices})
    # Granule choice: by default create_hybrid_device_mesh groups devices
    # by slice_index; when slices don't map 1:1 to processes (single-slice
    # multi-host pods, and multi-process CPU test clusters where every
    # device reports slice 0 — caught by the 2-process CPU test), group by
    # process instead. Either way the helper keeps the ICI-topology-aware
    # device ordering within each granule.
    arr = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(ici_dp, sp, ep, tp),          # within a granule (ICI)
        dcn_mesh_shape=(n_procs, 1, 1, 1),        # across granules (DCN)
        devices=devices,
        process_is_granule=(n_slices != n_procs),
    )
    return Mesh(arr.reshape(dp, sp, ep, tp), axis_names)


def global_batch_array(
    host_local: np.ndarray, mesh: Mesh, spec: P = P("dp")
) -> jax.Array:
    """Assemble a global sharded array from each host's LOCAL shard.

    The multi-host data-loading contract: every host loads only its slice
    of the batch (no host ever materializes the global array — the analogue
    of the reference's Ray object-store broadcast, without the broadcast),
    and this stitches the shards into one global ``jax.Array`` addressable
    under jit. Single-process it is just ``device_put`` with the sharding.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(host_local, sharding)
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        host_local, mesh, spec
    )


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point (no-op single-process).

    Use at phase boundaries (before reading a peer's checkpoint, after
    coordinator-only writes) — NOT inside the step loop, where jit+XLA
    already orders collectives.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_from_coordinator(pytree):
    """Every process returns the coordinator's value (process-consistent
    config/HPO decisions without a side channel). Identity single-process."""
    if jax.process_count() == 1:
        return pytree
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(pytree)


def describe() -> Dict[str, int]:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
