"""Sharded training step over a named mesh (dp × sp × tp).

This is the multi-core trial path (SURVEY.md §2c: data parallelism *within a
trial* — BASELINE.json config 5 — plus tensor/sequence parallelism the
reference never had).  Design per the standard JAX recipe: pick a mesh,
annotate param + batch shardings, jit, and let XLA GSPMD insert the
collectives (psum for row-parallel matmuls and the gradient all-reduce over
dp; all-gathers where seq-sharded activations meet attention) on ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.models.moe import collect_aux
from distributed_machine_learning_tpu.parallel.sharding import (
    TRANSFORMER_TP_RULES,
    opt_state_shardings,
    param_shardings,
    shard_params,
)


def make_sharded_train_step(
    model,
    tx: optax.GradientTransformation,
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    rules=TRANSFORMER_TP_RULES,
    shard_seq: bool = True,
    flag_name: str = "deterministic",
):
    """Returns (init_fn, step_fn).

    init_fn(rng, sample_x) -> (params, opt_state) already sharded on the mesh.
    step_fn(params, opt_state, x, y, rng) -> (params, opt_state, loss); jitted
    with explicit in/out shardings; donates params/opt_state.
    """
    seq_axis = "sp" if (shard_seq and "sp" in mesh.axis_names) else None
    x_sharding = NamedSharding(mesh, P("dp", seq_axis))
    y_sharding = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())

    def init_fn(rng, sample_x):
        variables = model.init(
            {"params": rng, "dropout": jax.random.fold_in(rng, 1)},
            sample_x,
            **{flag_name: True if flag_name == "deterministic" else False},
        )
        params = shard_params(variables["params"], mesh, rules)
        p_shardings = param_shardings(params, mesh, rules)

        # jit the optimizer init with explicit out shardings so the moments
        # inherit the TP layout (without out_shardings, XLA may place the
        # whole state on one device, dropping the layout AND producing mixed
        # committed placements that later jits reject).
        o_shardings = opt_state_shardings(
            jax.eval_shape(tx.init, params), p_shardings, mesh
        )
        opt_state = jax.jit(
            tx.init, in_shardings=(p_shardings,), out_shardings=o_shardings
        )(params)
        return params, opt_state

    def _step(params, opt_state, x, y, rng):
        x = jax.lax.with_sharding_constraint(x, x_sharding)

        def loss_of(p):
            # mutable=["moe"]: collect the MoE load-balance aux terms (sown
            # by models/moe.py, pre-scaled); without it flax silently drops
            # the sow and the router would get no balancing gradient.
            preds, mut = model.apply(
                {"params": p},
                x,
                rngs={"dropout": rng},
                mutable=["moe"],
                **{flag_name: False if flag_name == "deterministic" else True},
            )
            return loss_fn(preds.astype(jnp.float32), y) + collect_aux(mut)

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss

    step_fn = jax.jit(
        _step,
        donate_argnums=(0, 1),
        in_shardings=(None, None, x_sharding, y_sharding, repl),
    )
    return init_fn, step_fn


def make_data_parallel_eval(
    model,
    mesh: Mesh,
    flag_name: str = "deterministic",
):
    """Sharded eval: predictions for a dp-sharded batch."""
    x_sharding = NamedSharding(mesh, P("dp"))

    def _eval(params, x):
        x = jax.lax.with_sharding_constraint(x, x_sharding)
        return model.apply(
            {"params": params},
            x,
            **{flag_name: True if flag_name == "deterministic" else False},
        )

    return jax.jit(_eval)
