"""Sharded training programs over a named mesh (dp × sp × tp).

Two tiers:

* :func:`make_sharded_train_step` — one jitted step per batch (the
  original multi-core trial path; kept for callers that drive their own
  step loop: ring-attention/multihost tests, examples).
* :func:`make_fused_epoch_step` — the FUSED tier (ISSUE 7): one jitted
  program runs a whole epoch as ``lax.scan`` over pre-sharded batch
  chunks, with ``donate_argnums`` covering params, opt-state, AND the
  epoch's batch arrays — N steps of per-step dispatch collapse to one
  dispatch + one compile, and the donated batch buffers mean the staged
  epoch costs no second HBM copy.  Layouts come from a partition-rule
  table (``models/partition_rules.py``) instead of a hard-coded spec
  table; ``with_sharding_constraint`` pins the batch layout at the program
  boundary and the model pins the residual stream/attention activations
  (``models/layers.py``).

Design per the standard JAX recipe: pick a mesh, annotate param + batch
shardings, jit, and let XLA GSPMD insert the collectives (psum for
row-parallel matmuls and the gradient all-reduce over dp; all-gathers
where seq-sharded activations meet attention) on ICI.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.models.moe import collect_aux
from distributed_machine_learning_tpu.parallel.sharding import (
    TRANSFORMER_TP_RULES,
    opt_state_shardings,
    param_shardings,
    shard_params,
)


def resolve_remat_policy(name) -> Optional[Any]:
    """A ``jax.checkpoint_policies`` policy from its config name.

    Accepted: None/""/"none" (no policy — full remat when remat is on),
    or any attribute of ``jax.checkpoint_policies`` ("dots_saveable",
    "nothing_saveable", "everything_saveable",
    "dots_with_no_batch_dims_saveable", ...).  The knob that trades
    recompute FLOPs against activation HBM per block
    (docs/performance.md).
    """
    if name is None or name in ("", "none", False):
        return None
    policy = getattr(jax.checkpoint_policies, str(name), None)
    if policy is None:
        valid = sorted(
            n for n in dir(jax.checkpoint_policies) if not n.startswith("_")
        )
        raise ValueError(
            f"Unknown remat policy {name!r}; expected one of {valid}"
        )
    return policy


def make_sharded_train_step(
    model,
    tx: optax.GradientTransformation,
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    rules=TRANSFORMER_TP_RULES,
    shard_seq: bool = True,
    flag_name: str = "deterministic",
):
    """Returns (init_fn, step_fn).

    init_fn(rng, sample_x) -> (params, opt_state) already sharded on the mesh.
    step_fn(params, opt_state, x, y, rng) -> (params, opt_state, loss); jitted
    with explicit in/out shardings; donates params/opt_state.
    """
    seq_axis = "sp" if (shard_seq and "sp" in mesh.axis_names) else None
    x_sharding = NamedSharding(mesh, P("dp", seq_axis))
    y_sharding = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())

    def init_fn(rng, sample_x):
        flag = {flag_name: True if flag_name == "deterministic" else False}

        def build(r, x):
            return model.init(
                {"params": r, "dropout": jax.random.fold_in(r, 1)}, x, **flag
            )

        # Born sharded: derive the rule shardings from the ABSTRACT init
        # (eval_shape allocates nothing) and jit the real init with them
        # as out_shardings — an over-HBM flagship's params never
        # materialize unsharded on one device.
        abstract = jax.eval_shape(build, rng, sample_x)
        p_shardings = param_shardings(abstract["params"], mesh, rules)
        repl = NamedSharding(mesh, P())
        v_shardings = dict(
            jax.tree_util.tree_map(lambda _: repl, abstract),
            params=p_shardings,
        )
        params = jax.jit(build, out_shardings=v_shardings)(
            rng, sample_x
        )["params"]

        # jit the optimizer init with explicit out shardings so the moments
        # inherit the TP layout (without out_shardings, XLA may place the
        # whole state on one device, dropping the layout AND producing mixed
        # committed placements that later jits reject).
        o_shardings = opt_state_shardings(
            jax.eval_shape(tx.init, params), p_shardings, mesh
        )
        opt_state = jax.jit(
            tx.init, in_shardings=(p_shardings,), out_shardings=o_shardings
        )(params)
        return params, opt_state

    def _step(params, opt_state, x, y, rng):
        x = jax.lax.with_sharding_constraint(x, x_sharding)

        def loss_of(p):
            # mutable=["moe"]: collect the MoE load-balance aux terms (sown
            # by models/moe.py, pre-scaled); without it flax silently drops
            # the sow and the router would get no balancing gradient.
            preds, mut = model.apply(
                {"params": p},
                x,
                rngs={"dropout": rng},
                mutable=["moe"],
                **{flag_name: False if flag_name == "deterministic" else True},
            )
            return loss_fn(preds.astype(jnp.float32), y) + collect_aux(mut)

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss

    step_fn = jax.jit(
        _step,
        donate_argnums=(0, 1),
        in_shardings=(None, None, x_sharding, y_sharding, repl),
    )
    return init_fn, step_fn


def make_fused_epoch_step(
    model,
    tx: optax.GradientTransformation,
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    rules=TRANSFORMER_TP_RULES,
    shard_seq: bool = False,
    flag_name: str = "deterministic",
):
    """Returns (init_fn, epoch_fn): the fused tier.

    ``epoch_fn(params, opt_state, xb, yb, epoch_key)`` consumes the whole
    epoch as ``[num_batches, batch, ...]`` arrays (in-batch dim sharded
    over ``dp``), scans the train step across them inside ONE jitted
    program, and returns ``(params, opt_state, mean_loss)``.  Donation
    covers every large input — params (0), opt_state (1), and both batch
    arrays (2, 3) — so the epoch runs with zero redundant HBM copies; the
    donated batch is consumed exactly once per epoch by construction.
    """
    seq_axis = "sp" if (shard_seq and "sp" in mesh.axis_names) else None
    xb_sharding = NamedSharding(mesh, P(None, "dp", seq_axis))
    yb_sharding = NamedSharding(mesh, P(None, "dp"))
    repl = NamedSharding(mesh, P())
    init_fn, _ = make_sharded_train_step(
        model, tx, loss_fn, mesh, rules=rules, shard_seq=shard_seq,
        flag_name=flag_name,
    )

    def _epoch(params, opt_state, xb, yb, epoch_key):
        xb = jax.lax.with_sharding_constraint(xb, xb_sharding)
        yb = jax.lax.with_sharding_constraint(yb, yb_sharding)

        def step(carry, batch):
            params, opt_state, i = carry
            x, y = batch
            rng = jax.random.fold_in(epoch_key, i)

            def loss_of(p):
                preds, mut = model.apply(
                    {"params": p},
                    x,
                    rngs={"dropout": rng},
                    mutable=["moe"],
                    **{
                        flag_name: False
                        if flag_name == "deterministic" else True
                    },
                )
                return loss_fn(preds.astype(jnp.float32), y) + collect_aux(mut)

            loss, grads = jax.value_and_grad(loss_of)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, i + 1), loss

        (params, opt_state, _), losses = jax.lax.scan(
            step, (params, opt_state, jnp.int32(0)), (xb, yb)
        )
        return params, opt_state, losses.mean()

    epoch_fn = jax.jit(
        _epoch,
        donate_argnums=(0, 1, 2, 3),
        in_shardings=(None, None, xb_sharding, yb_sharding, repl),
    )
    return init_fn, epoch_fn


def make_data_parallel_eval(
    model,
    mesh: Mesh,
    flag_name: str = "deterministic",
):
    """Sharded eval: predictions for a dp-sharded batch."""
    x_sharding = NamedSharding(mesh, P("dp"))

    def _eval(params, x):
        x = jax.lax.with_sharding_constraint(x, x_sharding)
        return model.apply(
            {"params": params},
            x,
            **{flag_name: True if flag_name == "deterministic" else False},
        )

    return jax.jit(_eval)
