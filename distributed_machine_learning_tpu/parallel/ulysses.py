"""Ulysses-style sequence parallelism: all-to-all head/sequence reshuffle.

The second long-context strategy next to ring attention
(`parallel/ring_attention.py`), after the DeepSpeed-Ulysses pattern: instead
of rotating K/V chunks around a ring, ONE ``all_to_all`` per projection
trades the sequence sharding for a head sharding —

    [B, S/n, H, D]  --all_to_all-->  [B, S, H/n, D]

so every device computes *exact, unmodified* softmax attention over the FULL
sequence for its head group, then a second ``all_to_all`` restores the
sequence sharding for the rest of the (sequence-sharded) network.

Trade-offs vs the ring (why both exist):

* Ulysses moves activations twice per attention call but computes plain
  attention with no online-softmax bookkeeping — fewer, bigger MXU matmuls
  and a simpler backward; at moderate sequence lengths it is usually faster.
* Ring never materializes full-sequence activations (per-device memory
  O(S/n * S/n) per step) and its per-hop traffic is nearest-neighbor — it
  scales to sequences Ulysses cannot hold, since Ulysses stores full-S
  activations per head group (O(S * H/n * D) per device).
* Ulysses requires ``num_heads`` divisible by the sequence-axis size; the
  ring has no such constraint.

Both compose with dp (batch) and tp (head) sharding; select per layer with
``seq_parallel_mode`` (`models/layers.py`).

The reference has no sequence parallelism of any kind (SURVEY.md §5).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_machine_learning_tpu.parallel.ring_attention import _shard_map


def _ulysses_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool,
    scale: Optional[float],
    use_flash="auto",
    flash_interpret: bool = False,
) -> jnp.ndarray:
    """Per-device body; q, k, v are local [B, S/n, H_local, D] shards."""
    D = q.shape[-1]
    s = (D ** -0.5) if scale is None else scale

    # seq-sharded -> head-sharded: gather the full sequence, keep 1/n of the
    # local head group. One collective, all ICI.
    def to_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)   # [B, S, H/n, D]
    S = qh.shape[1]

    from distributed_machine_learning_tpu.parallel.ring_attention import (
        _use_flash_inner,
    )

    if _use_flash_inner(use_flash, S, S, D):
        # After the reshuffle this is plain full-sequence attention — the
        # Pallas flash kernel (with its custom VJP) drops straight in; no
        # merge bookkeeping needed. Same measured-win gate as the ring.
        # Grouped kv (kh/vh at Hkv/n heads < qh's H/n) passes natively.
        from distributed_machine_learning_tpu.ops.pallas_attention import (
            flash_attention,
        )

        out = flash_attention(
            qh, kh, vh, scale=s, causal=causal, interpret=flash_interpret
        )
    else:
        if kh.shape[2] != qh.shape[2]:
            # Grouped kv rode the all_to_all at kv_heads (the comm saving);
            # the dense einsum needs full heads — a LOCAL repeat, no comm.
            g = qh.shape[2] // kh.shape[2]
            kh = jnp.repeat(kh, g, axis=2)
            vh = jnp.repeat(vh, g, axis=2)
        logits = jnp.einsum(
            "bqhd,bkhd->bqhk",
            qh.astype(jnp.float32) * s,
            kh.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            cmask = jnp.tril(jnp.ones((S, S), bool))[None, :, None, :]
            logits = jnp.where(cmask, logits, -jnp.inf)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bqhk,bkhd->bqhd", p, vh.astype(jnp.float32))

    # head-sharded -> seq-sharded: the inverse reshuffle.
    return jax.lax.all_to_all(
        out.astype(q.dtype), axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def head_split(mesh: Mesh, axis_name: str, head_axis: Optional[str]) -> int:
    """The factor the all-to-alls split the head dim by (sp size x tp
    size). ONE definition — models/layers.py uses it to decide whether
    grouped kv can ride the reshuffle, so the rule cannot drift from the
    validation below."""
    t = (
        mesh.shape[head_axis]
        if head_axis and head_axis in mesh.axis_names
        else 1
    )
    n = mesh.shape[axis_name] if axis_name in mesh.axis_names else 1
    return n * t


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash="auto",
    flash_interpret: bool = False,
) -> jnp.ndarray:
    """Exact softmax attention with the sequence sharded over ``axis_name``.

    Same contract as ``ring_attention``: q, k, v are [B, S, H, D] global
    arrays with S divisible by the axis size; batch/heads optionally shard
    over ``batch_axis``/``head_axis``; returns [B, S, H, D] with the same
    sharding.  Additionally requires H divisible by (sequence-axis size x
    head-axis size), since the all_to_all re-shards heads.

    ``use_flash``: run the per-device full-sequence attention through the
    Pallas flash kernel ("auto" = the kernel's measured-win regime on TPU;
    see ``ring_attention``); ``flash_interpret`` for CPU tests.
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis_name!r}: {mesh.axis_names}")
    baxis = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    haxis = head_axis if (head_axis and head_axis in mesh.axis_names) else None
    n = mesh.shape[axis_name]
    t = mesh.shape[haxis] if haxis else 1
    H = q.shape[2]
    if H % (n * t) != 0:
        raise ValueError(
            f"ulysses attention needs num_heads ({H}) divisible by "
            f"seq-axis size x head-axis size ({n}x{t}); use "
            f"seq_parallel_mode='ring' for head counts the all_to_all "
            f"cannot split"
        )
    Hkv = k.shape[2]
    if Hkv != H and (H % Hkv != 0 or Hkv % (n * t) != 0):
        raise ValueError(
            f"grouped kv ({Hkv} heads) must divide num_heads ({H}) and "
            f"divide by {n}x{t} to ride the all_to_all; broadcast kv to "
            f"full heads first (models/layers.py does this automatically)"
        )
    spec = P(baxis, axis_name, haxis, None)
    fn = _shard_map(
        partial(_ulysses_local, axis_name=axis_name, causal=causal,
                scale=scale, use_flash=use_flash,
                flash_interpret=flash_interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
