"""Ring attention: exact softmax attention over sequence-sharded inputs.

Long-context sequence parallelism, TPU-native. The sequence axis is sharded
over a mesh axis (``sp``); each device holds a query chunk and rotates
key/value chunks around the ring with ``jax.lax.ppermute`` (one ICI hop per
step) while maintaining flash-style online-softmax statistics, so

* memory per device is O(S/n * S/n) per step instead of O(S^2);
* communication is the K/V chunk per step, riding nearest-neighbor ICI links
  (the layout the TPU torus is built for) and overlapping with the block
  matmuls XLA schedules between permutes;
* the result is *exact* softmax attention — bitwise-independent of how many
  devices the sequence is sharded over (up to float associativity).

The reference has no long-context path at all (SURVEY.md §5: sequence length
capped at 2000 by a dense PE table, vanilla ``nn.MultiheadAttention`` at
`ray-tune-hpo-regression.py:139`); this module is the capability the TPU
framework adds so sequence length scales with the mesh instead of with HBM.

``ring_attention`` is differentiable (the loop is a ``lax.scan`` of jax ops;
ppermute has a transpose rule), so it drops straight into the sharded train
step for training over long sequences.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax API generations (>=0.8 keyword-only; older
    experimental takes check_rep)."""
    try:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except (AttributeError, TypeError):  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as legacy

        return legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def _ring_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool,
    scale: Optional[float],
) -> jnp.ndarray:
    """Per-device body; q, k, v are the local [B, S/n, H, D] shards."""
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    s = (D ** -0.5) if scale is None else scale

    qf = q.astype(jnp.float32) * s
    # Rotate kv blocks "down" the ring: after step i, this device holds the
    # shard originally owned by device (my_idx + i) mod n.
    perm = [(j, (j - 1) % n) for j in range(n)]

    q_pos = my_idx * Sq + jnp.arange(Sq)

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        src = (my_idx + i) % n
        k_pos = src * Sk + jnp.arange(Sk)

        logits = jnp.einsum(
            "bqhd,bkhd->bqhk",
            qf,
            k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            cmask = q_pos[None, :, None, None] >= k_pos[None, None, None, :]
            logits = jnp.where(cmask, logits, -jnp.inf)

        m_new = jnp.maximum(m, logits.max(axis=-1))
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bqhk,bkhd->bqhd", p, v_cur.astype(jnp.float32)
        )

        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    m0 = jnp.full((B, Sq, H), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, Sq, H), jnp.float32)
    acc0 = jnp.zeros((B, Sq, H, D), jnp.float32)
    (m, l, acc, _, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n)
    )
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact softmax attention with the sequence sharded over ``axis_name``.

    q, k, v: [B, S, H, D] global arrays (S divisible by the axis size).
    ``batch_axis`` optionally shards batch over a second mesh axis (dp);
    ``head_axis`` optionally shards heads over a third (tp) — heads are
    independent, so tensor parallelism composes with the ring for free.
    Returns [B, S, H, D] with the same sharding.
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis_name!r}: {mesh.axis_names}")
    baxis = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    haxis = head_axis if (head_axis and head_axis in mesh.axis_names) else None
    spec = P(baxis, axis_name, haxis, None)
    fn = _shard_map(
        partial(_ring_local, axis_name=axis_name, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
