"""Ring attention: exact softmax attention over sequence-sharded inputs.

Long-context sequence parallelism, TPU-native. The sequence axis is sharded
over a mesh axis (``sp``); each device holds a query chunk and rotates
key/value chunks around the ring with ``jax.lax.ppermute`` (one ICI hop per
step) while maintaining flash-style online-softmax statistics, so

* memory per device is O(S/n * S/n) per step instead of O(S^2);
* communication is the K/V chunk per step, riding nearest-neighbor ICI links
  (the layout the TPU torus is built for) and overlapping with the block
  matmuls XLA schedules between permutes;
* the result is *exact* softmax attention — bitwise-independent of how many
  devices the sequence is sharded over (up to float associativity).

The reference has no long-context path at all (SURVEY.md §5: sequence length
capped at 2000 by a dense PE table, vanilla ``nn.MultiheadAttention`` at
`ray-tune-hpo-regression.py:139`); this module is the capability the TPU
framework adds so sequence length scales with the mesh instead of with HBM.

``ring_attention`` is differentiable (the loop is a ``lax.scan`` of jax ops;
ppermute has a transpose rule), so it drops straight into the sharded train
step for training over long sequences.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax API generations (>=0.8 keyword-only; older
    experimental takes check_rep)."""
    try:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except (AttributeError, TypeError):  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as legacy

        return legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def _ring_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool,
    scale: Optional[float],
) -> jnp.ndarray:
    """Per-device body; q: local [B, S/n, H, D] shard, k/v: [B, S/n, Hkv, D]
    (Hkv < H = grouped-query attention; kv chunks ROTATE at kv_heads, so the
    per-step ICI payload shrinks by the group factor — the broadcast to full
    heads happens only inside each step's local compute)."""
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    s = (D ** -0.5) if scale is None else scale

    qf = q.astype(jnp.float32) * s
    # Rotate kv blocks "down" the ring: after step i, this device holds the
    # shard originally owned by device (my_idx + i) mod n.
    perm = [(j, (j - 1) % n) for j in range(n)]

    q_pos = my_idx * Sq + jnp.arange(Sq)

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        src = (my_idx + i) % n
        k_pos = src * Sk + jnp.arange(Sk)

        if Hkv != H:
            k_loc = jnp.repeat(k_cur, H // Hkv, axis=2)
            v_loc = jnp.repeat(v_cur, H // Hkv, axis=2)
        else:
            k_loc, v_loc = k_cur, v_cur

        logits = jnp.einsum(
            "bqhd,bkhd->bqhk",
            qf,
            k_loc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            cmask = q_pos[None, :, None, None] >= k_pos[None, None, None, :]
            logits = jnp.where(cmask, logits, -jnp.inf)

        m_new = jnp.maximum(m, logits.max(axis=-1))
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bqhk,bkhd->bqhd", p, v_loc.astype(jnp.float32)
        )

        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    m0 = jnp.full((B, Sq, H), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, Sq, H), jnp.float32)
    acc0 = jnp.zeros((B, Sq, H, D), jnp.float32)
    (m, l, acc, _, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n)
    )
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Flash inner kernel: the ring's per-step block math through the Pallas MXU
# kernel (ops/pallas_attention.py) instead of a dense f32 einsum.
#
# Forward: each ring step runs the flash FORWARD on (my q chunk, visiting
# kv chunk), getting a chunk-normalized output plus its logsumexp; chunk
# outputs merge by logsumexp weighting (the same online-softmax algebra the
# kernel uses internally, applied across chunks), so the result is exact
# softmax attention over the full sequence.
#
# Backward: for a chunk pair, the flash backward evaluated with the GLOBAL
# logsumexp/output is exactly the global gradient's contribution from that
# pair (P = exp(logits - lse_global) are the true softmax weights). One ring
# pass computes everything: dq accumulates in place, while dk/dv partial
# accumulators ROTATE WITH their k/v chunks — after n steps every chunk is
# back at its owner carrying its fully-accumulated gradient.
#
# Causality never needs global positions inside the kernel: a visiting chunk
# is either entirely earlier (full attention), the diagonal (locally causal,
# since global row>=col iff local row>=col when offsets are equal), or
# entirely later (skipped) — a 3-way lax.switch around the existing kernels.
# ---------------------------------------------------------------------------


def _flash_chunk_fwd(q, k, v, scale, causal, interpret):
    """Chunk flash forward -> (out [B,S,H,D] normalized, lse [B*H,1,S])."""
    from distributed_machine_learning_tpu.ops.pallas_attention import (
        _default_blocks,
        _flash_forward,
    )

    S, D = q.shape[1], q.shape[-1]
    bq, bk = _default_blocks(S, D, None, None)
    return _flash_forward(
        q, k, v, scale, causal, bq, bk, interpret, with_lse=True
    )


def _flash_chunk_bwd(q, k, v, out, lse, do, scale, causal, interpret,
                     q_side=None):
    """Chunk-pair flash backward with GLOBAL out/lse -> (dq, dk, dv).

    ``q_side``: precomputed (qb, dob, delta) — loop-invariant across the
    ring's k/v chunks, so the caller hoists it out of the scan."""
    from distributed_machine_learning_tpu.ops.pallas_attention import (
        _default_blocks,
        _flash_backward,
    )

    S, D = q.shape[1], q.shape[-1]
    bq, bk = _default_blocks(S, D, None, None, backward=True)
    return _flash_backward(
        q, k, v, out, lse, do, scale, causal, bq, bk, interpret,
        q_side=q_side,
    )


def _lse_weights(lse_old, lse_new, lse_tot, B, H):
    """Merge weights exp(lse - lse_tot) for [B*H,1,S] lse, shaped to
    broadcast over [B, S, H, D] outputs; -inf rows contribute 0."""

    def w(lse):
        safe_tot = jnp.where(jnp.isfinite(lse_tot), lse_tot, 0.0)
        raw = jnp.where(jnp.isfinite(lse), jnp.exp(lse - safe_tot), 0.0)
        bh, _, s = raw.shape
        return raw.reshape(B, H, s).transpose(0, 2, 1)[..., None]

    return w(lse_old), w(lse_new)


def _make_ring_flash(axis_name: str, causal: bool, scale: float,
                     interpret: bool):
    """Build the per-device flash-ring function with its custom VJP.

    A factory (rather than nondiff_argnums on a module-level function) so
    the closure carries the static config; jax caches tracing per factory
    call site, and _ring_local calls this once per trace.
    """

    def fwd_impl(q, k, v):
        n = jax.lax.psum(1, axis_name)
        my_idx = jax.lax.axis_index(axis_name)
        B, Sq, H, D = q.shape
        perm_n = [(j, (j - 1) % n) for j in range(n)]

        def chunk(q_, k_, v_, causal_flag):
            return _flash_chunk_fwd(q_, k_, v_, scale, causal_flag, interpret)

        def step(carry, i):
            acc, lse, k_cur, v_cur = carry
            src = (my_idx + i) % n

            def do_full(_):
                return chunk(q, k_cur, v_cur, False)

            def do_diag(_):
                return chunk(q, k_cur, v_cur, True)

            def do_skip(_):
                return (
                    jnp.zeros_like(q),
                    jnp.full((B * H, 1, Sq), -jnp.inf, jnp.float32),
                )

            if causal:
                branch = jnp.where(src == my_idx, 1,
                                   jnp.where(src < my_idx, 0, 2))
                out_i, lse_i = jax.lax.switch(
                    branch, (do_full, do_diag, do_skip), None
                )
            else:
                out_i, lse_i = do_full(None)

            lse_new = jnp.logaddexp(lse, lse_i)
            w_old, w_i = _lse_weights(lse, lse_i, lse_new, B, H)
            acc = acc * w_old + out_i.astype(jnp.float32) * w_i

            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm_n)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm_n)
            return (acc, lse_new, k_nxt, v_nxt), None

        acc0 = jnp.zeros(q.shape, jnp.float32)
        lse0 = jnp.full((B * H, 1, Sq), -jnp.inf, jnp.float32)
        (acc, lse, _, _), _ = jax.lax.scan(
            step, (acc0, lse0, k, v), jnp.arange(n)
        )
        return acc.astype(q.dtype), lse

    @jax.custom_vjp
    def ring_flash(q, k, v):
        out, _ = fwd_impl(q, k, v)
        return out

    def ring_flash_fwd(q, k, v):
        out, lse = fwd_impl(q, k, v)
        return out, (q, k, v, out, lse)

    def ring_flash_bwd(res, g):
        from distributed_machine_learning_tpu.ops.pallas_attention import (
            _to_bh,
        )

        q, k, v, out, lse = res
        do = g
        n = jax.lax.psum(1, axis_name)
        my_idx = jax.lax.axis_index(axis_name)
        perm = [(j, (j - 1) % n) for j in range(n)]
        # Loop-invariant q side, hoisted out of the scan: the transposes
        # and the delta reduction would otherwise repeat per ring step.
        qb, dob, ob = _to_bh(q), _to_bh(do), _to_bh(out)
        delta = jnp.sum(
            dob.astype(jnp.float32) * ob.astype(jnp.float32), axis=-1
        )[:, None, :]
        q_side = (qb, dob, delta)

        def step(carry, i):
            dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
            src = (my_idx + i) % n

            def pair(causal_flag):
                return _flash_chunk_bwd(
                    q, k_cur, v_cur, out, lse, do, scale, causal_flag,
                    interpret, q_side=q_side,
                )

            def do_full(_):
                return pair(False)

            def do_diag(_):
                return pair(True)

            def do_skip(_):
                return (jnp.zeros_like(q), jnp.zeros_like(k_cur),
                        jnp.zeros_like(v_cur))

            if causal:
                branch = jnp.where(src == my_idx, 1,
                                   jnp.where(src < my_idx, 0, 2))
                dq_i, dk_i, dv_i = jax.lax.switch(
                    branch, (do_full, do_diag, do_skip), None
                )
            else:
                dq_i, dk_i, dv_i = do_full(None)

            dq_acc = dq_acc + dq_i.astype(jnp.float32)
            # dk/dv partials travel WITH their chunk: after n rotations the
            # chunk (and its fully-summed gradient) is back at its owner.
            dk_cur = dk_cur + dk_i.astype(jnp.float32)
            dv_cur = dv_cur + dv_i.astype(jnp.float32)
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
            dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
            return (dq_acc, k_nxt, v_nxt, dk_nxt, dv_nxt), None

        dq0 = jnp.zeros(q.shape, jnp.float32)
        (dq, _, _, dk, dv), _ = jax.lax.scan(
            step,
            (dq0, k, v, jnp.zeros(k.shape, jnp.float32),
             jnp.zeros(v.shape, jnp.float32)),
            jnp.arange(n),
        )
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    ring_flash.defvjp(ring_flash_fwd, ring_flash_bwd)
    return ring_flash


def _use_flash_inner(mode, Sq: int, Sk: int, D: int) -> bool:
    """Resolve the use_flash knob: 'auto' = the measured-win regime on TPU
    (same gate as the softmax->flash route: benchmarks/RESULTS.md).

    The flash chunk kernels assume equal q/kv chunk lengths (self-
    attention over one sharded sequence); cross-length rings stay on the
    dense path (auto) or are rejected (forced True).
    """
    if mode not in ("auto", True, False):
        # bool('false') is True — reject strings so a config typo can't
        # silently force the kernel path.
        raise ValueError(
            f"use_flash must be 'auto', True, or False; got {mode!r}"
        )
    if mode == "auto":
        try:
            on_tpu = jax.default_backend() == "tpu"
        except Exception:  # pragma: no cover
            on_tpu = False
        return on_tpu and Sq == Sk and Sq >= 1024 and D <= 64
    if mode and Sq != Sk:
        raise ValueError(
            f"use_flash=True needs equal q/kv sequence lengths per shard "
            f"(got {Sq} vs {Sk}); the dense ring handles cross-length"
        )
    return mode


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "sp",
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash="auto",
    flash_interpret: bool = False,
) -> jnp.ndarray:
    """Exact softmax attention with the sequence sharded over ``axis_name``.

    q: [B, S, H, D]; k, v: [B, S, Hkv, D] with ``H % Hkv == 0`` —
    grouped-query attention is native on BOTH inner paths: kv chunks rotate
    the ring at kv_heads (per-step ICI payload shrinks by the group factor);
    the dense path broadcasts only inside each step's local compute, and the
    flash path streams grouped kv straight through the Pallas kernels.
    Global arrays (S divisible by the axis size).
    ``batch_axis`` optionally shards batch over a second mesh axis (dp);
    ``head_axis`` optionally shards heads over a third (tp) — heads are
    independent, so tensor parallelism composes with the ring for free.
    Returns [B, S, H, D] with the same sharding.

    ``use_flash``: run each ring step's block attention through the Pallas
    flash kernel instead of the dense einsum — ``"auto"`` (default) picks
    it in the kernel's measured-win regime (TPU, local chunk >= 1024,
    head_dim <= 64); True/False force it. ``flash_interpret`` runs the
    kernels in the Pallas interpreter (CPU tests).
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis_name!r}: {mesh.axis_names}")
    baxis = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    haxis = head_axis if (head_axis and head_axis in mesh.axis_names) else None
    if (
        haxis
        and k.shape[2] != q.shape[2]  # grouped kv only; full-head q and kv
        # failing to divide the axis is the ordinary sharding error
        and k.shape[2] % mesh.shape[haxis] != 0
    ):
        raise ValueError(
            f"grouped kv ({k.shape[2]} heads) cannot shard over head axis "
            f"{haxis!r} (size {mesh.shape[haxis]}); broadcast kv to full "
            f"heads first (models/layers.py does this automatically)"
        )
    spec = P(baxis, axis_name, haxis, None)
    n_shards = mesh.shape[axis_name]
    local_S, D = q.shape[1] // n_shards, q.shape[-1]
    local_Sk = k.shape[1] // n_shards
    if _use_flash_inner(use_flash, local_S, local_Sk, D):
        s = (D ** -0.5) if scale is None else scale

        def local(q_, k_, v_):
            return _make_ring_flash(
                axis_name, causal, s, flash_interpret
            )(q_, k_, v_)

        fn = _shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
        )
        return fn(q, k, v)
    fn = _shard_map(
        partial(_ring_local, axis_name=axis_name, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
