"""Partition-rule trees: regex rules -> per-leaf PartitionSpecs -> shardings.

The first-class sharding layer (ROADMAP item 1): every sharded surface —
trainables, ckpt/ saves, compile-cache keys, the bench flagship — derives
its layout from ONE rule list instead of hand-annotating pytrees.  A rule
list is ``((pattern, PartitionSpec), ...)`` matched against each leaf's
``'/'``-joined key path with **``re.search`` semantics, first match wins**
(the ``match_partition_rules`` idiom from the retrieved snippets; EasyLM /
fmengine lineage).  Patterns may equivalently be tuples of per-component
regexes — ``("ff", "kernel")`` matches any path with adjacent components
matching ``ff`` then ``kernel`` — which is the tuple-path dialect some rule
tables are written in; both dialects resolve identically (golden-tested).

Scalar leaves (rank 0 or one element) never partition.  Unmatched leaves
take ``default`` (replicated) — or raise under ``on_unmatched="error"``,
the strict mode for rule tables that claim full coverage.

Specs are *intent*; :func:`clean_spec` reconciles intent with a concrete
``(mesh, leaf)``: axes the mesh lacks, axes beyond the leaf's rank, and
axes whose size does not divide the dim fall back to ``None`` — so one
rule table serves every mesh shape from ``{"dp": 8}`` to
``{"dp": 2, "tp": 4}`` without edits.

:func:`rules_fingerprint` hashes a rule list into a stable id; compilecache
keys fold it in (with the mesh shape) so a rule-table edit or a reshaped
mesh can never alias a cached sharded executable
(``compilecache.keys.sharded_program_key``).
"""

from __future__ import annotations

import hashlib
import json
import re
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

RuleList = Sequence[Tuple[Any, P]]


def path_str(path) -> str:
    """A jax key path -> ``'/'``-joined string (flax param naming)."""
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(parts)


def _pattern_matches(pattern, path: str) -> bool:
    """One rule pattern against one ``'/'``-joined path.

    String patterns use ``re.search`` (snippet semantics: anchor with
    ``$``/``^`` yourself).  Tuple patterns match when some window of
    ADJACENT path components fullmatches the component regexes in order —
    the tuple-path dialect, equivalent to
    ``search("(^|/)c1/c2(/|$)")`` with each component anchored.
    """
    if isinstance(pattern, (tuple, list)):
        comps = [str(c) for c in pattern]
        parts = path.split("/")
        n = len(comps)
        for i in range(len(parts) - n + 1):
            if all(
                re.fullmatch(c, parts[i + j]) for j, c in enumerate(comps)
            ):
                return True
        return False
    return re.search(str(pattern), path) is not None


def _is_scalar_leaf(leaf) -> bool:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return True
    return len(shape) == 0 or int(np.prod(shape)) == 1


def match_partition_rules(
    rules: RuleList,
    params: Any,
    *,
    default: Optional[P] = P(),
    on_unmatched: str = "default",
) -> Any:
    """Rule list -> a pytree of :class:`PartitionSpec` matching ``params``.

    Scalar leaves are never partitioned (always ``P()``).  A leaf no rule
    matches gets ``default`` — or raises ``ValueError`` when
    ``on_unmatched="error"`` (parity with the snippet, whose rule tables
    end in an explicit catch-all).
    """
    if on_unmatched not in ("default", "error"):
        raise ValueError(
            f"on_unmatched must be 'default' or 'error', got {on_unmatched!r}"
        )

    def assign(path, leaf):
        name = path_str(path)
        if _is_scalar_leaf(leaf):
            return P()
        for pattern, spec in rules:
            if _pattern_matches(pattern, name):
                return spec
        if on_unmatched == "error":
            raise ValueError(f"Partition rule not found for param: {name}")
        return default

    return jax.tree_util.tree_map_with_path(assign, params)


def clean_spec_report(
    spec: P, shape: Tuple[int, ...], axis_sizes: dict
) -> Tuple[P, list]:
    """:func:`clean_spec` over plain ``{axis: size}`` sizes, reporting WHY
    each axis fell away: ``(cleaned_spec, [(dim, axis, reason), ...])``
    with reason in ``"missing-axis"`` / ``"excess-rank"`` /
    ``"non-dividing"``.  Mesh-free on purpose — the jaxlint coverage audit
    (analysis/jaxlint/coverage.py) prices rule tables against mesh shapes
    no local device set can build, and "silently cleaned to None" is
    exactly the information :func:`clean_spec` discards."""
    shape = tuple(int(s) for s in shape or ())
    ndim = len(shape)
    out = []
    drops = []
    for i, axis in enumerate(spec):
        if i >= ndim:
            if axis is not None:
                drops.append((i, axis, "excess-rank"))
            continue
        if axis is None:
            out.append(None)
        elif axis not in axis_sizes:
            out.append(None)
            drops.append((i, axis, "missing-axis"))
        elif shape[i] % int(axis_sizes[axis]) != 0:
            out.append(None)
            drops.append((i, axis, "non-dividing"))
        else:
            out.append(axis)
    return P(*out), drops


def clean_spec(spec: P, leaf, mesh: Mesh) -> P:
    """Reconcile a rule spec with a concrete leaf on a concrete mesh:
    drop axes the mesh lacks, axes beyond the leaf's rank, and axes whose
    mesh size does not divide the dim."""
    shape = tuple(getattr(leaf, "shape", ()) or ())
    cleaned, _ = clean_spec_report(
        spec, shape, {str(k): int(v) for k, v in mesh.shape.items()}
    )
    return cleaned


def shardings_from_rules(
    tree: Any, mesh: Mesh, rules: RuleList, *, on_unmatched: str = "default"
) -> Any:
    """Rule list -> pytree of :class:`NamedSharding` for ``tree`` (specs
    cleaned per leaf/mesh — the one entry point every sharded surface
    uses)."""
    specs = match_partition_rules(rules, tree, on_unmatched=on_unmatched)
    return jax.tree_util.tree_map(
        lambda leaf, spec: NamedSharding(mesh, clean_spec(spec, leaf, mesh)),
        tree, specs,
    )


def make_shard_and_gather_fns(
    partition_specs: Any, mesh: Mesh
) -> Tuple[Any, Any]:
    """Pytrees of (shard_fn, gather_fn) from a pytree of PartitionSpecs —
    the snippet's ``make_shard_and_gather_fns`` idiom over NamedSharding.

    ``shard_fn(x)`` places a host/replicated array onto the mesh per its
    spec (cleaned against the actual leaf); ``gather_fn(x)`` brings a
    sharded array back to a host numpy array (checkpoint export path).
    """

    def make_shard(spec: P) -> Callable:
        def shard(x):
            return jax.device_put(
                x, NamedSharding(mesh, clean_spec(spec, x, mesh))
            )

        return shard

    def make_gather(_spec: P) -> Callable:
        def gather(x):
            return np.array(x)  # device->host copy, never an aliasing view

        return gather

    shard_fns = jax.tree_util.tree_map(make_shard, partition_specs,
                                       is_leaf=lambda x: isinstance(x, P))
    gather_fns = jax.tree_util.tree_map(make_gather, partition_specs,
                                        is_leaf=lambda x: isinstance(x, P))
    return shard_fns, gather_fns


def spec_to_jsonable(spec: P) -> list:
    """A PartitionSpec as a JSON-stable list (axis name, None, or a list of
    names for multi-axis dims) — the rendering fingerprints and checkpoint
    indexes share."""
    out = []
    for axis in spec:
        if isinstance(axis, (tuple, list)):
            out.append([str(a) for a in axis])
        else:
            out.append(None if axis is None else str(axis))
    return out


def spec_from_jsonable(parts: Sequence) -> P:
    """Inverse of :func:`spec_to_jsonable`."""
    axes = []
    for axis in parts or ():
        if isinstance(axis, list):
            axes.append(tuple(str(a) for a in axis))
        else:
            axes.append(None if axis is None else str(axis))
    return P(*axes)


def rules_fingerprint(rules: RuleList) -> str:
    """Stable sha256 id of a rule list (pattern dialect + order + specs all
    significant).  Folded into sharded program keys so a rule edit can
    never alias a cached executable compiled under the old table."""
    payload = []
    for pattern, spec in rules:
        if isinstance(pattern, (tuple, list)):
            pat = ["t"] + [str(c) for c in pattern]
        else:
            pat = ["s", str(pattern)]
        payload.append([pat, spec_to_jsonable(spec)])
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return "pr_" + hashlib.sha256(blob.encode()).hexdigest()[:16]


def mesh_axis_sizes(mesh: Mesh) -> dict:
    """``{axis: size}`` in mesh axis order (JSON-stable; key material)."""
    return {str(k): int(v) for k, v in mesh.shape.items()}
