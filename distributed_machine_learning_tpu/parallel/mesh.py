"""Device-mesh helpers.

TPU-native replacement for the reference's device story (Ray sets
``CUDA_VISIBLE_DEVICES``, every trial hard-codes ``cuda:0`` —
`ray-tune-hpo-regression.py:286`; SURVEY.md §2b D3/D4): trials either own one
core (DeviceManager lease) or span several via a named ``jax.sharding.Mesh``,
with XLA inserting ICI collectives from sharding annotations.

Axis conventions used across the framework:
  ``dp`` — data parallel (batch dimension)
  ``sp`` — sequence parallel (sequence dimension of activations)
  ``tp`` — tensor parallel (hidden/heads dimensions of params+activations)
  ``ep`` — expert parallel (the expert dimension of MoE parameter stacks)
  ``pp`` — pipeline parallel (layer stages; parallel/pipeline.py)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# The framework-wide mesh-axis vocabulary (module docstring above).  A
# PartitionSpec / collective naming an axis outside this set is a typo or
# an import from another stack's convention — no mesh this framework
# builds will ever carry it, so the spec silently cleans to replication
# (jaxlint DML104 mesh-axis-soundness flags exactly this).
CANONICAL_AXES = ("dp", "sp", "tp", "ep", "pp")


def make_mesh(
    axis_sizes: Dict[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a named mesh from {axis: size}. Total size must match #devices.

    Axis order follows dict insertion order; put the fastest-varying axis
    (usually ``tp``) last so it maps to ICI-adjacent cores.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    sizes = {k: int(v) for k, v in axis_sizes.items() if int(v) > 0}
    total = int(np.prod(list(sizes.values()))) if sizes else 1
    if total != len(devices):
        raise ValueError(
            f"mesh axes {sizes} need {total} devices, got {len(devices)}"
        )
    arr = np.array(devices).reshape(*sizes.values())
    return Mesh(arr, tuple(sizes.keys()))


def auto_mesh(
    n_devices: Optional[int] = None, *, tp: int = 1, sp: int = 1, ep: int = 1
) -> Mesh:
    """A mesh over the first n devices: dp fills whatever tp/sp/ep don't use.

    All four axes are always present (size 1 when unused) so shardings that
    name them — P("tp", ...), P("ep", ...) — stay valid for any auto_mesh.
    """
    devices = list(jax.devices())
    n = n_devices or len(devices)
    used = tp * sp * ep
    if n % used != 0:
        raise ValueError(f"{n} devices not divisible by tp*sp*ep={used}")
    return make_mesh(
        {"dp": n // used, "sp": sp, "ep": ep, "tp": tp}, devices[:n]
    )


def batch_sharding(mesh: Mesh, *, shard_seq: bool = False) -> NamedSharding:
    """[batch, seq, ...] arrays: batch over dp, optionally seq over sp."""
    if shard_seq and "sp" in mesh.axis_names:
        return NamedSharding(mesh, P("dp", "sp"))
    return NamedSharding(mesh, P("dp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_devices(mesh: Mesh) -> List:
    return list(mesh.devices.flat)
