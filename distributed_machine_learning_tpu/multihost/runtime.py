"""Multi-host SPMD runtime: jax.distributed + DCN/ICI-aware meshes.

The reference's multi-node story is Ray's gRPC control plane with zero
collectives (SURVEY.md §1 L3, §5 "distributed communication backend" — no
NCCL/MPI anywhere).  The TPU-native framework splits that capability in two:

* **HPO control plane** — driver↔worker TCP supervisors
  (`tune/cluster.py`): many independent trials, metrics/decisions over DCN.
* **One model over many processes** — THIS module: every process runs the
  same jitted program, `jax.distributed` wires the XLA runtime together,
  and collectives ride ICI inside a slice / DCN across slices.  This is
  the NCCL/MPI-equivalent layer, done the XLA way: you never call a
  collective yourself — you annotate shardings on a mesh from
  ``multihost_mesh()`` and XLA inserts/schedules them.

Mesh layout rule (the "How to Scale Your Model" recipe): put ``dp``
(gradient all-reduce once per step — latency-tolerant) across hosts on DCN,
and the chatty axes (``tp``/``sp``/``ep`` — per-layer collectives) inside a
host/slice on ICI.  ``multihost_mesh`` encodes exactly that via
``mesh_utils.create_hybrid_device_mesh``.

Single-process (tests, one chip, CPU meshes) every function degrades to a
sensible no-op/local equivalent, so the same training script runs unchanged
from a laptop CPU mesh to a multi-host pod — launch it once per host with
the coordinator env set (or under a cluster manager jax auto-detects), or
let the cluster head broker the whole bootstrap (``multihost/bootstrap.py``).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_initialized = False


class BarrierTimeout(RuntimeError):
    """A deadline-gated :func:`barrier` expired.  Carries the process ids
    that never arrived (``absent``) so callers — and the flight dump fired
    before the raise — can name the straggler instead of just timing out."""

    def __init__(self, name: str, absent: Sequence[int], deadline_s: float):
        self.name = name
        self.absent = sorted(int(p) for p in absent)
        self.deadline_s = float(deadline_s)
        super().__init__(
            f"barrier {name!r} expired after {deadline_s:.1f}s; "
            f"absent process ids: {self.absent}"
        )


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> bool:
    """Join (or skip joining) the jax.distributed runtime. Idempotent.

    Args default from the standard env (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID`` — also set by TPU pod
    metadata, which ``jax.distributed.initialize()`` auto-detects with no
    args). Returns True when a multi-process runtime is active after the
    call, False for the single-process fallback (no coordinator configured
    and none auto-detectable). Call BEFORE any other jax API touches the
    backend — device enumeration pins the runtime.
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    num_processes = (
        num_processes if num_processes is not None
        else int(env_np) if env_np else None
    )
    process_id = (
        process_id if process_id is not None
        else int(env_pid) if env_pid else None
    )
    in_managed_cluster = any(
        os.environ.get(k)
        for k in ("TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS",
                  "CLOUD_TPU_TASK_ID")
    )
    if coordinator_address is None and not in_managed_cluster:
        return False  # single-process: nothing to join
    from distributed_machine_learning_tpu import obs

    t0 = time.monotonic()
    obs.event("multihost_initialize", {
        "coordinator": coordinator_address,
        "num_processes": num_processes,
        "process_id": process_id,
    })
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    obs.event("multihost_initialized", {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "join_s": round(time.monotonic() - t0, 3),
    })
    return jax.process_count() > 1


def is_coordinator() -> bool:
    """Process 0 — the one that should write checkpoints/logs/results."""
    return jax.process_index() == 0


def multihost_mesh(
    *, tp: int = 1, sp: int = 1, ep: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Global mesh over every process's devices, DCN/ICI-aware.

    ``dp`` fills whatever tp/sp/ep leave over. Multi-process: ``dp`` spans
    hosts (its once-per-step gradient reduction tolerates DCN latency) and
    tp/sp/ep must fit INSIDE one process's devices so their per-layer
    collectives stay on ICI — sizes that straddle hosts raise.
    Single-process: plain mesh over the local devices (axis order dp, sp,
    ep, tp — tp last = ICI-adjacent, same convention as mesh.auto_mesh).
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    n_procs = jax.process_count()
    used = tp * sp * ep
    if len(devices) % used != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible by tp*sp*ep={used}"
        )
    dp = len(devices) // used
    axis_names = ("dp", "sp", "ep", "tp")
    if n_procs == 1:
        arr = np.array(devices).reshape(dp, sp, ep, tp)
        return Mesh(arr, axis_names)

    per_host = len(devices) // n_procs
    if used > per_host or per_host % used != 0:
        raise ValueError(
            f"tp*sp*ep={used} must divide one host's {per_host} devices: "
            f"tensor/sequence/expert collectives are per-layer traffic and "
            f"must stay on ICI, not DCN (put dp across hosts instead)"
        )
    from jax.experimental import mesh_utils

    ici_dp = per_host // used
    n_slices = len({getattr(d, "slice_index", None) for d in devices})
    # Granule choice: by default create_hybrid_device_mesh groups devices
    # by slice_index; when slices don't map 1:1 to processes (single-slice
    # multi-host pods, and multi-process CPU test clusters where every
    # device reports slice 0 — caught by the 2-process CPU test), group by
    # process instead. Either way the helper keeps the ICI-topology-aware
    # device ordering within each granule.
    arr = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(ici_dp, sp, ep, tp),          # within a granule (ICI)
        dcn_mesh_shape=(n_procs, 1, 1, 1),        # across granules (DCN)
        devices=devices,
        process_is_granule=(n_slices != n_procs),
    )
    return Mesh(arr.reshape(dp, sp, ep, tp), axis_names)


def spanning_mesh(mesh_shape: Dict[str, int]) -> Mesh:
    """A named mesh of the given axis sizes over ALL processes' devices —
    the process-spanning twin of ``parallel.mesh.make_mesh`` (which builds
    over an explicit local device list).

    Axis sizes must multiply to the global device count; the first axis
    (by convention ``dp``) spans processes, later axes stay inside one
    process's devices — enforced by delegating to :func:`multihost_mesh`
    and then relabeling to the caller's axis names in order.  Single
    process: identical to ``make_mesh`` over ``jax.devices()``.
    """
    sizes = {str(k): int(v) for k, v in mesh_shape.items()}
    total = 1
    for v in sizes.values():
        total *= v
    n = jax.device_count()
    if total != n:
        raise ValueError(
            f"mesh_shape {sizes} needs {total} devices; the process-"
            f"spanning runtime has {n} "
            f"({jax.process_count()} processes x "
            f"{jax.local_device_count()} local)"
        )
    non_dp = 1
    for k, v in sizes.items():
        if k != "dp":
            non_dp *= v
    base = multihost_mesh(tp=non_dp)
    arr = base.devices.reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes.keys()))


def serving_mesh(axis: str = "tp") -> Mesh:
    """A single-axis mesh over EVERY process's devices — the pod-slice
    serving layout (``serve/gang.py``).

    Training meshes keep tp/sp/ep inside one host (``multihost_mesh``
    raises otherwise: per-layer collectives belong on ICI).  Serving is
    the case where that rule deliberately bends — a model sharded to fit
    training on a multi-process mesh cannot be served at all unless its
    tensor axis is allowed to span processes, and inference traffic is a
    forward pass per request, not per-step gradient exchange.  Device
    order is canonical (process index, then device id), so every member
    of a gang builds the IDENTICAL mesh and the compiled programs agree.
    """
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return Mesh(np.array(devs), (str(axis),))


def global_batch_array(
    host_local: np.ndarray, mesh: Mesh, spec: P = P("dp")
) -> jax.Array:
    """Assemble a global sharded array from each host's LOCAL shard.

    The multi-host data-loading contract: every host loads only its slice
    of the batch (no host ever materializes the global array — the analogue
    of the reference's Ray object-store broadcast, without the broadcast),
    and this stitches the shards into one global ``jax.Array`` addressable
    under jit. Single-process it is just ``device_put`` with the sharding.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(host_local, sharding)
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        host_local, mesh, spec
    )


def stage_global(global_np: np.ndarray, sharding) -> jax.Array:
    """Stage a GLOBAL host array onto a (possibly process-spanning)
    sharding, reading only the slices this process's devices address.

    The dual of :func:`global_batch_array`: there every host holds only its
    shard; here every host holds (or can index) the full array — the
    regression trainables' epoch slabs — and the per-process callback
    slices out exactly the addressable shards, so the ``process_index``
    offset is derived from the sharding instead of hand-computed (the
    DML016 failure class).  Single-process: plain ``device_put``.
    ``sharding`` is a ``NamedSharding`` (or ``(mesh, spec)`` tuple).
    """
    if isinstance(sharding, tuple):
        sharding = NamedSharding(*sharding)
    if jax.process_count() == 1:
        return jax.device_put(global_np, sharding)
    return jax.make_array_from_callback(
        tuple(global_np.shape), sharding, lambda idx: global_np[idx]
    )


def barrier(
    name: str = "barrier", deadline_s: Optional[float] = None
) -> None:
    """Block until every process reaches this point (no-op single-process).

    Use at phase boundaries (before reading a peer's checkpoint, after
    coordinator-only writes) — NOT inside the step loop, where jit+XLA
    already orders collectives.

    With ``deadline_s`` the wait is bounded: each process first marks its
    arrival in the coordination service's key-value store, and on expiry
    the flight recorder is dumped naming the ABSENT process ids before
    :class:`BarrierTimeout` raises — a straggler host becomes a named
    forensic event, not an indefinite hang.
    """
    if jax.process_count() == 1:
        return
    if deadline_s is None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
        return
    client = _coordination_client()
    if client is None:  # pragma: no cover - no runtime; degrade to sync
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
        return
    key_prefix = f"dml_barrier/{name}/"
    try:
        client.key_value_set(
            f"{key_prefix}p{jax.process_index()}", str(time.time())
        )
    except Exception:  # noqa: BLE001 - arrival mark is forensics only
        pass
    try:
        client.wait_at_barrier(
            f"dml_barrier:{name}", int(max(deadline_s, 0.001) * 1000)
        )
    except Exception as exc:
        absent = _absent_processes(client, key_prefix)
        from distributed_machine_learning_tpu import obs

        obs.event("barrier_timeout", {"name": name, "absent": absent})
        obs.dump_flight_recorder(
            f"barrier_timeout_{name}",
            extra={
                "barrier": name,
                "deadline_s": deadline_s,
                "absent_process_ids": absent,
                "process_index": jax.process_index(),
                "error": repr(exc),
            },
        )
        raise BarrierTimeout(name, absent, deadline_s) from exc


def _coordination_client():
    """The distributed-runtime coordination client, or None outside a
    multi-process runtime (or on a jax without the internal surface)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client
    except Exception:  # noqa: BLE001 - internal API moved; degrade
        return None


def _absent_processes(client, key_prefix: str) -> List[int]:
    """Process ids that never marked arrival under ``key_prefix``."""
    present: set = set()
    try:
        for key, _val in client.key_value_dir_get(key_prefix):
            tail = key.rsplit("/", 1)[-1]
            if tail.startswith("p"):
                present.add(int(tail[1:]))
    except Exception:  # noqa: BLE001 - dir scan is best-effort forensics
        pass
    return [p for p in range(jax.process_count()) if p not in present]


def broadcast_from_coordinator(pytree):
    """Every process returns the coordinator's value (process-consistent
    config/HPO decisions without a side channel). Identity single-process."""
    if jax.process_count() == 1:
        return pytree
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(pytree)


def host_snapshot(tree):
    """Device→host readback for checkpointing that is safe on ANY topology.

    Fully-addressable leaves (single-process arrays, replicated values)
    become real numpy copies — same donation-safety contract as the
    trainables' ``_host`` (a view would alias a donated buffer).  A
    process-SPANNING leaf cannot be gathered to one host without an
    all-gather nobody asked for, so it is returned as-is: the sharded
    checkpoint writer serializes exactly the shards each process holds
    (``ckpt/format.py``), which is the multi-host save contract.
    """
    def snap(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return x
        if isinstance(x, jax.Array):
            return np.array(x, copy=True)
        return np.asarray(x) if isinstance(x, np.ndarray) else x

    return jax.tree.map(snap, tree)


_skew_monitor = None


def check_gang_skew(
    seconds: float,
    label: str = "epoch",
    ratio_threshold: float = 1.75,
    sustain: int = 2,
):
    """Per-gang-member straggler detection for one round (epoch).

    Every member calls this with its own round duration; the values are
    allgathered (one tiny DCN collective) and judged by
    ``perf.anomaly.GangSkewMonitor`` — a member sustained past
    ``ratio_threshold`` x its peers' median becomes a named incident:
    ``perf_straggler[process_<id>]`` in the registry plus a flight dump
    carrying the full round timings.  Counters/dumps fire on the
    COORDINATOR only (the head aggregates each incident once); every
    member still gets the straggler list back so a trainable can stamp
    it into its records.  No-op (empty list) single-process.

    MUST be called by every process of the gang (it is a collective) —
    the trainables gate it on ``config["perf_gang_skew"]`` which rides
    the broadcast config, so all members agree."""
    if jax.process_count() == 1:
        return []
    from jax.experimental import multihost_utils

    from distributed_machine_learning_tpu.perf.anomaly import (
        GangSkewMonitor,
    )

    vals = np.asarray(
        multihost_utils.process_allgather(np.float64(float(seconds)))
    ).ravel()
    values = {i: float(v) for i, v in enumerate(vals)}
    global _skew_monitor
    if _skew_monitor is None:
        _skew_monitor = GangSkewMonitor(
            ratio_threshold=ratio_threshold, sustain=sustain
        )
    return _skew_monitor.observe_round(
        values, label=label, report=is_coordinator()
    )


def process_topology() -> Dict[str, object]:
    """The process-layout identity of this runtime: process count plus the
    per-process local device counts (sorted by process index).

    This is what folds into compile-cache keys for process-spanning
    programs (``compilecache.keys``): the SAME mesh shape lowered over a
    different process decomposition produces different cross-process
    collectives, so the key must split — and the same topology on another
    gang must NOT split, so the layout is canonical (no device ids, no
    hostnames).
    """
    counts: Dict[int, int] = {}
    for d in jax.devices():
        counts[d.process_index] = counts.get(d.process_index, 0) + 1
    return {
        "process_count": jax.process_count(),
        "local_device_counts": [
            counts.get(i, 0) for i in range(jax.process_count())
        ],
    }


def describe() -> Dict[str, int]:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
