"""Head-brokered jax.distributed bootstrap for gang trials.

A gang is N fresh worker processes that together run ONE trial over a
process-spanning mesh.  The cluster head (``tune/cluster.py``) brokers the
bootstrap — it assigns the coordinator address and dense process ids and
ships each member a :class:`GangSpec` through the spawn environment — and
every member gates on an all-processes-joined :func:`join_gang` barrier
with a deadline, so a member that never comes up turns into a named
forensic event (flight dump listing the absent process ids) plus a
:class:`~distributed_machine_learning_tpu.multihost.runtime.BarrierTimeout`
instead of an indefinite hang in the first collective.

Why fresh processes: ``jax.distributed.initialize`` must run BEFORE the
backend initializes, and a long-lived worker supervisor enumerated its
devices long ago — so gang members are spawned per trial
(``multihost/spawn.py``), exactly like the process-per-trial executor's
children.
"""

from __future__ import annotations

import json
import os
import socket
from dataclasses import asdict, dataclass
from typing import Dict, Optional

GANG_SPEC_ENV = "DML_GANG_SPEC"

# Default all-members-joined deadline.  Generous: a gang member's cold
# start is a fresh interpreter + jax import + distributed join, and the
# whole point of the deadline is naming stragglers, not racing them.
DEFAULT_JOIN_DEADLINE_S = 120.0


@dataclass
class GangSpec:
    """Everything one gang member needs to join its runtime.

    Assigned by the HEAD (never self-elected): ``coordinator_address`` is
    member 0's host plus a port that member 0's supervisor reserved
    (``gang_prepare`` frame), and ``process_id`` is dense in dispatch
    order so the dp axis's process decomposition is deterministic.
    """

    gang_id: str
    coordinator_address: str
    num_processes: int
    process_id: int
    local_device_count: int
    join_deadline_s: float = DEFAULT_JOIN_DEADLINE_S

    def to_env(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_env(cls, raw: Optional[str] = None) -> Optional["GangSpec"]:
        raw = raw if raw is not None else os.environ.get(GANG_SPEC_ENV)
        if not raw:
            return None
        try:
            return cls(**json.loads(raw))
        except (ValueError, TypeError):
            return None


def allocate_coordinator_port(host: str = "127.0.0.1") -> int:
    """Reserve a free TCP port on ``host`` for a gang's jax.distributed
    coordinator (member 0 binds it when it initializes).  Runs on the
    MEMBER-0 supervisor — only that host knows its own free ports."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def join_gang(spec: GangSpec) -> Dict[str, int]:
    """Join the gang's distributed runtime and gate on the all-joined
    barrier.  Returns :func:`runtime.describe` on success; on a barrier
    deadline expiry the flight recorder has already been dumped naming the
    absent process ids and ``BarrierTimeout`` propagates (the member exits
    with an error frame; the head tears the gang down and requeues).
    """
    from distributed_machine_learning_tpu import obs
    from distributed_machine_learning_tpu.multihost import runtime

    with obs.span("multihost.bootstrap", {
        "gang_id": spec.gang_id,
        "process_id": spec.process_id,
        "num_processes": spec.num_processes,
    }):
        runtime.initialize(
            coordinator_address=spec.coordinator_address,
            num_processes=spec.num_processes,
            process_id=spec.process_id,
        )
        import jax

        if jax.process_count() != spec.num_processes:
            raise RuntimeError(
                f"gang {spec.gang_id}: joined a runtime of "
                f"{jax.process_count()} processes, expected "
                f"{spec.num_processes}"
            )
        # All-members-joined gate: no member proceeds to data loading or
        # compilation until the whole gang exists — a straggler here is a
        # named flight-dump + BarrierTimeout, not a hang in collective #1.
        runtime.barrier(
            f"gang_join:{spec.gang_id}", deadline_s=spec.join_deadline_s
        )
        d = runtime.describe()
        obs.event("gang_joined", {"gang_id": spec.gang_id, **d})
        return d
