"""Worker-supervisor side of gang trials: spawn + relay one gang member.

``jax.distributed.initialize`` must run BEFORE the backend initializes,
and a long-lived worker supervisor enumerated its devices at startup — so
each gang member runs in a FRESH subprocess
(``multihost/_gang_child.py``), exactly like the process-per-trial
executor's children, speaking the same length-prefixed pickle protocol
over binary stdio:

    parent -> child   {"trial_id", "incarnation", "config",
                       "trainable": bytes, "restore_path",
                       "checkpoint_dir", "checkpoint_format",
                       "start_iteration", "obs"}          (init)
    child  -> parent  ("joined", describe_dict)   (gang bootstrap done)
    child  -> parent  ("result", metrics, ckpt_path|None)  (coordinator)
    parent -> child   ("decision", "continue"|"stop"|"pause")
    child  -> parent  ("beat",)                   (coordinator heartbeat)
    child  -> parent  ("complete",) | ("error", traceback_str)

The supervisor's relay thread (``tune/cluster.py``) forwards these up the
control plane and routes the head's decisions back down.  ``kill()`` is
the gang-teardown path: SIGKILL, because a member wedged in a collective
whose peer died will not honour SIGTERM from native code.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
from typing import Dict, List, Optional

from distributed_machine_learning_tpu.multihost.bootstrap import (
    GANG_SPEC_ENV,
    GangSpec,
)
from distributed_machine_learning_tpu.tune._process_child import (
    read_frame,
    write_frame,
)


def member_child_env(
    spec: GangSpec,
    devices: Optional[List] = None,
    platform: Optional[str] = None,
    base_env: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """The spawn environment for one gang member.

    Device visibility is fixed HERE (the TPU analogue of per-actor
    ``CUDA_VISIBLE_DEVICES``): on TPU the leased local group becomes
    ``TPU_VISIBLE_CHIPS``; on CPU the member gets exactly
    ``spec.local_device_count`` virtual devices.  Any inherited
    ``JAX_COORDINATOR_*`` env is stripped — the :class:`GangSpec` is the
    single source of bootstrap truth for a gang child.
    """
    env = dict(base_env if base_env is not None else os.environ)
    env[GANG_SPEC_ENV] = spec.to_env()
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        env.pop(var, None)
    # The axon sitecustomize claims the TPU tunnel at interpreter start;
    # a gang member must never race the supervisor for it.
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    )
    platform = platform or env.get("JAX_PLATFORMS", "")
    if platform.startswith("tpu") and devices:
        env["TPU_VISIBLE_CHIPS"] = ",".join(
            str(getattr(d, "id", i)) for i, d in enumerate(devices)
        )
    else:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            env.get("XLA_FLAGS", ""),
        ).strip()
        env["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count="
              f"{spec.local_device_count}"
        ).strip()
    return env


class GangChildHandle:
    """One spawned gang member and its frame pipes.

    ``module`` selects the child entrypoint: the default trains one gang
    trial (``multihost/_gang_child.py``); the serving plane spawns its
    members with ``serve/_gang_member.py`` — same spec env, same frame
    pipes, same SIGKILL teardown."""

    DEFAULT_MODULE = "distributed_machine_learning_tpu.multihost._gang_child"

    def __init__(
        self,
        spec: GangSpec,
        init_msg: Dict,
        devices: Optional[List] = None,
        platform: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        module: Optional[str] = None,
    ):
        self.spec = spec
        self.proc = subprocess.Popen(
            [sys.executable, "-m", module or self.DEFAULT_MODULE],
            env=env if env is not None else member_child_env(
                spec, devices, platform
            ),
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL if os.environ.get(
                "DML_GANG_CHILD_QUIET"
            ) else None,
        )
        write_frame(self.proc.stdin, init_msg)

    def read(self):
        """Next child frame; raises EOFError when the child is gone."""
        return read_frame(self.proc.stdout)

    def send_decision(self, decision: str) -> None:
        write_frame(self.proc.stdin, ("decision", decision))

    def kill(self) -> None:
        """Gang teardown: SIGKILL (a member wedged in a collective whose
        peer died sits in native code; SIGTERM may never be delivered)."""
        if self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    @property
    def returncode(self) -> Optional[int]:
        return self.proc.poll()
