"""multihost/ — multi-controller SPMD: meshes that span processes.

Grown from the seed's thin ``parallel/multihost.py`` (ISSUE 14 tentpole).
Everything the stack built so far — the sharded flagship, streaming
prefetch, in-device PBT, the AOT cache — assumed every mesh device lives in
ONE process; the cluster layer only leased contiguous *local* device
groups.  This package is the missing multi-controller layer, split the way
the Podracer/Gemma pod setups split it (PAPERS.md):

* :mod:`runtime` — the process-local SPMD runtime: ``initialize`` (join
  ``jax.distributed``), deadline-gated :func:`barrier` with
  absent-process forensics, :func:`multihost_mesh` (DCN/ICI-aware),
  :func:`global_batch_array` / :func:`stage_global` (per-host shard
  loading — no host ever materializes a peer's slice),
  :func:`broadcast_from_coordinator`, :func:`host_snapshot`
  (checkpoint-safe device→host readback that leaves process-spanning
  arrays sharded), and :func:`process_topology` (the identity that folds
  into compile-cache keys).
* :mod:`bootstrap` — head-brokered gang bootstrap: the cluster head
  assigns coordinator address + process ids (:class:`GangSpec`, shipped
  to gang children over the spawn env), and every member gates on an
  all-processes-joined barrier with a deadline; expiry dumps the flight
  recorder naming the absent process ids.
* :mod:`gang` — driver-side gang bookkeeping for ``run_distributed(
  processes_per_trial=N)``: one trial owns a DP×TP mesh spanning N
  worker processes; any member death tears the gang down and requeues
  the trial from its newest valid checkpoint.
* :mod:`spawn` — worker-supervisor side: run one gang member as a fresh
  subprocess (``jax.distributed`` must join BEFORE backend init, which a
  long-lived supervisor already did) and relay its report/decision/
  heartbeat frames to the cluster control plane.

Single-process, every entry point degrades to a sensible no-op/local
equivalent — the same training script runs unchanged from a laptop CPU
mesh to a pod.
"""

from distributed_machine_learning_tpu.multihost.runtime import (
    BarrierTimeout,
    barrier,
    broadcast_from_coordinator,
    check_gang_skew,
    describe,
    global_batch_array,
    host_snapshot,
    initialize,
    is_coordinator,
    multihost_mesh,
    process_topology,
    stage_global,
)
from distributed_machine_learning_tpu.multihost.bootstrap import (
    GangSpec,
    join_gang,
)

__all__ = [
    "BarrierTimeout",
    "GangSpec",
    "barrier",
    "broadcast_from_coordinator",
    "check_gang_skew",
    "describe",
    "global_batch_array",
    "host_snapshot",
    "initialize",
    "is_coordinator",
    "join_gang",
    "multihost_mesh",
    "process_topology",
    "stage_global",
]
