"""Driver-side gang bookkeeping for ``run_distributed(processes_per_trial=N)``.

A :class:`Gang` is the head's record of one trial's N-process mesh: which
worker supervisor hosts each member, which control-plane slot each member
occupies, how far the bootstrap has progressed, and the join deadline the
head enforces (ISSUE 14: dispatch is GATED on all-processes-joined with a
deadline — a member that never comes up becomes a flight dump naming the
absent process ids plus a teardown/requeue, never a silent hang).

The cluster event loop (``tune/cluster.py``) drives all state transitions;
this module is deliberately passive data + predicates so the protocol
stays readable in one place there.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set


@dataclass
class GangMember:
    worker: Any          # cluster.RemoteWorker
    slot: int
    process_id: int
    done: bool = False   # terminal frame (or slot release) seen


@dataclass
class Gang:
    """One trial's process-spanning execution record on the head."""

    gang_id: str
    trial_id: str
    incarnation: int
    members: List[GangMember]
    # Lifecycle: "preparing" (waiting for member 0's supervisor to reserve
    # a coordinator port) -> "bootstrapping" (members spawned, waiting for
    # all gang_joined frames) -> "running".
    state: str = "preparing"
    coordinator_address: Optional[str] = None
    joined: Set[int] = field(default_factory=set)
    join_deadline: float = 0.0     # monotonic; 0 = not yet armed
    prepare_deadline: float = 0.0  # monotonic; bounds the port reservation

    @property
    def num_processes(self) -> int:
        return len(self.members)

    @property
    def coordinator(self) -> GangMember:
        return self.members[0]

    def member(self, process_id: int) -> Optional[GangMember]:
        for m in self.members:
            if m.process_id == int(process_id):
                return m
        return None

    def arm_join_deadline(self, deadline_s: float) -> None:
        self.state = "bootstrapping"
        self.join_deadline = time.monotonic() + float(deadline_s)

    def mark_joined(self, process_id: int) -> bool:
        """Record one member's bootstrap completion; True when the gang
        just became fully joined."""
        self.joined.add(int(process_id))
        if self.state == "bootstrapping" and self.all_joined():
            self.state = "running"
            return True
        return False

    def all_joined(self) -> bool:
        return len(self.joined) >= self.num_processes

    def absent_ids(self) -> List[int]:
        """Process ids that have not joined — the bootstrap-timeout dump's
        payload."""
        return [
            m.process_id for m in self.members
            if m.process_id not in self.joined
        ]

    def join_expired(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        return (
            self.state == "bootstrapping"
            and self.join_deadline > 0.0
            and now > self.join_deadline
            and not self.all_joined()
        )

    def prepare_expired(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        return (
            self.state == "preparing"
            and self.prepare_deadline > 0.0
            and now > self.prepare_deadline
        )

    def workers(self) -> List[Any]:
        return [m.worker for m in self.members]

    def describe(self) -> Dict[str, Any]:
        return {
            "gang_id": self.gang_id,
            "trial_id": self.trial_id,
            "incarnation": self.incarnation,
            "state": self.state,
            "coordinator_address": self.coordinator_address,
            "members": [
                {"worker": m.worker.address, "slot": m.slot,
                 "process_id": m.process_id, "joined":
                     m.process_id in self.joined}
                for m in self.members
            ],
        }
