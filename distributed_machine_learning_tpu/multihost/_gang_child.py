"""Gang-member child: one process of a trial's process-spanning mesh.

Spawned by a worker supervisor (``multihost/spawn.py``) with its
:class:`~distributed_machine_learning_tpu.multihost.bootstrap.GangSpec`
in the environment.  Joins the gang's ``jax.distributed`` runtime BEFORE
any backend use, gates on the all-joined barrier, then runs the trainable
under an SPMD-aware session:

* **Only the coordinator (gang process 0) reports.**  Its ``report``
  sends the result frame up the control plane and blocks on the head's
  decision; every OTHER member's ``report`` joins a
  ``broadcast_from_coordinator`` of that decision instead — so all N
  processes take the same continue/stop/pause branch without a side
  channel, and the head sees exactly one metric stream per trial.
* **Every member checkpoints.**  A process-spanning pytree can only be
  saved by all its owners (``ckpt/format.py`` writes per-process chunks;
  process 0 writes the index/COMMIT after the all-chunks barrier), so the
  save happens HERE on every process before the coordinator's result
  frame names the generation.
* **Chaos reaches gangs.**  ``DML_CHAOS_PLAN`` rides the spawn env;
  ``kill_process_at`` hard-exits THIS member at its scheduled report
  boundary — the mid-collective member death the gang teardown path
  exists for.
"""

from __future__ import annotations

import os
import sys
import traceback

from distributed_machine_learning_tpu.tune._process_child import (
    read_frame,
    write_frame,
)

DECISION_CODES = {"continue": 0, "stop": 1, "pause": 2}
DECISION_NAMES = {v: k for k, v in DECISION_CODES.items()}


class _TrialStub:
    def __init__(self, trial_id: str, config: dict):
        self.trial_id = trial_id
        self.config = config


def main() -> None:
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    sys.stdout = sys.stderr  # user prints must not corrupt the frame stream

    try:
        init = read_frame(stdin)
    except EOFError:
        return  # parent died before dispatching

    try:
        from distributed_machine_learning_tpu import chaos
        from distributed_machine_learning_tpu.multihost.bootstrap import (
            GangSpec,
        )

        chaos.activate_from_env()
        spec = GangSpec.from_env()
        if spec is None:
            raise RuntimeError("gang child spawned without DML_GANG_SPEC")

        import jax

        # Decide from the ENV only — jax.default_backend() would
        # initialize the backend, which must not happen before
        # jax.distributed.initialize below.
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            try:
                # Cross-process CPU collectives need a backend; gloo ships
                # in jaxlib.
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except Exception:  # noqa: BLE001 - knob renamed on newer jax
                pass

        from distributed_machine_learning_tpu import obs
        from distributed_machine_learning_tpu.compilecache import (
            enable_persistent_cache,
        )
        from distributed_machine_learning_tpu.multihost import (
            bootstrap,
            runtime,
        )

        obs.configure_from_frame(
            init.get("obs"),
            label=f"gang{spec.process_id}-{os.getpid()}",
        )
        # Join BEFORE the persistent-cache attach (which touches jax
        # config, not the backend) and before any device enumeration.
        described = bootstrap.join_gang(spec)
        enable_persistent_cache()
        write_frame(stdout, ("joined", described))

        import cloudpickle
        import numpy as np

        from distributed_machine_learning_tpu.tune import (
            checkpoint as ckpt_lib,
        )
        from distributed_machine_learning_tpu.tune.session import (
            PauseTrial,
            Session,
            StopTrial,
            set_session,
        )

        trainable = cloudpickle.loads(init["trainable"])
        trial_id = init["trial_id"]
        config = dict(init["config"])
        coordinator = runtime.is_coordinator()
        ckpt_dir = init.get("checkpoint_dir")
        ckpt_format = init.get("checkpoint_format", "sharded")
        iteration = [int(init.get("start_iteration", 0))]

        def _broadcast_decision(local: str) -> str:
            """All members leave with the coordinator's decision."""
            code = runtime.broadcast_from_coordinator(
                np.int32(DECISION_CODES.get(local, 0))
            )
            return DECISION_NAMES[int(code)]

        def report_fn(metrics, checkpoint) -> str:
            plan = chaos.active_plan()
            if plan is not None:
                # The gang fault class: ONE member hard-dies at a report
                # boundary; its peers are left mid-collective for the
                # teardown path to reap.
                plan.maybe_kill_process(
                    trial_id, iteration[0] + 1, spec.process_id,
                    incarnation=int(init.get("incarnation", 1)),
                )
                if coordinator:
                    plan.maybe_crash_trial(trial_id, iteration[0] + 1)
            iteration[0] += 1
            ckpt_path = None
            if checkpoint is not None and ckpt_dir:
                # Every member writes its shards; the format's internal
                # barriers order chunks before process 0's index/COMMIT.
                ckpt_path = ckpt_lib.checkpoint_path(
                    ckpt_dir, iteration[0], ckpt_format
                )
                ckpt_lib.save_checkpoint(ckpt_path, checkpoint)
            if coordinator:
                write_frame(
                    stdout, ("result", dict(metrics), ckpt_path)
                )
                msg = read_frame(stdin)
                assert msg[0] == "decision", msg
                return _broadcast_decision(msg[1])
            return _broadcast_decision("continue")

        import time as _time

        last_beat = [0.0]

        def heartbeat_fn() -> None:
            if not coordinator:
                return
            now = _time.monotonic()
            if now - last_beat[0] >= 0.05:
                last_beat[0] = now
                write_frame(stdout, ("beat",))

        restore_path = init.get("restore_path")

        def checkpoint_loader():
            if not restore_path:
                return None
            # Every member restores the SAME full host tree from shared
            # storage (the resharding restore's single-process side —
            # free, per ckpt/format.py); the trainable re-shards it onto
            # the live spanning mesh.
            tree, used, used_it = ckpt_lib.load_checkpoint_with_fallback(
                restore_path, ckpt_dir,
            )
            if used != restore_path and coordinator:
                print(
                    f"[gang] {trial_id}: restore fell back "
                    f"{restore_path} -> {used} (it={used_it})",
                    flush=True,
                )
            return tree

        set_session(Session(
            _TrialStub(trial_id, config),
            report_fn,
            checkpoint_loader,
            list(jax.devices()),
            heartbeat_fn=heartbeat_fn,
        ))
        try:
            with obs.span("trial", {
                "trial_id": trial_id,
                "incarnation": int(init.get("incarnation", 0)),
                "gang_id": spec.gang_id,
                "process_id": spec.process_id,
            }):
                trainable(config)
            obs.flush()  # BEFORE the terminal frame: the supervisor may
            write_frame(stdout, ("complete",))  # reap us right after it
        except (StopTrial, PauseTrial):
            obs.flush()
            write_frame(stdout, ("complete",))
        finally:
            set_session(None)
            obs.flush()
    except BaseException:  # noqa: BLE001 - everything goes to the parent
        try:
            write_frame(stdout, ("error", traceback.format_exc()))
        except (OSError, ValueError):
            pass


if __name__ == "__main__":
    main()
