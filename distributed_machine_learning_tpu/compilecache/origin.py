"""Compile-artifact origin: pack, ship, and install cache entries by key.

The cluster head acts as the origin (``tune/cluster.py``): a worker about
to run a trial asks the head for artifacts under the trial's program key
BEFORE compiling locally; a worker that did compile publishes what the
compile produced.  What travels is the set of files the compile added to
the worker's local cache directories — persistent-XLA-cache entries and/or
AOT serialized executables — so the receiving worker's next jit call
resolves as a cache hit instead of a backend compile.

These helpers are deliberately transport-agnostic (the cluster reuses its
existing length-prefixed control-plane frames): ``snapshot_cache_dir`` /
``pack_artifacts`` on the publishing side, ``install_artifacts`` on the
receiving side, :class:`ArtifactRegistry` on the head.

Paths are flattened to basenames and re-rooted under the receiver's own
cache directory; ``install_artifacts`` rejects any name that would escape
it (the control plane is trusted-network, but a path traversal bug would
be a path traversal bug regardless).
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Dict, List, Optional, Sequence, Set
from distributed_machine_learning_tpu.analysis.locks import named_lock


def snapshot_cache_dir(directory: Optional[str]) -> Set[str]:
    """The file names currently in ``directory`` (recursive, relative
    paths) — diffed after a compile to find what it produced."""
    names: Set[str] = set()
    if not directory or not os.path.isdir(directory):
        return names
    for root, _dirs, files in os.walk(directory):
        rel_root = os.path.relpath(root, directory)
        for f in files:
            if f.endswith(".tmp"):
                continue
            names.add(f if rel_root == "." else os.path.join(rel_root, f))
    return names


def pack_artifacts(
    directory: Optional[str], names: Sequence[str],
    max_bytes: int = 64 * 1024 * 1024,
) -> Dict[str, bytes]:
    """Read ``names`` (relative paths from :func:`snapshot_cache_dir`) into
    a {name: bytes} payload, skipping anything missing or oversize (a
    multi-GB executable must not wedge the control plane)."""
    out: Dict[str, bytes] = {}
    if not directory:
        return out
    total = 0
    for name in sorted(names):
        path = os.path.join(directory, name)
        try:
            size = os.path.getsize(path)
            if total + size > max_bytes:
                continue
            with open(path, "rb") as f:
                out[name] = f.read()
            total += size
        except OSError:
            continue
    return out


def install_artifacts(directory: str, files: Dict[str, bytes]) -> int:
    """Write fetched artifacts under ``directory`` (atomic per file; an
    existing file is left alone — first writer wins, contents are
    content-addressed upstream anyway).  Returns how many files landed."""
    installed = 0
    base = os.path.realpath(directory)
    for name, data in files.items():
        dest = os.path.realpath(os.path.join(base, name))
        if not dest.startswith(base + os.sep):
            continue  # traversal attempt; drop it
        if os.path.exists(dest):
            continue
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dest), suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, dest)
            installed += 1
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return installed


class ArtifactRegistry:
    """Head-side store: program key -> published artifact files.

    Thread-compatible with the cluster driver's single event-loop thread;
    the lock makes it safe for tests that poke it directly.  Counters feed
    ``experiment_state.json["compile"]``:

    * ``origin_publishes`` — distinct (key, publish) events accepted; the
      "<= K head-side compiles for K shape classes" acceptance bound reads
      this.
    * ``origin_fetch_hits`` / ``origin_fetch_misses`` — fetches answered
      with / without files.

    With ``store`` (a ``store.ContentStore``), artifact bytes live as
    content-addressed blobs instead of head RAM: each key's files map to
    a manifest blob behind a ``compile-<hash(key)>`` ref, so executables
    and their cost sidecars dedup against anything else in the store,
    survive a head restart (a resumed driver re-fetches by ref), and are
    garbage-collected by the same reachability walk as checkpoints.  The
    ``max_bytes`` eviction only applies to the in-RAM mode — store-backed
    lifecycle belongs to GC (drop the ref, sweep the blobs).
    """

    def __init__(
        self, max_bytes: int = 256 * 1024 * 1024, store=None
    ):
        self._lock = named_lock("compilecache.origin")
        self._by_key: Dict[str, Dict[str, bytes]] = {}
        self._bytes = 0
        self._max_bytes = max_bytes
        self._store = store
        # Store mode: key -> {file name: blob digest} (the manifest's
        # ``files`` map, memoized; the ref is the durable copy).
        self._manifests: Dict[str, Dict[str, str]] = {}
        self.counters: Dict[str, int] = {
            "origin_publishes": 0,
            "origin_fetch_hits": 0,
            "origin_fetch_misses": 0,
        }

    @staticmethod
    def _ref_name(key: str):
        from distributed_machine_learning_tpu import store as store_lib

        return store_lib.ref_name_for_path("compile", key)

    def publish(self, key: str, files: Dict[str, bytes]) -> bool:
        """Accept a worker's published artifacts.  First publish per key
        wins (every publisher compiled the SAME program; later copies add
        nothing).  Returns whether the publish was stored."""
        if not files:
            return False
        size = sum(len(b) for b in files.values())
        with self._lock:
            if self._store is not None:
                return self._publish_store(key, files)
            if key in self._by_key:
                return False
            if self._bytes + size > self._max_bytes:
                # Evict oldest entries (dict order) until it fits; the
                # registry is a warm-start accelerator, not a durability
                # contract.
                for old in list(self._by_key):
                    if self._bytes + size <= self._max_bytes:
                        break
                    dropped = self._by_key.pop(old)
                    self._bytes -= sum(len(b) for b in dropped.values())
            self._by_key[key] = dict(files)
            self._bytes += size
            self.counters["origin_publishes"] += 1
            return True

    def _publish_store(self, key: str, files: Dict[str, bytes]) -> bool:
        from distributed_machine_learning_tpu import store as store_lib

        if key in self._manifests:
            return False
        ref_name = self._ref_name(key)
        if self._store.read_ref(ref_name) is not None:
            # A previous head incarnation already published this key —
            # adopt its manifest instead of re-publishing.
            mapping = self._mapping_from_ref(ref_name)
            if mapping is not None:
                self._manifests[key] = mapping
            return False
        with self._store.pin() as pin:
            mapping: Dict[str, str] = {}
            for name, data in files.items():
                digest = self._store.put_blob(data)
                pin.add(digest)
                mapping[name] = digest
            manifest_digest = self._store.put_manifest({
                "kind": "compile-artifacts",
                "key": key,
                "files": mapping,
                store_lib.MANIFEST_CHUNKS_KEY: sorted(set(mapping.values())),
            })
            pin.add(manifest_digest)
            self._store.set_ref(ref_name, manifest_digest, meta={"key": key})
        self._manifests[key] = mapping
        self.counters["origin_publishes"] += 1
        return True

    def _mapping_from_ref(self, ref_name: str) -> Optional[Dict[str, str]]:
        doc = self._store.read_ref(ref_name)
        if not doc:
            return None
        manifest = self._store.read_manifest(doc.get("manifest"))
        if not manifest:
            return None
        mapping = manifest.get("files")
        if not isinstance(mapping, dict):
            return None
        return {str(k): str(v) for k, v in mapping.items()}

    def fetch(self, key: str) -> Optional[Dict[str, bytes]]:
        with self._lock:
            if self._store is not None:
                files = self._fetch_store(key)
                if files is not None:
                    self.counters["origin_fetch_hits"] += 1
                    return files
                self.counters["origin_fetch_misses"] += 1
                return None
            files = self._by_key.get(key)
            if files:
                self.counters["origin_fetch_hits"] += 1
                return dict(files)
            self.counters["origin_fetch_misses"] += 1
            return None

    def _fetch_store(self, key: str) -> Optional[Dict[str, bytes]]:
        mapping = self._manifests.get(key)
        if mapping is None:
            mapping = self._mapping_from_ref(self._ref_name(key))
            if mapping is None:
                return None
            self._manifests[key] = mapping
        files: Dict[str, bytes] = {}
        for name, digest in mapping.items():
            data = self._store.get_blob(digest)
            if data is None:
                # A swept/damaged blob: the worker falls back to a local
                # compile, exactly like a plain miss.
                return None
            files[name] = data
        return files

    def keys(self) -> List[str]:
        with self._lock:
            if self._store is None:
                return sorted(self._by_key)
            known = set(self._manifests)
            for name in self._store.list_refs():
                if not name.startswith("compile-"):
                    continue
                doc = self._store.read_ref(name)
                key = ((doc or {}).get("meta") or {}).get("key")
                if key:
                    known.add(str(key))
            return sorted(known)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            distinct = len(
                self._manifests if self._store is not None else self._by_key
            )
            return dict(self.counters, distinct_keys=distinct)
