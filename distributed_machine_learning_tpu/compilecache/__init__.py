"""Compile-artifact layer: make the SECOND occurrence of any program free.

BENCH_r05 diagnosis: the stack loses on startup, not steady state — per-trial
jit compilation and process spin-up dominate short ASHA rungs (warm
``vs_baseline`` 0.80 vs cold 0.67).  The classic fix is ahead-of-time
compilation and executable reuse (PAPERS.md: the Julia-to-TPU compiler builds
its whole story on XLA AOT executables; Podracer gets its throughput from
compiling once and reusing the program across every actor).  This package
owns that story end to end:

* :mod:`keys` — canonical **program keys**: a config's shape-class
  fingerprint (non-structural hparams like lr/seed ignored) plus batch
  shape, dtype, and donation signature, hashed to a stable id that is
  identical across processes and hosts.  One key == one XLA program.
* :mod:`tracker` — the process-wide JAX monitoring listener (moved from
  ``utils/compile_cache.py``): per-thread compile seconds, backend-compile
  EVENT counts, persistent-cache hits; plus ownership of JAX's on-disk
  compilation cache (``enable_persistent_cache``).
* :mod:`counters` — the ``compile`` counter family (hits, misses,
  aot_exports/imports, fetch_hits/fallbacks, prewarm/spawn counts) that
  drivers publish into ``experiment_state.json["compile"]`` and TensorBoard
  ``compile/*`` next to the fault/liveness/checkpoint families.
* :mod:`aot` — :class:`ExecutableCache`: ``jax.jit(...).lower(...).compile()``
  ahead-of-time executables with serialized export/import on backends that
  support it, falling back to the persistent XLA cache (same keying) where
  they don't.
* :mod:`origin` — pack/install helpers and the head-side registry behind
  the cluster's compile-artifact origin: workers ask the head for a
  populated cache entry by program key before compiling locally, and
  publish what they compile, so a 256-trial sweep compiles each distinct
  program once per slice topology instead of once per worker.

``utils/compile_cache.py`` remains as a compatibility shim re-exporting the
tracker surface; new code should import from here.
"""

from distributed_machine_learning_tpu.compilecache.counters import (
    CompileCounters,
    get_counters,
)
from distributed_machine_learning_tpu.compilecache.keys import (
    NON_STRUCTURAL_KEYS,
    chunked_program_key,
    gang_program_key,
    pbt_program_key,
    program_key,
    sharded_program_key,
    shape_class_fingerprint,
)
from distributed_machine_learning_tpu.compilecache.tracker import (
    CompileTimeTracker,
    cache_dir,
    cache_entry_count,
    enable_persistent_cache,
    get_tracker,
)
from distributed_machine_learning_tpu.compilecache.aot import ExecutableCache
from distributed_machine_learning_tpu.compilecache.origin import (
    ArtifactRegistry,
    install_artifacts,
    pack_artifacts,
    snapshot_cache_dir,
)

__all__ = [
    "ArtifactRegistry",
    "CompileCounters",
    "CompileTimeTracker",
    "ExecutableCache",
    "NON_STRUCTURAL_KEYS",
    "cache_dir",
    "cache_entry_count",
    "chunked_program_key",
    "enable_persistent_cache",
    "gang_program_key",
    "get_counters",
    "get_tracker",
    "install_artifacts",
    "pack_artifacts",
    "pbt_program_key",
    "program_key",
    "sharded_program_key",
    "shape_class_fingerprint",
    "snapshot_cache_dir",
    "state_block",
]


def state_block(tracker_base=None, counters_base=None) -> dict:
    """The ``experiment_state.json["compile"]`` block for one run.

    Drivers snapshot ``get_tracker().snapshot()`` and
    ``get_counters().snapshot()`` at start and pass them here at teardown —
    the same scoping discipline as ``ckpt.metrics`` (the registries are
    process-wide; the block is per-run)."""
    tracker = get_tracker()
    tsnap = tracker.snapshot()
    if tracker_base:
        tsnap = {
            k: round(v - tracker_base.get(k, 0), 4) for k, v in tsnap.items()
        }
    block = dict(tsnap)
    csnap = get_counters().snapshot()
    if counters_base is not None:
        csnap = get_counters().delta_since(counters_base)
    block.update({k: v for k, v in csnap.items() if v})
    return block
