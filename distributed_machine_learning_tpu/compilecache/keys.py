"""Canonical program keys: config -> shape-class fingerprint -> stable id.

An XLA program is determined by everything that shapes the traced
computation: model family and architecture knobs, batch/sequence shapes,
dtypes, optimizer FAMILY (the chain's structure), and the donation
signature.  It is NOT determined by the hyperparameters that ride in state
— ``learning_rate`` and ``weight_decay`` live in the injected optimizer
hyperparams (``ops/optimizers.py``) and ``seed`` enters as a traced PRNG
key argument — so two trials differing only in those trace to IDENTICAL
HLO.  The key must say so: that identity is what lets the second trial, the
second worker, and the restarted replica skip compilation entirely.

The fingerprint must also be **stable across processes and hosts** (the
cluster origin exchanges artifacts by key; the bench compares keys across
child processes), so it is a sha256 over a canonical JSON rendering, never
``hash()`` (salted per process) or ``repr`` of dicts (order-dependent
pre-3.7 idioms).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional, Sequence, Tuple

# Hyperparameters that never shape the traced program: they are carried in
# optimizer state / PRNG arguments (the vectorized runner's VECTOR_KEYS is
# this same set — tune/vectorized.py asserts they agree).
NON_STRUCTURAL_KEYS = frozenset({"learning_rate", "weight_decay", "seed"})

# Driver-level knobs that select HOW a program is built/cached but never
# appear in the traced computation itself.
_DRIVER_KEYS = frozenset({"share_programs", "checkpoint_freq"})


def _canonical(value: Any) -> Any:
    """JSON-stable rendering: tuples -> lists, sets sorted, floats via repr
    (json floats are already deterministic in CPython, but -0.0 vs 0.0 and
    int-valued floats must not alias ints)."""
    if isinstance(value, (list, tuple)):
        return [_canonical(v) for v in value]
    if isinstance(value, (set, frozenset)):
        return sorted(_canonical(v) for v in value)
    if isinstance(value, dict):
        return {str(k): _canonical(v) for k, v in sorted(value.items())}
    if isinstance(value, float):
        return f"f:{value!r}"
    if isinstance(value, bool):
        return f"b:{value}"
    return value


def shape_class_fingerprint(config: Dict[str, Any]) -> Tuple:
    """The structural slice of a trial config, as a sorted item tuple.

    Everything except :data:`NON_STRUCTURAL_KEYS` and pure driver knobs is
    structural — d_model, heads, layers, batch_size, optimizer family,
    schedule family, interval/steps counts, dtypes all change the traced
    program.  EXCEPTION: with ``inject_hyperparams=False`` the optimizer
    bakes lr/wd into the HLO as constants, so they become structural again
    (the key must split what the compiler splits)."""
    injected = bool(config.get("inject_hyperparams", True))
    skip = set(_DRIVER_KEYS)
    skip.update(
        k for k in NON_STRUCTURAL_KEYS
        if injected or k == "seed"  # seed is a traced argument either way
    )
    items = []
    for k in sorted(config):
        if k in skip:
            continue
        items.append((k, _canonical(config[k])))
    return tuple(items)


def program_key(
    config: Dict[str, Any],
    *,
    batch_shape: Optional[Sequence[Sequence[int]]] = None,
    dtype: Optional[str] = None,
    donation: Sequence[int] = (),
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Stable id for one (shape class, batch shape, dtype, donation) program.

    ``batch_shape``: the data shapes the program closes over / is called
    with (e.g. staged train/val split shapes, or a serve bucket's padded
    input shape).  ``donation``: the ``donate_argnums`` signature — a
    donated and an undonated build of the same computation are different
    executables.  ``extra``: any additional identity the caller knows
    (population row count, scan trip count, mesh topology).
    """
    payload = {
        "v": 1,  # key-format version: bump if the canonicalization changes
        "fingerprint": _canonical(list(shape_class_fingerprint(config))),
        "batch_shape": _canonical(
            [list(s) for s in batch_shape] if batch_shape else []
        ),
        "dtype": dtype or "",
        "donation": sorted(int(d) for d in donation),
        "extra": _canonical(extra or {}),
    }
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return "pk_" + hashlib.sha256(blob.encode()).hexdigest()[:32]


def pbt_program_key(
    config: Dict[str, Any],
    *,
    interval: int,
    generations: int,
    rows: int,
    objective: Any = None,
    mutation_spec: Any = None,
    batch_shape: Optional[Sequence[Sequence[int]]] = None,
    dtype: Optional[str] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """:func:`program_key` for the compiled PBT generation scan.

    The generation scan is keyed by everything that shapes ITS trace on
    top of the base shape class: the **perturbation interval** (inner
    epoch-scan trip count), the **generation count** (outer scan trip
    count), the **population row count**, the **objective** scalarization,
    and the **mutation spec** constants (domain bounds, factors, resample
    probability, quantile — all baked into the exploit/explore step).
    The PBT ``seed`` must NOT split the key: it enters as per-row PRNG key
    arguments, exactly like trial seeds in the base key — and
    ``learning_rate``/``weight_decay`` stay non-structural (injected
    optimizer state the scan mutates in-device).
    """
    spec = dict(mutation_spec or {})
    merged = {
        "pbt_scan": {
            "interval": int(interval),
            "generations": int(generations),
            "rows": int(rows),
            "objective": _canonical(objective or "quality"),
            "mutations": _canonical(spec),
        }
    }
    if extra:
        merged.update(extra)
    return program_key(
        config,
        batch_shape=batch_shape,
        dtype=dtype,
        donation=(0, 1, 2),
        extra=merged,
    )


def chunked_program_key(
    config: Dict[str, Any],
    *,
    chunk_rows: int,
    batch_shape: Optional[Sequence[Sequence[int]]] = None,
    dtype: Optional[str] = None,
    donation: Sequence[int] = (),
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """:func:`program_key` for one streaming CHUNK program
    (``data/pipeline.py``: the out-of-core prefetch ring).

    The chunk's **row count** (batches per staged slab — the chunk scan's
    trip count, baked into the trace) folds into the key on top of the
    base shape class; the **number of chunks per epoch does NOT** — the
    host loops over chunks, so a 10-chunk and a 1000-chunk epoch of the
    same slab shape run the identical executable.  An epoch whose batch
    count does not divide the chunk size gets exactly one extra key (the
    tail chunk's smaller row count).  Dataset length and epoch batch
    count therefore never split streaming keys — only the slab geometry
    does.
    """
    merged = {"stream_chunk_rows": int(chunk_rows)}
    if extra:
        merged.update(extra)
    return program_key(
        config,
        batch_shape=batch_shape,
        dtype=dtype,
        donation=donation,
        extra=merged,
    )


def gang_program_key(
    config: Dict[str, Any],
    *,
    process_count: int,
    local_device_counts: Sequence[int],
    batch_shape: Optional[Sequence[Sequence[int]]] = None,
    dtype: Optional[str] = None,
    donation: Sequence[int] = (),
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """:func:`program_key` for a program lowered over a PROCESS-SPANNING
    mesh (``multihost/`` gang trials).

    The **process topology** — process count × per-process local device
    layout — folds into the key because the compiler splits on it: the
    same mesh shape decomposed differently across processes lowers
    different cross-process collectives (2 processes × 2 devices and
    4 × 1 are different programs).  Reshaping the gang therefore splits
    the key; a SECOND gang of the same topology computes the identical
    key, which is what lets it fetch the first gang's artifacts from the
    cluster origin and compile nothing.  Canonical (counts only — no
    device ids, hostnames, or ports), so the key is stable across hosts.
    """
    merged = {
        "process_topology": {
            "process_count": int(process_count),
            "local_device_counts": [int(c) for c in local_device_counts],
        }
    }
    if extra:
        merged.update(extra)
    return program_key(
        config,
        batch_shape=batch_shape,
        dtype=dtype,
        donation=donation,
        extra=merged,
    )


def sharded_program_key(
    config: Dict[str, Any],
    *,
    mesh_shape: Dict[str, int],
    rules_fingerprint: str,
    batch_shape: Optional[Sequence[Sequence[int]]] = None,
    dtype: Optional[str] = None,
    donation: Sequence[int] = (),
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """:func:`program_key` for a program compiled under a named mesh.

    Two additional identities fold into the key because the compiler
    splits on both: the **mesh shape** (``{"dp": 2, "tp": 4}`` and
    ``{"dp": 4, "tp": 2}`` lower to different collectives even over the
    same 8 devices) and the **partition-rule fingerprint**
    (``parallel.partition.rules_fingerprint`` — a rule-table edit changes
    every layout the traced program bakes in).  With these in the key,
    sharded programs AOT-cache and cross-worker-dedup exactly like
    unsharded ones: same mesh shape + same rule table on another worker
    ⇒ artifact fetch, anything else ⇒ honest recompile.
    """
    merged = {
        "mesh_shape": {str(k): int(v) for k, v in (mesh_shape or {}).items()},
        "rules_fp": str(rules_fingerprint),
    }
    if extra:
        merged.update(extra)
    return program_key(
        config,
        batch_shape=batch_shape,
        dtype=dtype,
        donation=donation,
        extra=merged,
    )
