"""Ahead-of-time executable cache: compile once, load everywhere.

The persistent XLA cache (``tracker.enable_persistent_cache``) already
makes a repeated BACKEND compile free — but the repeat process still pays
tracing and lowering, and still has to reach the compile call at all.  This
layer goes one step further where the backend supports it:
``jax.jit(fn).lower(*args).compile()`` produces a loaded executable, and
``jax.experimental.serialize_executable`` round-trips it to bytes — so a
restarted serve replica, a pre-warmed trial runner, or a second bench child
deserializes the finished executable and skips trace/lower/compile
entirely.

Keying is :func:`compilecache.keys.program_key` — the same id the cluster
origin and the persistent-cache layer use, so every layer agrees on what
"the same program" means.

Trust model: the serialized payload embeds pytree defs, which ride pickle
(jax's own serialization format).  The store is therefore for
**framework-owned directories only** — the local AOT dir and artifacts
received over the (already pickled, optionally HMAC'd) cluster control
plane.  Checkpoint bytes never come near this path (test_import_guard
keeps the checkpoint formats pickle-free; this file is deliberately not in
that list because executables are process-trust artifacts, not data).

Failure posture: every load path degrades to a plain compile — a stale,
truncated, or cross-version payload must cost a recompile, never an error.
A deserialized executable is strict about argument dtypes/shapes; if a call
ever rejects its inputs the entry is dropped and the call re-dispatches
through ordinary ``jax.jit`` (counted, so drift is visible).
"""

from __future__ import annotations

import os
import pickle
import re
import tempfile
import threading
from typing import Any, Callable, Dict, Optional, Sequence

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.compilecache.counters import get_counters
from distributed_machine_learning_tpu.compilecache import tracker as _tracker

_MAGIC = b"DMLAOT1\n"

# ``func.func public @main(%arg3: tensor<8x4xf32> {..., tf.aliasing_output
# = 1 : i32, ...})`` — the MLIR attribute jax's lowering stamps on every
# input buffer that will ALIAS an output (donation that actually took).
# ``jax.buffer_donor`` marks a donated input XLA may scavenge for
# intermediates even though no output matches its aval (the consumed-slab
# case — see data/pipeline.py's warning filter).
_ARG_RE = re.compile(r"%arg(\d+):")
_ALIAS_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")
_DONOR_RE = re.compile(r"jax\.buffer_donor\s*=\s*true")


def lowered_alias_info(lowered) -> Dict[str, Any]:
    """Input/output aliasing of a ``jax.jit(...).lower(...)`` result,
    WITHOUT compiling it (the donation decision is made at lowering time;
    reading it must stay allocation- and compile-free — the jaxlint
    donation verifier's whole contract, analysis/jaxlint/donation.py).

    Returns ``{"num_args": N, "aliased": {arg_index: output_index},
    "buffer_donors": {arg_index, ...}}`` over the FLATTENED argument list
    (the order ``jax.tree_util.tree_leaves`` yields the example args in).
    """
    text = lowered.as_text()
    # Only the entry function's signature matters; stop at its body so a
    # nested func's %arg0 cannot shadow main's.
    main = text.split("func.func public @main", 1)
    sig = main[1].split("{\n", 1)[0] if len(main) == 2 else text
    # Per-arg attribute dicts may embed strings containing braces
    # (``mhlo.sharding = "{replicated}"``), so bracket matching is not an
    # option: scan each arg's span up to the next ``%argN:`` token (or
    # the result arrow) instead.
    aliased: Dict[int, int] = {}
    donors = set()
    num_args = 0
    matches = list(_ARG_RE.finditer(sig))
    for i, m in enumerate(matches):
        idx = int(m.group(1))
        num_args = max(num_args, idx + 1)
        end = matches[i + 1].start() if i + 1 < len(matches) else len(sig)
        span = sig[m.end():end]
        if i + 1 >= len(matches):
            span = span.split("->", 1)[0]
        am = _ALIAS_RE.search(span)
        if am:
            aliased[idx] = int(am.group(1))
        if _DONOR_RE.search(span):
            donors.add(idx)
    return {"num_args": num_args, "aliased": aliased,
            "buffer_donors": donors}


def default_aot_dir() -> str:
    """``$DML_TPU_AOT_CACHE``, else ``<persistent cache dir>/aot``."""
    env = os.environ.get("DML_TPU_AOT_CACHE")
    if env:
        return os.path.expanduser(env)
    base = _tracker.cache_dir() or os.path.join(
        os.path.expanduser("~"), ".cache", "dml_tpu", "xla_cache"
    )
    return os.path.join(base, "aot")


class _Entry:
    __slots__ = ("compiled", "fallback", "make_fallback")

    def __init__(self, compiled):
        self.compiled = compiled
        self.fallback = None
        self.make_fallback = None


class ExecutableCache:
    """Program-key -> loaded executable, with a serialized on-disk tier.

    ``get_or_compile(key, fn, *args)`` resolves in order:

    1. in-memory (``program_hits``);
    2. on-disk serialized executable ``<dir>/<key>.aotexec``
       (``aot_imports`` + ``program_hits``);
    3. compile via ``jax.jit(fn, ...).lower(*args).compile()``
       (``program_misses``), then export the serialized executable
       (``aot_exports``) — or mark the backend unsupported
       (``aot_unsupported``) and rely on the persistent XLA cache for the
       cross-process story.

    The returned callable accepts the same concrete arguments as ``fn``.
    """

    def __init__(self, directory: Optional[str] = None,
                 persist: bool = True):
        self._dir = directory or default_aot_dir()
        self._persist = persist
        self._lock = named_lock("compilecache.aot")
        self._mem: Dict[str, _Entry] = {}
        self._serialize_supported: Optional[bool] = None

    @property
    def directory(self) -> str:
        return self._dir

    # -- disk tier -----------------------------------------------------------

    def _path(self, key: str) -> str:
        return os.path.join(self._dir, f"{key}.aotexec")

    def _load_from_disk(self, key: str):
        path = self._path(key)
        if not self._persist or not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                magic = f.read(len(_MAGIC))
                if magic != _MAGIC:
                    return None
                payload, in_tree, out_tree = pickle.load(f)
            from jax.experimental import serialize_executable as se

            return se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:  # noqa: BLE001 - stale/cross-version payloads
            # A damaged entry must cost a recompile, never an error; drop
            # it so the fresh export below replaces it.
            try:
                os.unlink(path)
            except OSError:
                pass
            return None

    def _export_to_disk(self, key: str, compiled) -> bool:
        if not self._persist:
            return False
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            os.makedirs(self._dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self._dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(_MAGIC)
                    pickle.dump((payload, in_tree, out_tree), f,
                                protocol=pickle.HIGHEST_PROTOCOL)
                os.replace(tmp, self._path(key))  # atomic: no torn entries
            finally:
                if os.path.exists(tmp):
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
            self._serialize_supported = True
            return True
        except Exception:  # noqa: BLE001 - backend without serialization
            self._serialize_supported = False
            return False

    # -- resolution ----------------------------------------------------------

    def get_or_compile(
        self,
        key: str,
        fn: Callable,
        *args,
        static_argnums: Sequence[int] = (),
        donate_argnums: Sequence[int] = (),
        jit_kwargs: Optional[Dict[str, Any]] = None,
    ) -> Callable:
        """Resolve ``key`` to a callable executable for ``fn(*args)``.

        ``args`` are example arguments of the exact shapes/dtypes the
        program will be called with (they are only traced/lowered on a
        miss, never executed).  ``jit_kwargs`` passes extra ``jax.jit``
        options through (``in_shardings``/``out_shardings`` for programs
        compiled under a named mesh) — they shape the executable, so the
        caller's ``key`` must already encode them
        (``keys.sharded_program_key``)."""
        counters = get_counters()
        with self._lock:
            entry = self._mem.get(key)
        if entry is not None:
            counters.add("program_hits")
            return self._wrap(key, entry)

        compiled = self._load_from_disk(key)
        if compiled is not None:
            counters.add("program_hits")
            counters.add("aot_imports")
            self._capture_cost(key, compiled, from_disk=True)
            entry = self._remember(key, compiled, fn, static_argnums,
                                   donate_argnums, jit_kwargs)
            return self._wrap(key, entry)

        counters.add("program_misses")
        jitted = self._jit(fn, static_argnums, donate_argnums, jit_kwargs)
        compiled = jitted.lower(*args).compile()
        if self._export_to_disk(key, compiled):
            counters.add("aot_exports")
        else:
            counters.add("aot_unsupported")
        self._capture_cost(key, compiled, from_disk=False)
        entry = self._remember(key, compiled, fn, static_argnums,
                               donate_argnums, jit_kwargs)
        return self._wrap(key, entry)

    def _capture_cost(self, key: str, compiled, from_disk: bool) -> None:
        """Cost-model audit capture (perf/costmodel.py) — riding ONLY on
        executables this cache was compiling or deserializing anyway, so
        the audit adds zero compiles by construction.  A disk hit prefers
        the sidecar written at export time (it carries the ORIGIN
        process's numbers across workers); the fallback reads the
        deserialized executable's own analysis.  Never raises: cost
        capture is telemetry, not a cache dependency."""
        try:
            from distributed_machine_learning_tpu.perf import costmodel

            if from_disk and self._persist and costmodel.load_program_cost(
                key, self._dir
            ) is not None:
                return
            costmodel.record_program_cost(
                key, compiled, self._dir if self._persist else None
            )
        except Exception:  # noqa: BLE001 - audit must never cost a trial
            pass

    @staticmethod
    def _jit(fn, static_argnums, donate_argnums, jit_kwargs=None):
        import jax

        kwargs = dict(jit_kwargs or {})
        if static_argnums:
            kwargs["static_argnums"] = tuple(static_argnums)
        if donate_argnums:
            kwargs["donate_argnums"] = tuple(donate_argnums)
        return jax.jit(fn, **kwargs)

    def _remember(self, key, compiled, fn, static_argnums, donate_argnums,
                  jit_kwargs=None):
        # The fallback is built lazily: a plain jit of the original fn, used
        # only if the AOT executable ever rejects its arguments (dtype /
        # weak-type drift between the exporting and importing process).
        entry = _Entry(compiled)

        def fallback(*call_args):
            if entry.fallback is None:
                entry.fallback = self._jit(fn, static_argnums,
                                           donate_argnums, jit_kwargs)
            return entry.fallback(*call_args)

        entry.make_fallback = fallback
        with self._lock:
            self._mem[key] = entry
        return entry

    def _wrap(self, key: str, entry: _Entry) -> Callable:
        def call(*args):
            try:
                return entry.compiled(*args)
            except (TypeError, ValueError):
                # Strict AOT signature mismatch: drop the entry and serve
                # through ordinary jit (persistent cache still applies).
                get_counters().add("aot_unsupported")
                with self._lock:
                    self._mem.pop(key, None)
                try:
                    os.unlink(self._path(key))
                except OSError:
                    pass
                return entry.make_fallback(*args)

        return call

    # -- introspection ---------------------------------------------------------

    def __contains__(self, key: str) -> bool:
        with self._lock:
            if key in self._mem:
                return True
        return self._persist and os.path.exists(self._path(key))

    def mem_size(self) -> int:
        with self._lock:
            return len(self._mem)

    def disk_keys(self) -> Sequence[str]:
        if not self._persist or not os.path.isdir(self._dir):
            return []
        return sorted(
            n[: -len(".aotexec")]
            for n in os.listdir(self._dir)
            if n.endswith(".aotexec")
        )

    def stats(self) -> Dict[str, Any]:
        return {
            "mem_programs": self.mem_size(),
            "disk_programs": len(self.disk_keys()),
            "directory": self._dir,
            "serialize_supported": self._serialize_supported,
        }
