"""Process-wide compile-artifact counters (the ``compile`` counter family).

Same registry discipline as ``ckpt/metrics.py``: one process-wide instance,
drivers snapshot at start and publish ``delta_since`` at teardown into
``experiment_state.json["compile"]`` and TensorBoard ``compile/*`` — so
"compile-once actually happened" is a property of the artifact, not of
test logs or a hunch.

Counter semantics:

* ``program_hits`` / ``program_misses`` — program-key lookups that found /
  did not find a ready executable (any layer: in-memory, AOT disk, or a
  cache-dir artifact installed by the origin).
* ``aot_exports`` / ``aot_imports`` — serialized executables written to /
  loaded from the AOT disk store (``aot.ExecutableCache``).
* ``aot_unsupported`` — the backend refused serialization; the persistent
  XLA cache carries the key instead.
* ``fetch_hits`` / ``fetch_misses`` — cluster-origin artifact fetches that
  returned / lacked files for the key.
* ``fetch_fallbacks`` — fetches that FAILED (fault, timeout, partition) and
  fell back to local compilation — the chaos-exercised path.
* ``publishes`` — artifacts this process published to the origin.
* ``prewarmed_spawns`` / ``cold_spawns`` — process-executor trials started
  on a pre-warmed runner vs a cold ``Popen``.
* ``prewarm_compiles`` — programs compiled ahead of dispatch during
  scheduler think-time.
"""

from __future__ import annotations

import threading
from typing import Dict
from distributed_machine_learning_tpu.analysis.locks import named_lock


class CompileCounters:
    """Thread-safe counter registry for compile-artifact activity."""

    _FIELDS = (
        "program_hits",
        "program_misses",
        "aot_exports",
        "aot_imports",
        "aot_unsupported",
        "fetch_hits",
        "fetch_misses",
        "fetch_fallbacks",
        "publishes",
        "prewarmed_spawns",
        "cold_spawns",
        "prewarm_compiles",
        # Donation audit (sharded trainable): donated inputs of the fused
        # epoch program OBSERVED consumed after its first call — runtime
        # proof the buffer alias took effect, not just that donate_argnums
        # was requested (docs/performance.md donation audit table).
        "donation_aliased_buffers",
        # Cost-model audit (perf/costmodel.py): programs whose
        # cost_analysis() was captured at compile time vs reloaded from a
        # <key>.cost.json sidecar — captures + sidecar_loads together
        # must track aot activity with ZERO extra program_misses (the
        # audit rides executables the cache was building anyway).
        "cost_captures",
        "cost_sidecar_loads",
    )

    def __init__(self):
        self._lock = named_lock("compilecache.counters")
        self._c: Dict[str, float] = {k: 0 for k in self._FIELDS}

    def add(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + value

    def get(self, name: str) -> float:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in self._c.items()
            }

    def delta_since(self, baseline: Dict[str, float]) -> Dict[str, float]:
        snap = self.snapshot()
        return {k: round(v - baseline.get(k, 0), 4) for k, v in snap.items()}

    def reset(self) -> None:
        """Test hook: zero every counter."""
        with self._lock:
            self._c = {k: 0 for k in self._FIELDS}


_counters = CompileCounters()

# Registered as the ``compile`` family in the unified metrics registry
# (obs/registry.py) — the experiment_state.json block keeps its exact
# shape (drivers still build it from state_block); this is the
# process-wide live view.
from distributed_machine_learning_tpu.obs.registry import (  # noqa: E402
    get_registry as _obs_registry,
)

_obs_registry().register_family("compile", _counters)


def get_counters() -> CompileCounters:
    """The process-wide registry (one per process, like the compile-time
    tracker in ``compilecache/tracker.py``)."""
    return _counters
