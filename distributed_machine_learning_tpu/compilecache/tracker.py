"""Persistent XLA compile-cache ownership + per-trial compile accounting.

Moved here from ``utils/compile_cache.py`` (which remains as a shim) when
the compile-artifact layer grew into a package.  Two mechanisms:

1. :func:`enable_persistent_cache` — turns on JAX's on-disk compilation
   cache so that a trial whose traced program matches ANY earlier trial
   (this run or a previous one, this process or another) skips XLA backend
   compilation entirely.  Every driver calls this at startup; it is not
   left to the user.

2. :class:`CompileTimeTracker` — a process-wide listener on JAX's
   monitoring events that attributes compile seconds, backend-compile
   EVENT counts, and persistent-cache hits to the thread that triggered
   them.  Trial threads each jit their own programs, so per-thread
   attribution IS per-trial attribution.  The event COUNTS (not just
   seconds) are what the compile-once acceptance checks assert: "a fresh
   process with a populated cache records 0 new backend compiles" is
   ``total_backend_compiles() == 0``, not an eyeballed duration.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional
from distributed_machine_learning_tpu.analysis.locks import named_lock

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "dml_tpu", "xla_cache"
)

_lock = named_lock("compilecache.tracker.registry")
_enabled_dir: Optional[str] = None

# Monitoring event names (`/jax/core/compile/*`,
# `/jax/compilation_cache/*`) — verified against this image's jax.
_DURATION_EVENTS = (
    "/jax/core/compile/backend_compile_duration",
    "/jax/core/compile/jaxpr_trace_duration",
    "/jax/core/compile/jaxpr_to_mlir_module_duration",
)
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"


def enable_persistent_cache(cache_dir: Optional[str] = None) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir`` (created if
    missing) and drop the min-size/min-time thresholds so even small HPO
    programs are cached.  Idempotent; returns the resolved directory.

    Default: ``$DML_TPU_COMPILE_CACHE`` or ``~/.cache/dml_tpu/xla_cache``.
    """
    global _enabled_dir
    resolved = os.path.expanduser(
        cache_dir
        or os.environ.get("DML_TPU_COMPILE_CACHE")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or _DEFAULT_DIR
    )
    with _lock:
        if _enabled_dir == resolved:
            return resolved
        os.makedirs(resolved, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", resolved)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # By default jax also turns on XLA's GPU autotune cache, whose
        # directory PATH lands in compile_options — which is hashed into
        # the cache key, so two hosts with different cache dirs compute
        # DIFFERENT keys for the same program and artifacts shipped
        # between them (cluster origin, bench children) can never hit.
        # Disable it: key stability across hosts is the whole point, and
        # the knob only affects a GPU autotuning sidecar cache.
        try:
            jax.config.update("jax_persistent_cache_enable_xla_caches", "")
        except AttributeError:  # pragma: no cover - knob absent on old jax
            pass
        if _enabled_dir is not None and _enabled_dir != resolved:
            # JAX instantiates the cache object lazily ONCE; re-pointing the
            # config after that is silently ignored without a reset.
            from jax.experimental.compilation_cache import compilation_cache

            compilation_cache.reset_cache()
        _enabled_dir = resolved
    return resolved


def cache_dir() -> Optional[str]:
    """The directory the persistent cache is enabled at (None if not)."""
    return _enabled_dir


def cache_entry_count() -> int:
    """Number of compiled executables currently in the persistent cache."""
    if not _enabled_dir or not os.path.isdir(_enabled_dir):
        return 0
    return sum(1 for name in os.listdir(_enabled_dir) if name.endswith("-cache"))


class CompileTimeTracker:
    """Attributes JAX compile seconds + persistent-cache hits per thread.

    JAX runs monitoring listeners inline on the thread that compiles, so
    ``threading.get_ident()`` inside the listener identifies which trial
    thread paid for a compilation.  A single process-wide instance is
    installed lazily (:func:`get_tracker`); the executor snapshots a thread's
    counters before a trial starts and diffs after each report.
    """

    def __init__(self):
        self._lock = named_lock("compilecache.tracker")
        self._seconds: Dict[int, float] = {}
        self._hits: Dict[int, int] = {}
        self._backend_seconds: Dict[int, float] = {}
        self._backend_count: Dict[int, int] = {}
        self._trace_count: Dict[int, int] = {}
        self._max_backend_s: float = 0.0

    # -- listener callbacks (run on the compiling thread) -------------------

    def _on_duration(self, event: str, duration: float, **_kw):
        if event not in _DURATION_EVENTS:
            return
        ident = threading.get_ident()
        with self._lock:
            self._seconds[ident] = self._seconds.get(ident, 0.0) + duration
            if event == _DURATION_EVENTS[0]:
                self._backend_seconds[ident] = (
                    self._backend_seconds.get(ident, 0.0) + duration
                )
                self._backend_count[ident] = (
                    self._backend_count.get(ident, 0) + 1
                )
                self._max_backend_s = max(self._max_backend_s, duration)
            elif event == _DURATION_EVENTS[1]:
                self._trace_count[ident] = self._trace_count.get(ident, 0) + 1
        if event == _DURATION_EVENTS[0]:
            # Into the observability plane: each backend compile becomes a
            # trace span (the listener hands us the measured duration, so
            # the span is recorded retroactively) and a flight-ring event —
            # a wedged process's dump shows what was compiling when.
            from distributed_machine_learning_tpu import obs

            obs.add_complete("compile.backend", duration)
            obs.event("backend_compile", {"dur_s": round(duration, 4)})

    def _on_event(self, event: str, **_kw):
        if event != _CACHE_HIT_EVENT:
            return
        ident = threading.get_ident()
        with self._lock:
            self._hits[ident] = self._hits.get(ident, 0) + 1

    # -- queries ------------------------------------------------------------

    def thread_seconds(self, ident: Optional[int] = None) -> float:
        """Cumulative compile seconds (trace + lower + backend) on a thread."""
        ident = ident if ident is not None else threading.get_ident()
        with self._lock:
            return self._seconds.get(ident, 0.0)

    def thread_backend_seconds(self, ident: Optional[int] = None) -> float:
        """Cumulative XLA backend-compile seconds on a thread (the part a
        persistent-cache hit eliminates)."""
        ident = ident if ident is not None else threading.get_ident()
        with self._lock:
            return self._backend_seconds.get(ident, 0.0)

    def thread_cache_hits(self, ident: Optional[int] = None) -> int:
        ident = ident if ident is not None else threading.get_ident()
        with self._lock:
            return self._hits.get(ident, 0)

    def total_seconds(self) -> float:
        with self._lock:
            return sum(self._seconds.values())

    def total_cache_hits(self) -> int:
        with self._lock:
            return sum(self._hits.values())

    def total_backend_compiles(self) -> int:
        """Backend-compile EVENTS in this process.  NOTE: on this jax the
        event fires around the compile-or-fetch section, so persistent-
        cache HITS count too — :meth:`total_uncached_compiles` is the
        number of compiles that actually ran the XLA compiler."""
        with self._lock:
            return sum(self._backend_count.values())

    def total_uncached_compiles(self) -> int:
        """Backend compiles NOT served by the persistent cache — the
        number every cache layer exists to hold at the distinct-program
        count, and at ZERO for a warm restart (the compile-once
        acceptance checks assert on exactly this)."""
        with self._lock:
            return max(
                sum(self._backend_count.values()) - sum(self._hits.values()),
                0,
            )

    def total_traces(self) -> int:
        """Jaxpr traces in this process.  The import-time guard asserts this
        stays flat across an import sweep — tracing at import is hidden
        startup cost every process pays before doing any work."""
        with self._lock:
            return sum(self._trace_count.values())

    def max_backend_compile_s(self) -> float:
        """Longest single XLA backend compile seen in this process — the
        pessimistic price of compiling a program no cache has seen."""
        with self._lock:
            return self._max_backend_s

    def snapshot(self) -> Dict[str, float]:
        """Process totals for the ``compile`` state block (driver-scoped via
        delta, same discipline as ``ckpt.metrics``)."""
        with self._lock:
            backend = sum(self._backend_count.values())
            hits = sum(self._hits.values())
            return {
                "backend_compiles": backend,
                # Compiles the XLA compiler actually ran (the event above
                # also fires on persistent-cache hits): the compile-once
                # invariant is THIS staying at the distinct-program count.
                "backend_compiles_uncached": max(backend - hits, 0),
                "backend_compile_s": round(
                    sum(self._backend_seconds.values()), 4
                ),
                "compile_wall_s": round(sum(self._seconds.values()), 4),
                "persistent_cache_hits": hits,
                "traces": sum(self._trace_count.values()),
            }


_tracker: Optional[CompileTimeTracker] = None


def get_tracker() -> CompileTimeTracker:
    """The process-wide tracker, installing the JAX listeners on first use."""
    global _tracker
    with _lock:
        if _tracker is None:
            import jax.monitoring

            _tracker = CompileTimeTracker()
            jax.monitoring.register_event_duration_secs_listener(
                _tracker._on_duration
            )
            jax.monitoring.register_event_listener(_tracker._on_event)
    return _tracker
