"""Liveness primitives: detecting SILENCE, not just failure.

PR 2's chaos harness proved the stack survives faults that *announce*
themselves — IO errors, crashes, corrupt bytes, killed replicas.  Every
remaining incident class is fail-slow: a dispatch that never returns, a
worker that hangs while keeping its TCP connection open, storage that
stalls instead of erroring.  Nothing raises, so nothing recovers.

This module owns the two primitives every layer uses to turn silence into
an event (Podracer's stance, PAPERS.md: preemption/stall recovery is a
first-class scheduler property on TPU pods, not an ops afterthought):

* :class:`Heartbeat` — a monotonic progress marker.  ``beat()`` at real
  progress points (report boundaries, dispatch completions, mid-epoch
  ``tune.heartbeat()`` calls); ``age_s()`` is the time since the last one.
  Monotonic clock, so NTP steps and clock slew can't fake progress.

* :class:`DispatchWatchdog` — a registry of heartbeats with a progress
  deadline.  Consumers either poll :meth:`expired` from their own event
  loop (the tune runner / cluster driver, which already tick every 0.5s)
  or run the built-in monitor thread and get an ``on_stall`` callback
  (the vectorized runner, whose dispatch blocks its only thread).  A key
  that beats again after being flagged is a *recovery* — counted, not
  forgotten, because "slow but alive" and "dead" need different operator
  responses (docs/operations.md "Hangs, stalls, and preemption").

The watchdog never unblocks a wedged call itself — on TPU a hung dispatch
holds its core until the runtime gives it back.  What it enables is the
layer-appropriate response: the process executor SIGTERMs the trial's
incarnation and restarts from checkpoint, the thread executor marks the
trial STALLED for the scheduler/operator, the cluster driver requeues the
trial onto a live worker and fences the silent one.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.obs import record_event as _obs_event


class Heartbeat:
    """Thread-safe monotonic progress marker."""

    __slots__ = ("_lock", "_last", "beats", "created")

    def __init__(self):
        self._lock = named_lock("liveness.heartbeat")
        now = time.monotonic()
        self._last = now
        self.created = now
        self.beats = 0

    def beat(self) -> None:
        with self._lock:
            self._last = time.monotonic()
            self.beats += 1

    def age_s(self) -> float:
        """Seconds since the last beat (or since creation)."""
        with self._lock:
            return time.monotonic() - self._last


class StallEvent:
    """What the watchdog hands to ``on_stall`` observers."""

    __slots__ = ("key", "age_s", "deadline_s", "info")

    def __init__(self, key: str, age_s: float, deadline_s: float, info: Any):
        self.key = key
        self.age_s = age_s
        self.deadline_s = deadline_s
        self.info = info

    def __repr__(self) -> str:
        return (
            f"StallEvent({self.key!r}, age={self.age_s:.1f}s > "
            f"deadline={self.deadline_s:.1f}s)"
        )


class _Tracked:
    __slots__ = ("heartbeat", "deadline_s", "grace_s", "info", "stalled")

    def __init__(self, deadline_s: float, grace_s: float, info: Any):
        self.heartbeat = Heartbeat()
        self.deadline_s = deadline_s
        self.grace_s = grace_s
        self.info = info
        self.stalled = False

    def threshold_s(self) -> float:
        # Until the FIRST beat, the activity is still starting up (process
        # spawn, jax import, cold compile) — that latency is real but it is
        # not a wedged dispatch; the deadline alone applies once the
        # activity has proven it can make progress.
        return (
            self.deadline_s
            if self.heartbeat.beats > 0
            else self.deadline_s + self.grace_s
        )


class DispatchWatchdog:
    """Progress-deadline tracking over a set of named activities.

    ``expired()`` is edge-triggered: each tracked key is returned once per
    stall episode (re-armed by the next ``beat``), so pollers can treat a
    returned key as "act now" without dedup bookkeeping.  Counters
    (``stalls_total``, ``recoveries_total``, per-key ``beats``) surface in
    :meth:`snapshot` for experiment_state.json / TensorBoard.
    """

    def __init__(
        self,
        deadline_s: float,
        on_stall: Optional[Callable[[StallEvent], None]] = None,
        poll_s: Optional[float] = None,
        first_beat_grace_s: Optional[float] = None,
    ):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0: {deadline_s}")
        self.deadline_s = float(deadline_s)
        # Extra allowance before the first beat only (see _Tracked): cold
        # starts legitimately dwarf steady-state report gaps.
        self.first_beat_grace_s = (
            float(first_beat_grace_s)
            if first_beat_grace_s is not None
            else max(3.0 * self.deadline_s, 30.0)
        )
        self._on_stall = on_stall
        self._poll_s = poll_s or max(min(self.deadline_s / 4.0, 1.0), 0.02)
        self._lock = named_lock("liveness.watchdog")
        self._tracked: Dict[str, _Tracked] = {}
        self.stalls_total = 0
        self.recoveries_total = 0
        self.observer_errors = 0
        self._monitor: Optional[threading.Thread] = None
        self._closing = threading.Event()

    # -- registry ------------------------------------------------------------

    def track(self, key: str, deadline_s: Optional[float] = None,
              info: Any = None,
              first_beat_grace_s: Optional[float] = None) -> None:
        """(Re)register ``key`` with a fresh heartbeat."""
        with self._lock:
            self._tracked[key] = _Tracked(
                deadline_s or self.deadline_s,
                self.first_beat_grace_s
                if first_beat_grace_s is None else float(first_beat_grace_s),
                info,
            )

    def beat(self, key: str) -> None:
        """Record progress for ``key``; a beat on a stalled key counts as a
        recovery.  Unknown keys are ignored (a late beat from an activity
        already untracked must not resurrect it)."""
        with self._lock:
            entry = self._tracked.get(key)
            if entry is None:
                return
            if entry.stalled:
                entry.stalled = False
                self.recoveries_total += 1
            entry.heartbeat.beat()

    def untrack(self, key: str) -> None:
        with self._lock:
            self._tracked.pop(key, None)

    def is_stalled(self, key: str) -> bool:
        with self._lock:
            entry = self._tracked.get(key)
            return bool(entry and entry.stalled)

    # -- detection -----------------------------------------------------------

    def expired(self) -> List[StallEvent]:
        """Keys newly past their deadline (each stall episode fires once)."""
        out: List[StallEvent] = []
        with self._lock:
            for key, entry in self._tracked.items():
                if entry.stalled:
                    continue
                age = entry.heartbeat.age_s()
                if age > entry.threshold_s():
                    entry.stalled = True
                    self.stalls_total += 1
                    out.append(StallEvent(key, age, entry.deadline_s,
                                          entry.info))
        for event in out:
            # Into the always-on flight ring: a later dump of this process
            # carries WHEN each silence was detected, next to whatever the
            # process was doing around it.
            _obs_event("watchdog_stall", {
                "key": event.key,
                "age_s": round(event.age_s, 2),
                "deadline_s": event.deadline_s,
            })
        return out

    # -- blocking-call guard (monitor-thread mode) ---------------------------

    def guard(self, key: str, info: Any = None):
        """Context manager wrapping ONE blocking dispatch: tracks on entry,
        untracks on exit.  Needs the monitor thread (``start()``) for the
        ``on_stall`` callback to fire while the caller is blocked."""
        return _Guard(self, key, info)

    def start(self) -> "DispatchWatchdog":
        """Run the monitor thread: polls ``expired()`` and invokes
        ``on_stall`` for each event.  Idempotent; daemon thread."""
        if self._monitor is None or not self._monitor.is_alive():
            self._closing.clear()
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="dispatch-watchdog",
                daemon=True,
            )
            self._monitor.start()
        return self

    def close(self) -> None:
        self._closing.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
            self._monitor = None

    def _monitor_loop(self) -> None:
        while not self._closing.wait(self._poll_s):
            for event in self.expired():
                if self._on_stall is not None:
                    try:
                        self._on_stall(event)
                    except Exception:  # noqa: BLE001 - observer isolation
                        # Isolated on purpose (a broken observer must not
                        # kill stall detection) but never silent: the count
                        # surfaces in snapshot()/experiment_state.json.
                        self.observer_errors += 1

    # -- observability -------------------------------------------------------

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "stalls_detected": self.stalls_total,
                "stall_recoveries": self.recoveries_total,
                "currently_stalled": sum(
                    1 for e in self._tracked.values() if e.stalled
                ),
                "observer_errors": self.observer_errors,
            }


class _Guard:
    __slots__ = ("_dog", "_key", "_info")

    def __init__(self, dog: DispatchWatchdog, key: str, info: Any):
        self._dog = dog
        self._key = key
        self._info = info

    def __enter__(self):
        self._dog.track(self._key, info=self._info)
        return self._dog

    def __exit__(self, *exc):
        self._dog.untrack(self._key)
        return False
