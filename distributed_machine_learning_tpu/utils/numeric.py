"""Shared numeric-value predicates."""

from __future__ import annotations

import math
from typing import Any, Optional


def finite_number(value: Any) -> Optional[float]:
    """``value`` as a float when it is a usable score, else None.

    Usable = a real number that is not a bool and is finite: trainables may
    report None/strings during warmup, NaN from diverged steps, or +/-inf
    from overflowed losses — none of which may rank, display as "best", or
    enter a searcher's mean (the one definition shared by ProgressReporter,
    TensorBoard-adjacent guards, and the Repeater)."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    v = float(value)
    return v if math.isfinite(v) else None
