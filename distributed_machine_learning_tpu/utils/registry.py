"""Name -> implementation registries.

The reference's extension mechanism is plain name->class dicts for optimizers and
losses (`ray-tune-hpo-regression.py:253-258, 313-319`).  We keep that shape but make
it a first-class, reusable registry with decorator registration and helpful errors.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Generic, Iterator, Optional, TypeVar

T = TypeVar("T")


class Registry(Generic[T]):
    """A string-keyed registry with decorator-style registration."""

    def __init__(self, kind: str):
        self._kind = kind
        self._entries: Dict[str, T] = {}

    def register(self, name: str, obj: Optional[T] = None) -> Callable[[T], T]:
        key = name.lower()

        def _do_register(o: T) -> T:
            if key in self._entries:
                raise ValueError(f"{self._kind} {name!r} is already registered")
            self._entries[key] = o
            return o

        if obj is not None:
            return _do_register(obj)
        return _do_register

    def get(self, name: str) -> T:
        key = str(name).lower()
        if key not in self._entries:
            raise KeyError(
                f"Unknown {self._kind} {name!r}. Available: {sorted(self._entries)}"
            )
        return self._entries[key]

    def __contains__(self, name: str) -> bool:
        return str(name).lower() in self._entries

    def __iter__(self) -> Iterator[str]:
        return iter(self._entries)

    def names(self) -> list:
        return sorted(self._entries)

    def items(self):
        return self._entries.items()
