from distributed_machine_learning_tpu.utils.registry import Registry
from distributed_machine_learning_tpu.utils.seeding import fold_seed, rng_from

__all__ = ["Registry", "fold_seed", "rng_from"]
