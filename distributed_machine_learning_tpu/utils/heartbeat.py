"""Progress-heartbeat protocol shared by bench children and the runners.

A monitored parent (bench.py ``_run_child_monitored``) kills a child whose
heartbeat file goes stale: real progress — phase boundaries, vectorized
dispatch boundaries — must refresh the file's mtime, while a hung device
call must NOT (which is why this is called at progress points, never from
a liveness thread). The file path travels in ``DML_BENCH_HEARTBEAT_PATH``.
"""

from __future__ import annotations

import os
import time

ENV_VAR = "DML_BENCH_HEARTBEAT_PATH"


def touch_heartbeat() -> None:
    """Refresh the heartbeat file named by ``DML_BENCH_HEARTBEAT_PATH``;
    no-op (never raises) when unset or unwritable."""
    path = os.environ.get(ENV_VAR)
    if not path:
        return
    try:
        with open(path, "w") as f:
            f.write(repr(time.time()))
    except OSError:
        pass
