"""Compatibility shim: the compile-cache layer grew into ``compilecache/``.

The tracker and persistent-cache surface this module used to own now lives
in :mod:`distributed_machine_learning_tpu.compilecache` (which adds program
keys, AOT executables, the artifact origin, and the ``compile`` counter
family on top).  Every symbol importable from here keeps working; new code
should import from the package.
"""

from distributed_machine_learning_tpu.compilecache.tracker import (  # noqa: F401
    CompileTimeTracker,
    cache_dir,
    cache_entry_count,
    enable_persistent_cache,
    get_tracker,
)

__all__ = [
    "CompileTimeTracker",
    "cache_dir",
    "cache_entry_count",
    "enable_persistent_cache",
    "get_tracker",
]
