"""Structured logging for the framework.

The reference's only logging was a stdlib file handler with a hard-coded
home-directory path in the smoke script (`ray-tune-hpo-regression-sample.py:
16-23`) and bare ``print`` in the production script (`:350,480`).  Here every
component logs through one namespaced logger tree (``dml_tpu.*``) with the same
``asctime - levelname - message`` format the reference used, a configurable
destination, and an optional JSONL handler for machine-readable event streams
(SURVEY.md §5 observability).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Mapping, Optional

ROOT_NAME = "dml_tpu"
_FORMAT = "%(asctime)s - %(levelname)s - %(name)s - %(message)s"


def _root() -> logging.Logger:
    root = logging.getLogger(ROOT_NAME)
    if not root.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
    return root


def get_logger(name: str = "", level: Optional[int] = None) -> logging.Logger:
    """Return a namespaced framework logger.

    ``get_logger("tune.runner")`` -> logger ``dml_tpu.tune.runner``.  An
    explicit ``level`` is applied to the framework root on every call (not just
    the first), so later callers can raise/lower verbosity.
    """
    root = _root()
    if level is not None:
        root.setLevel(level)
    return logging.getLogger(f"{ROOT_NAME}.{name}" if name else ROOT_NAME)


def add_file_handler(log_file: str) -> logging.Handler:
    """Attach a file handler to the framework root; caller owns its lifetime.

    Pair with :func:`remove_handler` (e.g. at experiment end) so handlers do
    not accumulate across experiments in a long-lived process.
    """
    path = os.path.abspath(os.path.expanduser(log_file))
    handler = logging.FileHandler(path)
    handler.setFormatter(logging.Formatter(_FORMAT))
    _root().addHandler(handler)
    return handler


def remove_handler(handler: logging.Handler):
    _root().removeHandler(handler)
    handler.close()


class JsonlEventLog:
    """Append-only JSONL event stream (one experiment-level file).

    Every event gets a wall-clock timestamp; values are coerced to JSON-safe
    types the same way the experiment store does.  Field names that collide
    with the reserved ``event``/``timestamp`` keys are prefixed rather than
    dropped or crashed on.
    """

    RESERVED = ("event", "timestamp")

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
        self._f = open(path, "a")

    def write(self, event: str, fields: Optional[Mapping[str, Any]] = None):
        from distributed_machine_learning_tpu.tune.experiment import _jsonable

        record: Dict[str, Any] = {"event": event, "timestamp": time.time()}
        for k, v in (fields or {}).items():
            key = f"field_{k}" if k in self.RESERVED else k
            record[key] = _jsonable(v)
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def close(self):
        if not self._f.closed:
            self._f.close()
