"""Dependency-free TensorBoard scalar logging.

Ray Tune logs every trial's metrics to TensorBoard by default (its
``TBXLoggerCallback``); this supplies the same observability for the
TPU-native framework WITHOUT requiring tensorflow/tensorboardX in the image
(neither is installed here — SURVEY.md §5 metrics/observability).

A TensorBoard event file is a TFRecord stream of serialized ``Event``
protobufs.  Both formats are tiny and stable, so they are encoded by hand:

* TFRecord framing: ``uint64 length | masked crc32c(length) | payload |
  masked crc32c(payload)``, CRC-32C (Castagnoli) with TensorFlow's mask
  ``((crc >> 15 | crc << 17) + 0xa282ead8) & 0xffffffff``.
* ``Event`` proto (tensorflow/core/util/event.proto): field 1 ``wall_time``
  (double), field 2 ``step`` (int64), field 3 ``file_version`` (string,
  first record only), field 5 ``summary`` (message).
* ``Summary`` proto: repeated field 1 ``value``; ``Summary.Value``: field 1
  ``tag`` (string), field 2 ``simple_value`` (float).

Only scalar summaries are emitted — the TB surface HPO metrics need.  The
module also includes a reader (``read_events``) so tests can round-trip the
format without TensorBoard installed.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Iterator, List, Optional, Tuple

# --------------------------------------------------------------------------
# CRC-32C (Castagnoli), reflected polynomial 0x82F63B78 — table-driven.
# --------------------------------------------------------------------------

_CRC_TABLE: List[int] = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --------------------------------------------------------------------------
# Minimal protobuf wire encoding (varint / length-delimited / fixed).
# --------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3) + _varint(value)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _field_double(num: int, value: float) -> bytes:
    return _varint((num << 3) | 1) + struct.pack("<d", value)


def _field_float(num: int, value: float) -> bytes:
    return _varint((num << 3) | 5) + struct.pack("<f", value)


def _encode_event(wall_time: float, step: Optional[int] = None,
                  file_version: Optional[str] = None,
                  scalars: Optional[List[Tuple[str, float]]] = None) -> bytes:
    ev = _field_double(1, wall_time)
    if step is not None:
        ev += _field_varint(2, step & 0xFFFFFFFFFFFFFFFF)
    if file_version is not None:
        ev += _field_bytes(3, file_version.encode())
    if scalars:
        summary = b"".join(
            _field_bytes(
                1, _field_bytes(1, tag.encode()) + _field_float(2, float(v))
            )
            for tag, v in scalars
        )
        ev += _field_bytes(5, summary)
    return ev


def _tfrecord(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (
        header
        + struct.pack("<I", _masked_crc(header))
        + payload
        + struct.pack("<I", _masked_crc(payload))
    )


# --------------------------------------------------------------------------
# Writer
# --------------------------------------------------------------------------


class SummaryWriter:
    """Append-only scalar event writer for one TensorBoard run directory.

    Thread-safe (the tune runner may report from its event loop while a
    caller flushes). The file carries the conventional
    ``events.out.tfevents.<ts>.<host>`` name TensorBoard globs for.
    """

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        self.path = os.path.join(logdir, fname)
        self._lock = threading.Lock()
        self._f = open(self.path, "ab")
        # TensorBoard ignores files whose first record is not this version
        # stamp.
        self._write(_encode_event(time.time(), file_version="brain.Event:2"))

    def _write(self, event: bytes) -> None:
        self._f.write(_tfrecord(event))

    def add_scalar(self, tag: str, value: float, step: int,
                   wall_time: Optional[float] = None) -> None:
        with self._lock:
            if self._f.closed:
                return
            self._write(
                _encode_event(
                    wall_time if wall_time is not None else time.time(),
                    step=int(step), scalars=[(tag, value)],
                )
            )

    def add_scalars(self, scalars: List[Tuple[str, float]], step: int,
                    wall_time: Optional[float] = None) -> None:
        """All tags in ONE Event record (one timestamp, one fsync unit)."""
        with self._lock:
            if self._f.closed:
                return
            self._write(
                _encode_event(
                    wall_time if wall_time is not None else time.time(),
                    step=int(step), scalars=list(scalars),
                )
            )

    def flush(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


# --------------------------------------------------------------------------
# Reader (tests + offline analysis without TensorBoard installed)
# --------------------------------------------------------------------------


def _decode_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _parse_fields(buf: bytes) -> Iterator[Tuple[int, int, bytes]]:
    """Yield (field_number, wire_type, raw_payload) triples."""
    pos = 0
    while pos < len(buf):
        key, pos = _decode_varint(buf, pos)
        num, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _decode_varint(buf, pos)
            yield num, wt, _varint(val)
        elif wt == 1:
            yield num, wt, buf[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = _decode_varint(buf, pos)
            yield num, wt, buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            yield num, wt, buf[pos:pos + 4]
            pos += 4
        else:  # pragma: no cover - groups don't appear in event files
            raise ValueError(f"unsupported wire type {wt}")


def read_events(path: str, verify_crc: bool = True):
    """Parse an event file -> list of {wall_time, step, scalars:{tag: val}}.

    Raises ``ValueError`` on CRC mismatch when ``verify_crc`` (the framing
    is exactly what TensorBoard checks, so a pass here means TB loads it).
    """
    out = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        (len_crc,) = struct.unpack_from("<I", data, pos + 8)
        payload = data[pos + 12: pos + 12 + length]
        (pay_crc,) = struct.unpack_from("<I", data, pos + 12 + length)
        if verify_crc:
            if _masked_crc(data[pos: pos + 8]) != len_crc:
                raise ValueError(f"length CRC mismatch at offset {pos}")
            if _masked_crc(payload) != pay_crc:
                raise ValueError(f"payload CRC mismatch at offset {pos}")
        pos += 12 + length + 4

        record = {"wall_time": None, "step": 0, "scalars": {},
                  "file_version": None}
        for num, _wt, raw in _parse_fields(payload):
            if num == 1:
                record["wall_time"] = struct.unpack("<d", raw)[0]
            elif num == 2:
                record["step"], _ = _decode_varint(raw, 0)
            elif num == 3:
                record["file_version"] = raw.decode()
            elif num == 5:
                for vnum, _vwt, vraw in _parse_fields(raw):
                    if vnum != 1:
                        continue
                    tag, val = None, None
                    for fnum, _fwt, fraw in _parse_fields(vraw):
                        if fnum == 1:
                            tag = fraw.decode()
                        elif fnum == 2:
                            val = struct.unpack("<f", fraw)[0]
                    if tag is not None and val is not None:
                        record["scalars"][tag] = val
        out.append(record)
    return out
