"""Cross-thread device-dispatch serialization for fragile backends.

Concurrent-trial executors (``ThreadTrialExecutor``) run many trials as
Python threads inside one process; each trial fires its own device
calls (init, per-epoch train program, eval, checkpoint readback).  On a
normal local backend that is fine — XLA serializes execution on the
device and the runtime is thread-safe.  A *remote* single-chip tunnel
(the axon relay this project benches through) is not: both recorded
tunnel wedges (2026-07-31 session 6, 2026-08-01 09:10 UTC — see
benchmarks/RESULTS.md) happened at the one workload whose dispatches
come from multiple threads at once (the bohb thread-executor cohort),
while single-threaded dispatchers (vectorized sweeps, pbt, the suite)
ran clean in the same sessions.

``dispatch_lock()`` returns a context manager that serializes the
device-call sections of concurrent trials when serialization is on, and
is a no-op otherwise:

- ``DML_SERIALIZE_DISPATCH=1`` forces it on, ``=0`` forces it off;
- unset, it defaults to ON exactly when the axon tunnel sitecustomize
  is on ``PYTHONPATH`` (the one backend with the observed failure mode).

Serialization costs thread-level device overlap — which a one-chip
tunnel cannot deliver anyway (the chip runs one program at a time;
interleaved host->tunnel traffic buys nothing but relay pressure) — and
keeps host-side work (scheduler bookkeeping, checkpoint serialization,
data prep) fully concurrent.

The reference stack has no analogue: Ray actors are processes, so its
trials never share a CUDA context from threads
(ray-tune-hpo-regression.py:469-480 relies on actor isolation).
"""

from __future__ import annotations

import contextlib
import os
import threading

from distributed_machine_learning_tpu.analysis.locks import named_lock
# Named + reentrant: participates in the lock-order graph
# (analysis/locks.py) under the role "dispatch".
_LOCK = named_lock("dispatch", reentrant=True)
_resolved: bool | None = None


def _serialize_on() -> bool:
    global _resolved
    if _resolved is None:
        flag = os.environ.get("DML_SERIALIZE_DISPATCH", "").strip()
        if flag in ("1", "true", "on"):
            _resolved = True
        elif flag in ("0", "false", "off"):
            _resolved = False
        else:
            _resolved = ".axon_site" in os.environ.get("PYTHONPATH", "")
    return _resolved


def _reset_for_tests() -> None:
    global _resolved
    _resolved = None


def serialization_on() -> bool:
    """Whether dispatch serialization is active for this process.

    The resolution is captured at FIRST use (then cached for the process
    lifetime): set ``DML_SERIALIZE_DISPATCH`` before the first trial
    runs, not mid-run.
    """
    return _serialize_on()


def dispatch_lock():
    """Context manager guarding a device-call section of a trial.

    Reentrant (RLock): a guarded section may call helpers that guard
    themselves. No-op unless serialization resolved on (see module doc;
    resolution is captured at first use — ``serialization_on``).
    """
    if _serialize_on():
        return _LOCK
    return contextlib.nullcontext()
