"""Deterministic seeding helpers shared by samplers, data generators, and trials."""

from __future__ import annotations

import hashlib

import numpy as np


def rng_from(*parts) -> np.random.Generator:
    """Build a numpy Generator from an arbitrary tuple of seed parts.

    Hashing makes (experiment_seed, trial_index) style derivations stable across
    processes and platforms, unlike Python's salted ``hash``.
    """
    h = hashlib.sha256("/".join(str(p) for p in parts).encode()).digest()
    return np.random.default_rng(int.from_bytes(h[:8], "little"))


def fold_seed(*parts) -> int:
    """A stable 31-bit integer seed derived from the parts (for jax.random.key)."""
    h = hashlib.sha256("/".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:4], "little") & 0x7FFFFFFF


def init_rngs_for(seed):
    """The per-trial model-init rng streams ({"params", "dropout"}) derived
    from a trial seed — ONE derivation shared by the thread-executor and
    sharded trainables, so same-seed trials init identically on both paths.
    """
    import jax

    return {
        "params": jax.random.key(fold_seed(seed, "init")),
        "dropout": jax.random.key(fold_seed(seed, "init_dropout")),
    }
