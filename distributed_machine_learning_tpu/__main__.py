"""Package CLI: ``python -m distributed_machine_learning_tpu <command>``.

The reference's launch surface is ``python <script>.py`` (SURVEY.md §1 L5);
the framework keeps that for experiment drivers (your script calls
``tune.run``) and adds the operational commands a multi-host deployment
needs:

* ``worker`` — start a host trial supervisor (or ``--join`` a driver
  elastically); forwards to ``tune.cluster``'s CLI.
* ``info`` — print the jax backend/device/mesh view of THIS process, the
  first thing to check when a pod host misbehaves.
* ``export-orbax <ckpt.msgpack> <out_dir>`` — convert a framework
  checkpoint to an orbax StandardCheckpoint for orbax-consuming stacks.
* ``probe [--timeout S]`` — bounded accelerator health check in a CHILD
  process (a wedged backend times out instead of hanging this shell; the
  child is SIGTERMed, never SIGKILLed — a killed tunnel-holder can take
  shared relays down with it). Exit 0 = an accelerator executed a real
  computation; 1 = healthy but CPU-only; 2 = the probe child crashed
  (broken install/plugin); 124 = backend hung (the JSON records whether
  the wedged child actually exited).

Note on startup cost: ``python -m`` imports the package ``__init__`` (and
with it jax/flax/optax) before this module runs, so even ``--help`` pays
the framework import — the in-function imports below are for readability,
not deferral; there is no way to dodge an eager package ``__init__``
under ``-m``.
"""

from __future__ import annotations

import json
import sys


def _info() -> None:
    import jax

    devs = jax.devices()
    out = {
        "backend": jax.default_backend(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": len(devs),
        "device_kinds": sorted({d.device_kind for d in devs}),
    }
    try:
        from distributed_machine_learning_tpu.ops.flops import (
            device_peak_flops,
        )

        out["peak_flops_f32"] = device_peak_flops(devs[0])
        out["peak_flops_bf16"] = device_peak_flops(devs[0], "bfloat16")
    except Exception:  # noqa: BLE001 - info must print what it can
        pass
    print(json.dumps(out, indent=2))


def _probe(rest) -> None:
    import argparse
    import signal
    import subprocess

    p = argparse.ArgumentParser(prog="probe")
    p.add_argument("--timeout", type=float, default=120.0)
    args = p.parse_args(rest)
    code = (
        "import jax, jax.numpy as jnp, json\n"
        "d = jax.devices()[0]\n"
        "ok = float(jnp.ones((8, 8)).sum()) == 64.0\n"
        "print(json.dumps({'platform': d.platform,\n"
        "                  'device_kind': getattr(d, 'device_kind', None),\n"
        "                  'devices': jax.device_count(),\n"
        "                  'executed': ok}))\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        out, err = proc.communicate(timeout=args.timeout)
    except subprocess.TimeoutExpired:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.send_signal(signal.SIGINT)
            try:
                proc.communicate(timeout=15)
            except subprocess.TimeoutExpired:
                pass
        # A child wedged in native code can survive both signals — report
        # whether it is actually gone: a still-running orphan keeps holding
        # the accelerator claim, and every later probe hangs against it.
        print(json.dumps({
            "error": f"backend init/execute hung past {args.timeout}s "
                     f"(SIGTERMed; never SIGKILL a tunnel holder)",
            "child_exited": proc.poll() is not None,
            "child_pid": proc.pid,
        }))
        raise SystemExit(124)
    line = (out.strip().splitlines() or [""])[-1]
    try:
        res = json.loads(line)
    except json.JSONDecodeError:
        # Distinct from "healthy CPU-only host" (exit 1): the child CRASHED
        # (broken install, bad plugin) — a pod-health script must not read
        # that as fine-but-no-accelerator.
        print(json.dumps({"error": (err or out)[-400:]}))
        raise SystemExit(2) from None
    print(json.dumps(res))
    healthy_accel = res.get("platform") != "cpu" and res.get("executed") is True
    raise SystemExit(0 if healthy_accel else 1)


def _analyze(rest) -> None:
    import argparse
    import os

    p = argparse.ArgumentParser(prog="analyze")
    p.add_argument("experiment_dir",
                   help="an experiment directory (<storage_path>/<name>)")
    p.add_argument("--metric", default=None,
                   help="objective (default: the one recorded in "
                        "experiment_state.json)")
    p.add_argument("--mode", default=None, choices=("min", "max"))
    p.add_argument("--rows", type=int, default=10)
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    args = p.parse_args(rest)

    from distributed_machine_learning_tpu.tune.experiment import (
        ExperimentAnalysis,
    )

    root = args.experiment_dir
    if not os.path.isdir(root):  # diagnose a typo'd path FIRST
        print(f"error: no experiment directory at {root}", file=sys.stderr)
        raise SystemExit(1)
    state = {}
    state_path = os.path.join(root, "experiment_state.json")
    if os.path.exists(state_path):
        with open(state_path) as f:
            state = json.load(f)
    metric = args.metric or state.get("metric")
    mode = args.mode or state.get("mode") or "min"
    if not metric:
        print("error: experiment predates metric recording — pass --metric",
              file=sys.stderr)
        raise SystemExit(2)
    analysis = ExperimentAnalysis.from_directory(root, metric, mode)
    if not analysis.trials:
        print(f"error: no trials under {root}", file=sys.stderr)
        raise SystemExit(1)
    if not any(metric in r for t in analysis.trials for r in t.results):
        print(f"error: no trial reported metric {metric!r} under {root}",
              file=sys.stderr)
        raise SystemExit(1)
    if args.json:
        try:
            best_config, best_result = analysis.best_config, analysis.best_result
        except ValueError as exc:  # e.g. a typo'd --metric no trial reported
            print(f"error: {exc}", file=sys.stderr)
            raise SystemExit(1) from None
        print(json.dumps({
            "metric": metric,
            "mode": mode,
            "num_trials": len(analysis.trials),
            "num_terminated": analysis.num_terminated(),
            "best_config": best_config,
            "best_result": best_result,
            **{k: state[k] for k in (
                "wall_clock_s", "device_utilization",
                "compile_time_total_s", "compile_cache_hits",
            ) if k in state},
        }))
        return
    # Human view: reuse the ProgressReporter's final table verbatim.
    from distributed_machine_learning_tpu.tune.callbacks import (
        ProgressReporter,
    )

    # inf interval: no live re-renders while replaying — only the final
    # summary table prints.
    rep = ProgressReporter(interval_s=float("inf"), max_rows=args.rows)
    rep.setup(root, metric, mode)
    for t in analysis.trials:
        for r in t.results:
            rep.on_trial_result(t, r)
    rep.on_experiment_end(analysis.trials, state.get("wall_clock_s", 0.0))


def _lint(rest) -> None:
    import argparse
    import os

    p = argparse.ArgumentParser(
        prog="lint",
        description="dmlint: project-native static analysis "
                    "(docs/static-analysis.md)",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint (default: the installed "
                        "package tree)")
    p.add_argument("--rule", action="append", default=None,
                   help="run only this rule (name or id; repeatable)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: analysis/baseline.json; "
                        "'none' disables)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to absorb every current "
                        "unsuppressed finding (burn-down workflow)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings (includes suppressed/"
                        "baselined, marked); alias for --format=json")
    p.add_argument("--format", default=None,
                   choices=("text", "json", "sarif"),
                   help="report format (default: text; sarif = SARIF "
                        "2.1.0 for CI annotators)")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="lint only files touched vs a git ref (default "
                        "HEAD) — the fast pre-commit path; the whole "
                        "tree is still parsed so cross-file rules see "
                        "the full call graph, and exit codes match the "
                        "full run")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also show suppressed and baselined findings")
    p.add_argument("--jax", action="store_true",
                   help="ALSO run the program-level tier (jaxlint, "
                        "docs/static-analysis.md): partition-rule "
                        "coverage, donation verification, jaxpr hygiene, "
                        "mesh-axis soundness — imports jax but compiles "
                        "and allocates nothing")
    args = p.parse_args(rest)
    fmt = args.format or ("json" if args.json else "text")

    # The linter is stdlib-only on purpose: importing the analysis package
    # pulls in no jax (engine.py docstring) — `dml-tpu lint` stays usable
    # on hosts where backend init is broken (which is WHEN you lint).
    # --jax opts into the program-level tier and is the one path that
    # imports jax (still: eval_shape/make_jaxpr/lower only, nothing run).
    from distributed_machine_learning_tpu import analysis

    paths = args.paths or [
        os.path.dirname(os.path.abspath(analysis.__file__)) + "/.."
    ]
    # --rule restricts BOTH tiers: each name resolves to an AST rule or a
    # jax check; naming a jax check implies --jax.  A tier with no
    # selected rules is skipped entirely.
    rules = jax_checks = None
    if args.rule:
        rules, jax_checks = [], []
        for r in args.rule:
            try:
                rules.append(analysis.get_rule(r))
                continue
            except KeyError:
                pass
            try:
                jax_checks.append(analysis.get_jax_check(r))
                args.jax = True
            except KeyError:
                print(f"error: no dmlint rule or jaxlint check named "
                      f"{r!r}", file=sys.stderr)
                raise SystemExit(2) from None
    baseline = args.baseline or analysis.DEFAULT_BASELINE
    if baseline == "none":
        baseline = None
    only_files = None
    if args.changed is not None:
        only_files = _changed_python_files(args.changed, paths)
        if only_files is None:
            raise SystemExit(2)  # not a git checkout / bad ref
        if not only_files:
            print(f"dmlint: no .py files changed vs {args.changed}")
            raise SystemExit(0)
    if rules is not None and not rules:
        result = analysis.LintResult()  # only jax checks were selected
    else:
        result = analysis.lint_paths(
            paths, rules=rules, baseline_path=baseline,
            only_files=only_files,
        )
    if args.jax and (jax_checks is None or jax_checks):
        jres = analysis.run_jax_checks(
            checks=jax_checks, baseline_path=baseline,
            only_files=only_files,
        )
        result.findings.extend(jres.findings)
        result.errors.extend(jres.errors)
        result.files_checked += jres.files_checked
        result.findings.sort(key=lambda f: (f.file, f.line, f.rule_id))
    if args.update_baseline:
        if baseline is None:
            print("error: --update-baseline needs a baseline path",
                  file=sys.stderr)
            raise SystemExit(2)
        analysis.save_baseline(baseline, result.unsuppressed())
        print(f"baseline rewritten: {baseline} "
              f"({len(result.unsuppressed())} entries)")
        return
    if fmt == "json":
        print(json.dumps({
            "files_checked": result.files_checked,
            "findings": [f.to_json() for f in result.findings],
            "errors": result.errors,
            "ok": result.ok,
        }, indent=2))
    elif fmt == "sarif":
        catalog = list(rules) if rules is not None else list(
            analysis.ALL_RULES
        )
        if args.jax:
            catalog += (
                list(jax_checks) if jax_checks
                else analysis.jax_check_catalog()
            )
        print(json.dumps(analysis.render_sarif(result, catalog), indent=2))
    else:
        print(analysis.render(result, verbose=args.verbose))
    raise SystemExit(0 if result.ok else 1)


def _audit_sharding(rest) -> None:
    """``dml-tpu audit-sharding``: the jax tier plus per-family coverage
    reports — the operator view of ``lint --jax`` (same gate, same exit
    semantics, with the sharding arithmetic printed instead of implied)."""
    import argparse

    p = argparse.ArgumentParser(
        prog="audit-sharding",
        description="program-level sharding/donation audit (jaxlint; "
                    "alias for the jax tier of `lint --jax` plus "
                    "per-family partition coverage reports)",
    )
    p.add_argument("families", nargs="*", default=None,
                   help="model families to report on (default: every "
                        "registered family with canonical configs)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable reports + findings")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: analysis/baseline.json; "
                        "'none' disables)")
    args = p.parse_args(rest)

    from distributed_machine_learning_tpu import analysis
    from distributed_machine_learning_tpu.analysis.jaxlint import (
        coverage as coverage_lib,
    )
    from distributed_machine_learning_tpu.models.partition_rules import (
        PARTITION_RULE_TABLES,
    )

    families = args.families or sorted(
        f for f in coverage_lib.KNOWN_FAMILY_CONFIGS
        if f in PARTITION_RULE_TABLES
    )
    reports = []
    for family in families:
        if family not in PARTITION_RULE_TABLES:
            print(f"error: no partition-rule table for family "
                  f"{family!r}", file=sys.stderr)
            raise SystemExit(2)
        reports.append(coverage_lib.coverage_report(family))
    # A shared table's rule is dead only if NO audited family fires it
    # (the same union the lint gate applies) — the report must not claim
    # debt the gate would not.
    fired_union = {}
    for rep in reports:
        key = (rep["anchor_path"], rep["anchor_symbol"])
        fired_union.setdefault(key, set()).update(rep["fired"])
    for rep in reports:
        key = (rep["anchor_path"], rep["anchor_symbol"])
        rep["dead_rules"] = [
            d for d in rep["dead_rules"]
            if d["index"] not in fired_union[key]
        ]
    baseline = args.baseline or analysis.DEFAULT_BASELINE
    if baseline == "none":
        baseline = None
    result = analysis.run_jax_checks(baseline_path=baseline)
    if args.json:
        print(json.dumps({
            "reports": reports,
            "findings": [f.to_json() for f in result.findings],
            "errors": result.errors,
            "inert": result.inert,
            "ok": result.ok,
        }, indent=2))
        raise SystemExit(0 if result.ok else 1)
    for rep in reports:
        covered = rep["num_leaves"] - len(rep["unmatched"])
        print(f"[{rep['family']}] {rep['num_rules']} rule(s), "
              f"{rep['num_leaves']} non-scalar leaves over configs "
              f"({', '.join(rep['configs'])}): {covered} covered, "
              f"{len(rep['unmatched'])} unmatched, "
              f"{len(rep['dead_rules'])} dead rule(s), "
              f"{len(rep['non_dividing'])} non-dividing")
        for u in rep["unmatched"]:
            print(f"    unmatched: {u['path']} {u['shape']} "
                  f"({100 * u['fraction']:.1f}%, {u['config']})")
        for d in rep["dead_rules"]:
            print(f"    dead: {d['pattern']}")
        for n in rep["non_dividing"]:
            print(f"    non-dividing: {n['path']} dim {n['dim']} vs "
                  f"{n['axis']} of {n['mesh']}")
    print(analysis.render(result))
    print(f"jaxlint inert: {result.inert}")
    raise SystemExit(0 if result.ok else 1)


def _changed_python_files(ref, paths):
    """Absolute paths of ``.py`` files changed vs ``ref`` (committed diff
    + working tree + untracked), or None when git/ref is unusable.  The
    repo is found from the first lint path, so ``dml-tpu lint pkg/
    --changed`` works from anywhere inside the checkout."""
    import os
    import subprocess

    anchor = os.path.abspath(paths[0])
    cwd = anchor if os.path.isdir(anchor) else os.path.dirname(anchor)
    try:
        root = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=cwd, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired) as exc:
        print(f"error: --changed needs git: {exc}", file=sys.stderr)
        return None
    if root.returncode != 0:
        print(f"error: --changed outside a git checkout: "
              f"{root.stderr.strip()}", file=sys.stderr)
        return None
    top = root.stdout.strip()
    out = []
    for cmd in (
        ["git", "diff", "--name-only", ref, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            cmd, cwd=top, capture_output=True, text=True, timeout=60,
        )
        if proc.returncode != 0:
            print(f"error: {' '.join(cmd)}: {proc.stderr.strip()}",
                  file=sys.stderr)
            return None
        out.extend(
            line.strip() for line in proc.stdout.splitlines()
            if line.strip().endswith(".py")
        )
    return sorted({os.path.join(top, rel) for rel in out})


def _trace(rest) -> None:
    """``dml-tpu trace {export|merge|summarize}``: the operator surface of
    the observability plane (obs/, docs/observability.md)."""
    import argparse
    import os

    p = argparse.ArgumentParser(
        prog="trace",
        description="export / merge / summarize structured traces "
                    "(tune.run(trace=True) or DML_OBS_TRACE=1)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    p_exp = sub.add_parser(
        "export",
        help="merge an experiment's per-process span files into one "
             "Chrome-trace/Perfetto trace.json",
    )
    p_exp.add_argument("experiment_dir",
                       help="an experiment directory (or its trace/ dir)")
    p_exp.add_argument("-o", "--out", default=None,
                       help="output path (default: <trace_dir>/trace.json)")

    p_merge = sub.add_parser(
        "merge",
        help="merge trace dirs/experiment dirs from several hosts into "
             "one trace.json",
    )
    p_merge.add_argument("sources", nargs="+",
                         help="trace directories (or experiment dirs)")
    p_merge.add_argument("-o", "--out", required=True)

    p_sum = sub.add_parser(
        "summarize",
        help="per-phase wall-clock breakdown table (one trial with "
             "--trial; the MFU 'where did the time go' view)",
    )
    p_sum.add_argument("source",
                       help="experiment dir, trace dir, or trace.json")
    p_sum.add_argument("--trial", default=None,
                       help="restrict to spans of one trial id")
    p_sum.add_argument("--json", action="store_true")
    args = p.parse_args(rest)

    from distributed_machine_learning_tpu import obs

    def resolve_trace_dir(path):
        sub_dir = os.path.join(path, "trace")
        return sub_dir if os.path.isdir(sub_dir) else path

    if args.cmd == "export":
        trace_dir = resolve_trace_dir(args.experiment_dir)
        if not os.path.isdir(trace_dir):
            print(f"error: no directory at {trace_dir}", file=sys.stderr)
            raise SystemExit(1)
        out = obs.merge_trace_dir(trace_dir, args.out)
        if out is None:
            print(f"error: no trace_*.jsonl span files under {trace_dir} "
                  f"(was the run traced? tune.run(trace=True) or "
                  f"DML_OBS_TRACE=1)", file=sys.stderr)
            raise SystemExit(1)
        print(out)
    elif args.cmd == "merge":
        records = []
        for src in args.sources:
            trace_dir = resolve_trace_dir(src)
            if not os.path.isdir(trace_dir):
                print(f"error: no directory at {trace_dir}",
                      file=sys.stderr)
                raise SystemExit(1)
            records.extend(obs.read_trace_files(trace_dir))
        if not records:
            print("error: no span records in any source", file=sys.stderr)
            raise SystemExit(1)
        with open(args.out, "w") as f:
            json.dump(obs.chrome_trace(records), f)
        print(args.out)
    else:
        try:
            rows, table = obs.summarize_trace(args.source, trial=args.trial)
        except (OSError, ValueError, KeyError) as exc:
            print(f"error: cannot summarize {args.source}: {exc}",
                  file=sys.stderr)
            raise SystemExit(1) from None
        if args.json:
            print(json.dumps({"trial": args.trial, "phases": rows}))
        else:
            if args.trial:
                print(f"trial {args.trial}:")
            print(table)


def _perf(rest) -> None:
    """``dml-tpu perf {compare|audit}``: the operator surface of the
    performance observatory (perf/, docs/performance.md)."""
    import argparse
    import glob as glob_lib

    p = argparse.ArgumentParser(
        prog="perf",
        description="cost-model audit + bench regression sentinel "
                    "(perf/costmodel.py, perf/sentinel.py)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    p_cmp = sub.add_parser(
        "compare",
        help="bucket BENCH_r*/MULTICHIP_r* rounds into comparability "
             "classes and verdict only within a class (exit 1 on an "
             "in-class regression beyond the noise band)",
    )
    p_cmp.add_argument("--artifacts", nargs="+", required=True,
                       help="round artifact paths or globs "
                            "(BENCH_r*.json MULTICHIP_r*.json)")
    p_cmp.add_argument("--noise", type=float, default=None,
                       help="noise band as a fraction (default 0.15: "
                            "+/-15%% is flat, not a verdict)")
    p_cmp.add_argument("--json", action="store_true")

    p_aud = sub.add_parser(
        "audit",
        help="compile tiny canonical programs per model family on THIS "
             "backend and cross-check XLA's cost_analysis() FLOPs "
             "against the analytic model in ops/flops.py (exit 1 on "
             "divergence beyond tolerance)",
    )
    p_aud.add_argument("families", nargs="*",
                       default=None,
                       help="model families (default: mlp "
                            "simple_transformer transformer)")
    p_aud.add_argument("--tolerance", type=float, default=None,
                       help="ratio tolerance (default "
                            "perf.DEFAULT_CROSSCHECK_TOL)")
    p_aud.add_argument("--json", action="store_true")
    args = p.parse_args(rest)

    from distributed_machine_learning_tpu import perf

    if args.cmd == "compare":
        paths = []
        for pat in args.artifacts:
            hits = sorted(glob_lib.glob(pat))
            paths.extend(hits if hits else [pat])
        rounds = perf.load_rounds(paths)
        if not rounds:
            print(f"error: no BENCH_r*/MULTICHIP_r* artifacts among "
                  f"{args.artifacts}", file=sys.stderr)
            raise SystemExit(2)
        report = perf.evaluate_rounds(
            rounds,
            noise_band=(args.noise if args.noise is not None
                        else perf.DEFAULT_NOISE_BAND),
        )
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            print(perf.render_report(report))
        raise SystemExit(0 if report["ok"] else 1)

    # audit: zero-extra-compile discipline does not apply here — this IS
    # the command that compiles (tiny) programs, on purpose, to judge
    # the analytic model on the current backend.
    import jax
    import numpy as np

    from distributed_machine_learning_tpu.models import build_model
    from distributed_machine_learning_tpu.ops.flops import (
        device_peak_flops,
        forward_flops,
    )

    families = args.families or ["mlp", "simple_transformer",
                                 "transformer"]
    tol = (args.tolerance if args.tolerance is not None
           else perf.DEFAULT_CROSSCHECK_TOL)
    batch, seq, feats = 8, 16, 4
    rows = []
    ok = True
    for family in families:
        config = {"model": family, "dropout": 0.0}
        x = np.zeros((batch, seq, feats), np.float32)
        if family == "mlp":
            x = x.reshape(batch, seq * feats)
        model = build_model(config)
        variables = model.init(jax.random.key(0), x)

        def apply(v, xin):
            return model.apply(v, xin, deterministic=True)

        compiled = jax.jit(apply).lower(variables, x).compile()
        cost = perf.extract_cost(compiled)
        analytic = forward_flops(config, batch, seq, feats)
        finding = perf.crosscheck(
            analytic, (cost or {}).get("flops"), tolerance=tol,
            label=family,
        )
        dev = jax.devices()[0]
        row = {
            "family": family,
            "analytic_flops": analytic,
            "measured_flops": (cost or {}).get("flops"),
            "ratio": (
                round(cost["flops"] / analytic, 4)
                if cost and cost.get("flops") and analytic else None
            ),
            "roofline": perf.roofline(
                cost,
                device_peak_flops(dev),
                perf.device_hbm_bandwidth(dev),
            ),
            "divergence": finding,
        }
        rows.append(row)
        if finding is not None:
            ok = False
    if args.json:
        print(json.dumps({"tolerance": tol, "programs": rows, "ok": ok},
                         indent=1))
    else:
        for r in rows:
            ratio = f"{r['ratio']:.2f}x" if r["ratio"] else "n/a"
            verdict = (
                f"DIVERGENT ({r['divergence']['kind']})"
                if r["divergence"] else "ok"
            )
            bound = (r["roofline"] or {}).get("bound") or "?"
            print(f"[{r['family']}] measured/analytic {ratio} "
                  f"({verdict}); roofline: {bound}-bound")
    raise SystemExit(0 if ok else 1)


def _export_bundle(rest) -> None:
    import argparse

    p = argparse.ArgumentParser(prog="export-bundle")
    p.add_argument("experiment_dir",
                   help="an experiment directory (<storage_path>/<name>)")
    p.add_argument("out_dir", help="bundle directory to create")
    p.add_argument("--metric", default=None,
                   help="objective (default: recorded in "
                        "experiment_state.json)")
    p.add_argument("--mode", default=None, choices=("min", "max"))
    p.add_argument("--trial", default=None,
                   help="serve a specific trial instead of the best")
    p.add_argument("--precision", default="f32",
                   choices=("f32", "bf16", "int8"),
                   help="stored weight dtype (quant/); bf16/int8 require "
                        "--calibration")
    p.add_argument("--calibration", default=None,
                   help="path to a .npy calibration batch (n, features...) "
                        "— quantized exports measure their quality delta "
                        "on it")
    args = p.parse_args(rest)

    from distributed_machine_learning_tpu.serve import export_bundle

    calibration = None
    if args.calibration:
        import numpy as np

        calibration = np.load(args.calibration)
    try:
        out = export_bundle(
            args.experiment_dir, args.out_dir,
            metric=args.metric, mode=args.mode, trial_id=args.trial,
            precision=args.precision, calibration_batch=calibration,
        )
    except (FileNotFoundError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        raise SystemExit(1) from None
    note = f" [{args.precision}]" if args.precision != "f32" else ""
    print(f"exported best trial of {args.experiment_dir} -> {out}{note}")


def _loop(rest) -> None:
    """Self-healing loop status: the journal's episode/state/history plus
    the controller counters from an adjacent experiment_state.json —
    stdlib-only (readable from any host, no jax import)."""
    import argparse
    import json as _json
    import os as _os

    p = argparse.ArgumentParser(
        prog="loop",
        description="inspect a self-healing loop's journal (loop/)",
    )
    p.add_argument("action", choices=("status",))
    p.add_argument("path",
                   help="the journal file, or a loop out_dir containing "
                        "loop.json")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    args = p.parse_args(rest)

    path = args.path
    if _os.path.isdir(path):
        path = _os.path.join(path, "loop.json")
    try:
        with open(path) as f:
            doc = _json.load(f)
    except (OSError, ValueError) as exc:
        print(f"error: cannot read journal {path}: {exc}", file=sys.stderr)
        raise SystemExit(1) from None
    state_path = _os.path.join(_os.path.dirname(path),
                               "experiment_state.json")
    counters = None
    try:
        with open(state_path) as f:
            counters = _json.load(f).get("loop")
    except (OSError, ValueError):
        pass
    if args.as_json:
        print(_json.dumps({"journal": doc, "counters": counters},
                          indent=2))
        return
    from distributed_machine_learning_tpu.loop.journal import (
        TERMINAL_STATES,
    )

    state = doc.get("state")
    open_note = (
        "" if state is None or state in TERMINAL_STATES
        else "  [OPEN - a controller should resume() this]"
    )
    print(f"episode {doc.get('episode', 0)}: "
          f"{state or 'never triggered'}{open_note}")
    if doc.get("trace_id"):
        print(f"trace_id: {doc['trace_id']}")
    print(f"completed episodes: {doc.get('completed_episodes', 0)} "
          f"(promotions: {doc.get('promotions', 0)}, "
          f"rollbacks: {doc.get('rollbacks', 0)})")
    history = doc.get("history", [])
    if history:
        print("history:")
        t0 = history[0].get("at_unix")
        for h in history:
            dt = (f"+{h['at_unix'] - t0:.2f}s"
                  if t0 and h.get("at_unix") else "")
            detail = {k: v for k, v in h.items()
                      if k not in ("state", "at_unix")
                      and isinstance(v, (str, int, float, bool))}
            tail = ("  " + ", ".join(
                f"{k}={v}" for k, v in sorted(detail.items())
            )) if detail else ""
            print(f"  {dt:>9}  {h.get('state')}{tail}")
    if counters:
        print("controller counters: " + ", ".join(
            f"{k}={counters[k]}" for k in (
                "episodes", "promotions", "rollbacks", "resumes",
                "gate_rejects", "aborts",
            ) if k in counters
        ))


def _journal(rest) -> None:
    """Durable-control-plane status: the head's write-ahead decision
    journal for an experiment (tune/journal.py) — committed or left open by
    a crashed head, decision count, head incarnations/replays, per-trial
    report watermarks.  Stdlib-only (readable from any host, no jax
    import); docs/operations.md 'Head crash recovery' is the runbook."""
    import argparse
    import json as _json
    import os as _os

    p = argparse.ArgumentParser(
        prog="journal",
        description="inspect an experiment's head decision journal "
                    "(tune/journal.py)",
    )
    p.add_argument("action", choices=("status",))
    p.add_argument("path",
                   help="the experiment directory (containing "
                        "journal.jsonl), or the journal file itself")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    args = p.parse_args(rest)

    from distributed_machine_learning_tpu.tune.journal import (
        FILENAME,
        journal_status,
    )

    root = args.path
    if _os.path.basename(root) == FILENAME:
        root = _os.path.dirname(root) or "."
    status = journal_status(root)
    if args.as_json:
        print(_json.dumps(status, indent=2))
        return
    if not status["present"]:
        print(f"no journal at {_os.path.join(root, FILENAME)}")
        raise SystemExit(1)
    state = (
        "committed (experiment ended cleanly)" if status["committed"]
        else "OPEN — head died mid-sweep; resume with resume=\"auto\""
    )
    print(f"journal {status['path']}: {state}")
    print(f"decisions: {status['decisions']} "
          f"({status['records']} records, next trial index "
          f"{status['next_index']})")
    print(f"head incarnations: {status['head_starts']} "
          f"(journal replays: {status['replays']})")
    if status.get("trace_id"):
        print(f"trace_id: {status['trace_id']}")
    trials = status.get("trials") or {}
    if trials:
        print("trials:")
        for tid in sorted(trials):
            t = trials[tid]
            print(f"  {tid}: reported through iteration "
                  f"{t['reported_through']}, last decision "
                  f"{t['decision_at_watermark'] or '-'}"
                  + (f", terminal {t['status']}" if t.get("status")
                     else ""))
    if status.get("last_record"):
        print(f"last record: {status['last_record']}")


def _store(rest) -> None:
    """Content-store operator surface (store/): dedup stats, blob
    integrity verification, and reachability GC — the runbook commands
    behind docs/operations.md's store rows.  GC is a DRY RUN unless
    --run is given: it reports what the sweep would collect without
    deleting anything."""
    import argparse
    import json as _json
    import os as _os

    p = argparse.ArgumentParser(
        prog="store",
        description="inspect / verify / garbage-collect a content-"
                    "addressed store (store/)",
    )
    p.add_argument("action", choices=("stats", "verify", "gc"))
    p.add_argument("path",
                   help="the store root (a .cas directory), or any "
                        "directory it serves — an experiment or "
                        "checkpoint dir resolves to its .cas sibling "
                        "exactly the way writers do")
    p.add_argument("--run", action="store_true",
                   help="gc: actually delete unreachable blobs "
                        "(default is a dry run)")
    p.add_argument("--dry-run", action="store_true",
                   help="gc: report-only sweep (the default; explicit "
                        "spelling for scripts)")
    p.add_argument("--min-age-s", type=float, default=0.0,
                   help="gc: retain blobs younger than this many "
                        "seconds regardless of reachability (guards "
                        "cross-process writers beyond the pin table)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    args = p.parse_args(rest)
    if args.run and args.dry_run:
        p.error("--run and --dry-run are mutually exclusive")

    from distributed_machine_learning_tpu import store as store_lib

    root = args.path
    if (
        _os.path.basename(root.rstrip("/")) != store_lib.STORE_DIR_NAME
        and not _os.path.isdir(_os.path.join(root, store_lib.BLOBS_DIR))
    ):
        root = store_lib.store_root_for(_os.path.join(root, "_"))
    cas = store_lib.get_store(root)

    if args.action == "stats":
        out = cas.stats()
        if args.as_json:
            print(_json.dumps(out, indent=2, sort_keys=True))
            return
        print(f"store {out['root']}: {out['blobs']} blob(s), "
              f"{out['refs']} ref(s), {out['physical_bytes']} "
              f"physical byte(s)")
        c = out["counters"]
        print(f"this process: {c.get('puts', 0)} put(s), "
              f"{c.get('dedup_hits', 0)} dedup hit(s), "
              f"{c.get('bytes_logical', 0)} logical -> "
              f"{c.get('bytes_physical', 0)} physical byte(s) "
              f"(ratio {out['dedup_ratio']})")
    elif args.action == "verify":
        out = cas.verify()
        out["root"] = cas.root
        if args.as_json:
            print(_json.dumps(out, indent=2, sort_keys=True))
        else:
            print(f"store {cas.root}: {out['blobs']} blob(s) checked, "
                  f"{len(out['corrupt'])} corrupt")
            for digest in out["corrupt"]:
                print(f"  corrupt: {digest}")
        if out["corrupt"]:
            raise SystemExit(1)
    else:
        out = cas.gc(dry_run=not args.run, min_age_s=args.min_age_s)
        out["root"] = cas.root
        if args.as_json:
            print(_json.dumps(out, indent=2, sort_keys=True))
            return
        verb = "collected" if args.run else "would collect"
        print(f"store {cas.root}: {verb} {out['collected']} blob(s) "
              f"({out['reclaimed_bytes']} byte(s)), retained "
              f"{out['retained']}; {out['refs']} ref(s), "
              f"{out['broken_refs']} broken")
        if not args.run:
            print("dry run — pass --run to delete")


def _serve(rest) -> None:
    import argparse
    import time

    p = argparse.ArgumentParser(prog="serve")
    p.add_argument("--bundle", required=True,
                   help="a bundle directory (export-bundle's output)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--replicas", type=int, default=2,
                   help="initial replica count")
    p.add_argument("--max-batch-size", type=int, default=64)
    p.add_argument("--max-latency-ms", type=float, default=5.0,
                   help="micro-batcher flush deadline (--batcher micro)")
    p.add_argument("--max-bucket", type=int, default=256,
                   help="largest padded batch program (power-of-two grid)")
    p.add_argument("--batcher", choices=("continuous", "micro"),
                   default="continuous",
                   help="continuous = inflight, depth-adaptive flushes "
                        "(default); micro = size-or-latency")
    p.add_argument("--max-queue", type=int, default=1024,
                   help="bounded per-replica request queue; a full queue "
                        "answers 429 + Retry-After")
    p.add_argument("--target-step-ms", type=float, default=None,
                   help="latency budget per flush: the continuous batcher "
                        "steps its batch cap down the bucket grid while "
                        "the measured step time exceeds this")
    p.add_argument("--shed-watermark", type=int, default=None,
                   help="total queued requests past which admission "
                        "control sheds with 429 (default: off)")
    p.add_argument("--min-replicas", type=int, default=None,
                   help="autoscaler floor (default: --replicas)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="autoscaler ceiling; > --min-replicas enables the "
                        "autoscaler (default: off)")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="autoscaler scale-up trigger on windowed p99")
    p.add_argument("--autoscale-interval-s", type=float, default=0.5)
    p.add_argument("--tb-logdir", default=None,
                   help="stream /metrics scalars to a TensorBoard run dir")
    p.add_argument("--warmup-shape", default=None,
                   help="comma-separated per-row input shape (e.g. "
                        "'50,10' for seq x features) to pre-compile every "
                        "batch bucket before accepting traffic")
    p.add_argument("--gang", type=int, default=None,
                   help="pod-scale serving: each replica is a gang of N "
                        "member processes over a TP-spanning mesh "
                        "(serve/gang.py); the bundle is resharded onto "
                        "the gang's serving mesh at load")
    p.add_argument("--gang-devices", type=int, default=1,
                   help="local devices per gang member (with --gang)")
    args = p.parse_args(rest)

    import numpy as np

    from distributed_machine_learning_tpu.serve import (
        PredictionServer,
        load_bundle,
    )

    try:
        bundle = load_bundle(args.bundle)
    except (FileNotFoundError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        raise SystemExit(1) from None
    autoscale = None
    lo = args.min_replicas if args.min_replicas is not None else args.replicas
    hi = args.max_replicas if args.max_replicas is not None else args.replicas
    if hi > lo:
        from distributed_machine_learning_tpu.serve import AutoscaleConfig

        autoscale = AutoscaleConfig(
            min_replicas=lo, max_replicas=hi,
            slo_p99_ms=args.slo_p99_ms,
            interval_s=args.autoscale_interval_s,
        )
    replica_factory = None
    if args.gang:
        from distributed_machine_learning_tpu.serve import (
            make_gang_replica_factory,
        )

        replica_factory = make_gang_replica_factory(
            processes=args.gang, local_devices=args.gang_devices,
        )
        # Source -> target topology at startup: the manifest records the
        # TRAINING topology (mesh shape, process count, rule fingerprint),
        # so the operator sees reshard-vs-direct before the first request.
        print(json.dumps({
            "gang_serving": {
                "source_topology": bundle.source_topology,
                "target_topology": {
                    "process_count": args.gang,
                    "local_device_counts": (
                        [args.gang_devices] * args.gang
                    ),
                },
            },
        }), flush=True)
    server = PredictionServer(
        bundle,
        host=args.host,
        port=args.port,
        num_replicas=args.replicas,
        max_batch_size=args.max_batch_size,
        max_latency_ms=args.max_latency_ms,
        max_bucket=args.max_bucket,
        batcher=args.batcher,
        max_queue=args.max_queue,
        target_step_ms=args.target_step_ms,
        shed_watermark=args.shed_watermark,
        autoscale=autoscale,
        tb_logdir=args.tb_logdir,
        replica_factory=replica_factory,
    )
    if args.warmup_shape:
        dims = tuple(
            int(d) for d in args.warmup_shape.split(",") if d.strip()
        )
        stats = server.warmup(np.zeros((1, *dims), np.float32))
        print(json.dumps({"warmup": stats}))
    host, port = server.start()
    print(json.dumps({
        "serving": f"http://{host}:{port}",
        "model_family": bundle.model_family,
        # Always printed (satellite of the quant/ PR): a mixed fleet's
        # logs say which dtype each process answers in.
        "precision": bundle.precision,
        "quality_delta_mape": bundle.quality_delta_mape,
        "replicas": args.replicas,
        "gang": args.gang,
        "batcher": args.batcher,
        "autoscale": (
            {"min": lo, "max": hi} if autoscale is not None else None
        ),
        "endpoints": ["/predict", "/healthz", "/metrics", "/admin/swap"],
    }), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("shutting down", file=sys.stderr)
    finally:
        server.close()


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    usage = (
        "usage: python -m distributed_machine_learning_tpu "
        "{worker|info|probe|analyze|lint|audit-sharding|perf|trace|serve|"
        "loop|journal|store|export-bundle|export-orbax} [args]\n"
        "  worker         host trial supervisor (see 'worker --help')\n"
        "  lint           dmlint static analysis over the package (or given\n"
        "                 paths); exit 1 on any unsuppressed finding\n"
        "                 (--changed for pre-commit, --format=sarif for CI,\n"
        "                 --jax for the program-level jaxlint tier)\n"
        "  audit-sharding program-level sharding/donation audit (the jax\n"
        "                 tier + per-family partition coverage reports)\n"
        "  perf           compare: bench-round regression sentinel over\n"
        "                 BENCH_r*/MULTICHIP_r* artifacts (comparability\n"
        "                 classes; exit 1 on an in-class regression);\n"
        "                 audit: XLA cost-model vs analytic FLOPs\n"
        "  info           jax backend/device summary for this process\n"
        "  probe          bounded accelerator health check (child process)\n"
        "  analyze        <experiment_dir>: best config + trial table of a\n"
        "                 finished/interrupted experiment (--json for tools)\n"
        "  trace          export/merge/summarize structured traces from a\n"
        "                 traced run (tune.run(trace=True)): Chrome-trace/\n"
        "                 Perfetto JSON + per-phase wall-clock breakdowns\n"
        "  export-bundle  <experiment_dir> <out_dir>: freeze the best\n"
        "                 trial into a servable bundle (serve/export.py)\n"
        "  serve          --bundle <dir>: HTTP prediction service over\n"
        "                 compiled replicas (/predict /healthz /metrics)\n"
        "  loop           status <journal|out_dir>: a self-healing loop's\n"
        "                 episode state, history, and counters (loop/)\n"
        "  journal        status <experiment_dir>: the head's write-ahead\n"
        "                 decision journal — committed vs crash-open,\n"
        "                 incarnations, per-trial report watermarks\n"
        "  store          {stats|verify|gc} <root>: content-addressed\n"
        "                 store surface (store/) — dedup stats, blob\n"
        "                 integrity, reachability GC (gc is a dry run\n"
        "                 unless --run)\n"
        "  export-orbax   <ckpt.msgpack> <out_dir>: framework checkpoint\n"
        "                 -> orbax StandardCheckpoint"
    )
    if not argv or argv[0] in ("-h", "--help"):
        print(usage)
        return
    cmd, rest = argv[0], argv[1:]
    if cmd == "worker":
        from distributed_machine_learning_tpu.tune.cluster import _main

        _main(rest)
    elif cmd == "info":
        _info()
    elif cmd == "probe":
        _probe(rest)
    elif cmd == "analyze":
        _analyze(rest)
    elif cmd == "lint":
        _lint(rest)
    elif cmd == "audit-sharding":
        _audit_sharding(rest)
    elif cmd == "perf":
        _perf(rest)
    elif cmd == "trace":
        _trace(rest)
    elif cmd == "serve":
        _serve(rest)
    elif cmd == "loop":
        _loop(rest)
    elif cmd == "journal":
        _journal(rest)
    elif cmd == "store":
        _store(rest)
    elif cmd == "export-bundle":
        _export_bundle(rest)
    elif cmd == "export-orbax":
        if len(rest) != 2:
            print(usage, file=sys.stderr)
            raise SystemExit(2)
        from distributed_machine_learning_tpu.tune.checkpoint import (
            export_orbax,
        )

        try:
            out = export_orbax(rest[0], rest[1])
        except ImportError:
            print("error: orbax-checkpoint is not installed "
                  "(pip install 'distributed-machine-learning-tpu[orbax]')",
                  file=sys.stderr)
            raise SystemExit(1) from None
        except (FileNotFoundError, ValueError) as exc:
            # The predictable misuses (missing checkpoint, out_dir already
            # exists) get a one-liner, not a stack dump.
            print(f"error: {exc}", file=sys.stderr)
            raise SystemExit(1) from None
        print(f"exported {rest[0]} -> {out}")
    else:
        print(usage, file=sys.stderr)
        raise SystemExit(2)


if __name__ == "__main__":
    main()
