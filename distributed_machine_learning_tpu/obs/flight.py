"""Always-on flight recorder: the last N events of THIS process, cheap
enough to leave running everywhere.

Every fail-slow path in the stack — watchdog expiry, STALLED transitions,
lease expiry on a silent worker, a replica breaker opening, SIGTERM of a
wedged child, a wedged TPU bench probe — used to leave behind exactly one
counter increment.  The flight recorder turns each of those into "here are
the last ~2048 timestamped events this process saw", dumped automatically
at the moment the fail-slow path fires.

Design constraints (and how they're met):

* **Bounded + preallocated** — a fixed ring of ``capacity`` slots
  allocated once; recording can never grow memory.
* **Lock-free, single-writer per slot** — slot claims go through
  ``itertools.count()`` (its ``__next__`` is C-atomic under the GIL), so
  concurrent recorders from many threads interleave without a lock and a
  recorder can never block a hot path.
* **Crash-safe (opt-in mirror)** — a process that may die holding the
  ring in memory (the TPU bench probe child, which can wedge in native
  code where no signal handler runs) sets ``mirror_path``: every event is
  ALSO appended as a JSON line immediately, so the forensics survive even
  a SIGKILL.  Mirroring is off by default — hot paths pay only the ring
  write.

Recording must never raise: a telemetry failure inside a failure handler
would mask the original incident.  Dump failures are counted
(``obs.export_failures`` in the registry), never raised.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from distributed_machine_learning_tpu.obs.registry import get_registry

CAPACITY_ENV = "DML_OBS_FLIGHT_CAPACITY"
MIRROR_ENV = "DML_OBS_FLIGHT_MIRROR"
DUMP_DIR_ENV = "DML_OBS_DUMP_DIR"

DEFAULT_CAPACITY = 2048


class FlightRecorder:
    """Fixed-capacity ring of recent process events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 mirror_path: Optional[str] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1: {capacity}")
        self.capacity = int(capacity)
        self._ring: List[Optional[tuple]] = [None] * self.capacity
        self._slots = itertools.count()
        self._mirror_path = None
        self._mirror_file = None
        if mirror_path:
            self.set_mirror(mirror_path)

    # -- recording (hot path) ------------------------------------------------

    def record(self, kind: str, detail: Optional[Dict[str, Any]] = None):
        """Record one event.  Never raises; the ring write itself is two
        C-atomic operations (slot claim + item store)."""
        try:
            entry = (
                time.monotonic(), time.time(), threading.get_ident(),
                kind, detail,
            )
            self._ring[next(self._slots) % self.capacity] = entry
            if self._mirror_file is not None:
                self._mirror_line(entry)
        except Exception:  # noqa: BLE001 - telemetry must not break callers
            get_registry().add("record_failures")

    # -- crash-safe mirror ---------------------------------------------------

    def set_mirror(self, path: Optional[str]) -> None:
        """Mirror every future event to ``path`` as JSON lines (flushed per
        event).  ``None`` turns mirroring off."""
        if self._mirror_file is not None:
            try:
                self._mirror_file.close()
            except OSError:
                get_registry().add("export_failures")
        self._mirror_path = path
        self._mirror_file = None
        if path:
            try:
                self._mirror_file = open(path, "a", buffering=1)
            except OSError:
                get_registry().add("export_failures")

    def _mirror_line(self, entry: tuple) -> None:
        try:
            self._mirror_file.write(json.dumps(_entry_json(entry)) + "\n")
        except (OSError, ValueError, TypeError):
            get_registry().add("export_failures")

    # -- reading -------------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        """Ring contents oldest-first (concurrent writers may still be
        landing; this is a best-effort snapshot, which is all forensics
        need)."""
        entries = [e for e in list(self._ring) if e is not None]
        entries.sort(key=lambda e: e[0])
        return [_entry_json(e) for e in entries]

    def __len__(self) -> int:
        return sum(1 for e in self._ring if e is not None)


def _entry_json(entry: tuple) -> Dict[str, Any]:
    mono, wall, tid, kind, detail = entry
    out = {
        "t_mono": round(mono, 6),
        "t_wall": round(wall, 6),
        "tid": tid,
        "kind": kind,
    }
    if detail:
        out["detail"] = detail
    return out


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()  # creation only; recording is lock-free


def get_flight_recorder() -> FlightRecorder:
    """The process-wide recorder, created on first use (capacity from
    ``DML_OBS_FLIGHT_CAPACITY``, mirror from ``DML_OBS_FLIGHT_MIRROR`` —
    the env path is how probe/bench children inherit crash-safe
    forensics without any protocol)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                try:
                    cap = int(os.environ.get(CAPACITY_ENV, DEFAULT_CAPACITY))
                except ValueError:
                    cap = DEFAULT_CAPACITY
                _recorder = FlightRecorder(
                    max(cap, 1), os.environ.get(MIRROR_ENV) or None
                )
    return _recorder


def record_event(kind: str, detail: Optional[Dict[str, Any]] = None) -> None:
    """Module-level convenience: record into the process recorder."""
    get_flight_recorder().record(kind, detail)


_dump_dir: Optional[str] = None
_dump_seq = itertools.count()


def set_dump_dir(path: Optional[str]) -> None:
    """Default destination for automatic dumps (drivers point this at the
    experiment root at startup)."""
    global _dump_dir
    _dump_dir = path


def dump_dir() -> Optional[str]:
    return _dump_dir or os.environ.get(DUMP_DIR_ENV) or None


def dump_flight_recorder(
    reason: str,
    directory: Optional[str] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Optional[str]:
    """Write the ring + per-thread open-span stacks + registry snapshot to
    a JSON file; returns the path, or None when no destination is
    configured or the write failed (counted, never raised).

    This is THE fail-slow forensics hook: watchdog expiries, STALLED
    transitions, lease expiry, breaker-open, SIGTERM handlers, and the
    bench probe all route here.
    """
    dest = directory or dump_dir()
    if not dest:
        return None
    reg = get_registry()
    safe_reason = "".join(
        c if c.isalnum() or c in "-_" else "_" for c in reason
    )[:80]
    path = os.path.join(
        dest,
        f"flightrec_{os.getpid()}_{next(_dump_seq)}_{safe_reason}.json",
    )
    try:
        # Chaos coverage for the telemetry plane itself: an injected
        # export fault must be absorbed exactly like a real disk error.
        from distributed_machine_learning_tpu import chaos

        plan = chaos.active_plan()
        if plan is not None:
            plan.on_trace_export(path)
        from distributed_machine_learning_tpu.obs import trace as trace_lib

        payload = {
            "reason": reason,
            "pid": os.getpid(),
            "dumped_at": time.time(),
            "events": get_flight_recorder().events(),
            "span_stacks": trace_lib.active_span_stacks(),
            "registry": reg.snapshot(),
        }
        if extra:
            payload["extra"] = extra
        os.makedirs(dest, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 - forensics must never fail the caller
        reg.add("export_failures")
        return None
    reg.add("flight_dumps")
    return path
