"""Trace export: per-process JSONL span files -> one Chrome-trace JSON.

Every traced process (driver, process-executor children, cluster workers)
appends completed spans to its own ``trace_<label>_<pid>.jsonl`` under the
experiment's ``trace/`` directory (``obs/trace.py``).  This module merges
them into a single ``trace.json`` in Chrome trace-event format — loadable
in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing`` — and
answers the question the MFU work keeps hitting: *where did the wall
clock go inside one trial* (``summarize_trace`` prints the per-phase
breakdown without leaving the terminal).

Wall-clock ``ts`` + monotonic ``dur`` (see ``obs/trace.py``) make the
per-process files mergeable on one timeline; the merge normalizes ``ts``
to the earliest event so viewers start at t=0.

Export failures never propagate (``obs.export_failures`` counts them) —
telemetry trouble must not fail a trial, a request, or a teardown; the
chaos plan's ``trace_export_error_rate`` exists to prove exactly that.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from distributed_machine_learning_tpu.obs.registry import get_registry

_EVENT_KEYS = ("name", "ph", "ts", "dur", "pid", "tid")


def _maybe_inject_export_fault(path: str) -> None:
    from distributed_machine_learning_tpu import chaos

    plan = chaos.active_plan()
    if plan is not None:
        plan.on_trace_export(path)


def read_trace_files(trace_dir: str) -> List[Dict[str, Any]]:
    """All span records under ``trace_dir`` (bad lines skipped, counted)."""
    records: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(trace_dir, "trace_*.jsonl"))):
        label = os.path.basename(path)[len("trace_"):-len(".jsonl")]
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        # A torn tail line from a killed process: the
                        # records before it are still good.
                        get_registry().add("torn_trace_lines")
                        continue
                    rec.setdefault("args", {})["proc"] = label
                    records.append(rec)
        except OSError:
            get_registry().add("export_failures")
    return records


def chrome_trace(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Records -> Chrome trace-event JSON object (ts normalized to 0)."""
    events = [r for r in records if all(k in r for k in _EVENT_KEYS)]
    t0 = min((r["ts"] for r in events), default=0.0)
    out_events: List[Dict[str, Any]] = []
    seen_procs: Dict[int, str] = {}
    for r in sorted(events, key=lambda r: r["ts"]):
        ev = dict(r)
        ev["ts"] = round(ev["ts"] - t0, 1)
        out_events.append(ev)
        label = (r.get("args") or {}).get("proc")
        if label and r["pid"] not in seen_procs:
            seen_procs[r["pid"]] = label
    # Metadata events name each process lane in the viewer.
    for pid, label in seen_procs.items():
        out_events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": label},
        })
    return {
        "traceEvents": out_events,
        "displayTimeUnit": "ms",
        "otherData": {"origin_ts_us": t0},
    }


def merge_trace_dir(trace_dir: str,
                    out_path: Optional[str] = None) -> Optional[str]:
    """Merge every per-process trace file under ``trace_dir`` into
    ``trace.json`` (or ``out_path``).  Returns the written path, or None
    on failure / nothing to merge (counted, never raised)."""
    try:
        records = read_trace_files(trace_dir)
        if not records:
            return None
        out = out_path or os.path.join(trace_dir, "trace.json")
        _maybe_inject_export_fault(out)
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(chrome_trace(records), f)
        os.replace(tmp, out)
    except Exception:  # noqa: BLE001 - teardown telemetry must not raise
        get_registry().add("export_failures")
        return None
    return out


def _load_events(source: str) -> List[Dict[str, Any]]:
    """Events from a merged trace.json, a trace dir, or an experiment dir
    (which holds ``trace/``)."""
    if os.path.isdir(source):
        sub = os.path.join(source, "trace")
        trace_dir = sub if os.path.isdir(sub) else source
        merged = os.path.join(trace_dir, "trace.json")
        if not os.path.exists(merged):
            return read_trace_files(trace_dir)
        source = merged
    with open(source) as f:
        data = json.load(f)
    return data["traceEvents"] if isinstance(data, dict) else data


def summarize_trace(
    source: str, trial: Optional[str] = None,
) -> Tuple[List[Dict[str, Any]], str]:
    """Per-phase wall-clock breakdown: group complete spans by name, sum
    durations, and render a table.  ``trial`` filters to spans whose
    ``args.trial_id`` matches — the "where did one trial's time go" view
    the MFU climb needs.

    Returns ``(rows, rendered_table)``; rows are sorted by total time.
    """
    events = [
        e for e in _load_events(source)
        if e.get("ph") == "X" and "dur" in e
    ]
    if trial is not None:
        # The trial's own spans plus every DESCENDANT (epochs, compiles,
        # checkpoint saves — across processes: parent ids ride the
        # frames), walked over the span-id -> parent-id edges.
        roots = {
            (e.get("args") or {}).get("span_id")
            for e in events
            if str((e.get("args") or {}).get("trial_id")) == str(trial)
        } - {None}
        parent_of = {
            (e.get("args") or {}).get("span_id"):
                (e.get("args") or {}).get("parent_id")
            for e in events
        }

        def in_trial(span_id) -> bool:
            seen = set()
            while span_id is not None and span_id not in seen:
                if span_id in roots:
                    return True
                seen.add(span_id)
                span_id = parent_of.get(span_id)
            return False

        events = [
            e for e in events
            if in_trial((e.get("args") or {}).get("span_id"))
        ]
    by_name: Dict[str, Dict[str, float]] = {}
    for e in events:
        row = by_name.setdefault(
            e["name"], {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
        )
        dur_ms = float(e["dur"]) / 1000.0
        row["count"] += 1
        row["total_ms"] += dur_ms
        row["max_ms"] = max(row["max_ms"], dur_ms)
    # Share is computed against the longest phase total: nested spans
    # double-count wall time by construction, so a percent-of-run would
    # overflow 100 and mislead — percent-of-longest ranks instead.
    top = max((r["total_ms"] for r in by_name.values()), default=0.0)
    rows = [
        {
            "phase": name,
            "count": int(r["count"]),
            "total_ms": round(r["total_ms"], 3),
            "mean_ms": round(r["total_ms"] / r["count"], 3),
            "max_ms": round(r["max_ms"], 3),
            "rel": round(r["total_ms"] / top, 4) if top else 0.0,
        }
        for name, r in by_name.items()
    ]
    rows.sort(key=lambda r: -r["total_ms"])
    header = (
        f"{'phase':<28} {'count':>6} {'total_ms':>12} "
        f"{'mean_ms':>10} {'max_ms':>10} {'rel':>6}"
    )
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append(
            f"{r['phase']:<28.28} {r['count']:>6} {r['total_ms']:>12.3f} "
            f"{r['mean_ms']:>10.3f} {r['max_ms']:>10.3f} {r['rel']:>6.2f}"
        )
    if not rows:
        lines.append("(no complete spans matched)")
    return rows, "\n".join(lines)
