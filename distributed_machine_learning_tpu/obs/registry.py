"""Unified metrics registry: ONE place every counter family reports into.

Before this module, six subsystems each invented their own telemetry —
``experiment_state.json`` counter blocks (``liveness``, ``compile``,
``checkpoint``, ``host_input``, ``pbt``, ``injected_faults``), the serve
``/metrics`` JSON, and per-driver TensorBoard writers — with no way to ask
"what does this PROCESS know right now" in one call.  The registry closes
that gap without breaking anything: the existing counter classes keep
their shapes (drivers still snapshot/delta them directly, so every
``experiment_state.json`` block and the serve ``/metrics`` JSON stay
byte-compatible) and additionally *register* here as a **family** — any
object (or zero-arg callable) whose ``snapshot()`` returns a flat
``{name: number}`` dict.

Two surfaces:

* :meth:`MetricsRegistry.snapshot` — ``{"counters": {...}, "families":
  {fam: {...}}}``, the whole process's telemetry in one dict (flight-
  recorder dumps embed it, ``/metrics`` serves it under ``"obs"``).
* :meth:`MetricsRegistry.scalar_snapshot` — the same flattened to
  ``{"fam/name": value}``, which is what rides the cluster head-node
  aggregation frame: workers attach it to their terminal frames and the
  head sums across workers, so cluster-wide counters appear in ONE place
  (``experiment_state.json["obs"]["cluster"]``).

Registry-native counters (``add``/``get``) hold the obs plane's own
accounting — ``export_failures``, ``flight_dumps``, ``spans_recorded`` —
the counters dmlint DML015 (``bare-counter-increment``) steers new code
toward instead of ad-hoc ``self.x += 1`` attributes.

Stdlib-only (no jax): usable from the linter, the serve plane, and probe
children alike.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple, Union

from distributed_machine_learning_tpu.analysis.locks import named_lock

FamilyProvider = Union[Callable[[], Dict[str, Any]], Any]


class MetricsRegistry:
    """Process-wide registry of counter families + native counters.

    Thread-safe.  ``snapshot`` copies the provider table under the lock
    and calls each family's ``snapshot()`` OUTSIDE it, so the registry
    lock never nests inside (or around) a family's own lock — no
    lock-order edges with the families it aggregates.
    """

    def __init__(self):
        self._lock = named_lock("obs.registry")
        self._families: Dict[str, FamilyProvider] = {}
        self._counters: Dict[str, float] = {}

    # -- families ------------------------------------------------------------

    def register_family(self, name: str, provider: FamilyProvider) -> None:
        """(Re)register ``provider`` under ``name``.

        ``provider`` is either a zero-arg callable returning a flat dict
        or an object with a ``snapshot()`` method (the existing counter
        classes all qualify).  Last registration wins — per-run objects
        (watchdogs, fault plans) re-register freely.
        """
        with self._lock:
            self._families[name] = provider

    def unregister_family(self, name: str, provider: FamilyProvider = None):
        """Remove ``name``; with ``provider`` given, only if it is still
        the registered one (a newer run's family is never evicted by an
        older run's teardown)."""
        with self._lock:
            if provider is None or self._families.get(name) is provider:
                self._families.pop(name, None)

    def families(self) -> List[str]:
        with self._lock:
            return sorted(self._families)

    # -- native counters -----------------------------------------------------

    def add(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    # -- views ---------------------------------------------------------------

    def _family_snapshots(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            providers = dict(self._families)
        out: Dict[str, Dict[str, Any]] = {}
        for name, provider in providers.items():
            try:
                snap = provider() if callable(provider) else provider.snapshot()
                if isinstance(snap, dict):
                    out[name] = snap
            except Exception:  # noqa: BLE001 - a broken family must not
                # take the whole plane down; the failure is itself counted.
                self.add("family_errors")
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Everything this process's registry knows, structured."""
        families = self._family_snapshots()
        with self._lock:
            counters = dict(self._counters)
        return {"counters": counters, "families": families}

    def scalar_snapshot(self) -> Dict[str, float]:
        """Flat ``{"family/name": value}`` view (numbers only) — the shape
        the cluster aggregation frame and TensorBoard scalars consume."""
        snap = self.snapshot()
        out: Dict[str, float] = {
            f"obs/{k}": v for k, v in snap["counters"].items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        for fam, block in snap["families"].items():
            for k, v in block.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[f"{fam}/{k}"] = v
        return out

    def delta_since(self, baseline: Dict[str, float]) -> Dict[str, float]:
        """Native-counter delta vs a prior ``counters_snapshot()`` — how a
        driver scopes process-wide obs counters to one run."""
        with self._lock:
            snap = dict(self._counters)
        keys = set(snap) | set(baseline)
        return {
            k: round(snap.get(k, 0) - baseline.get(k, 0), 4) for k in keys
        }

    def counters_snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def reset(self) -> None:
        """Test hook: zero native counters (families stay registered)."""
        with self._lock:
            self._counters = {}


def aggregate_scalars(
    per_source: Dict[str, Dict[str, float]],
) -> Dict[str, float]:
    """Sum flat scalar snapshots across sources (the head-node view:
    one dict per worker in, one cluster-wide dict out)."""
    out: Dict[str, float] = {}
    for snap in per_source.values():
        for k, v in (snap or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = round(out.get(k, 0) + v, 4)
    return out


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry (one per process, same discipline as
    ``ckpt.metrics.get_metrics`` / ``compilecache.get_counters``)."""
    return _registry
