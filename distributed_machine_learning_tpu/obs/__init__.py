"""obs/ — the one observability plane (ISSUE 13 tentpole).

Three legs, one import:

* **Structured tracing** (``obs/trace.py``): ``obs.span(name, attrs)``
  context managers on monotonic clocks with thread-local span stacks and
  trace context that rides every existing frame protocol — driver ->
  process child (init frame), head -> cluster worker (dispatch frame),
  serve request -> replica -> batcher -> engine (pending entries).
  Per-process JSONL span files merge into Chrome-trace/Perfetto JSON
  (``obs/export.py``, ``dml-tpu trace``).
* **Always-on flight recorder** (``obs/flight.py``): a bounded,
  preallocated, lock-free ring of recent events per process, dumped
  automatically on watchdog expiry, STALLED transitions, lease expiry,
  breaker-open, SIGTERM, and bench probe wedges.
* **Unified MetricsRegistry** (``obs/registry.py``): the counter families
  that used to live in six private registries all register here; the
  cluster head aggregates worker snapshots into one place.

Everything is stdlib-only and safe to import anywhere (no jax at import
time); the disabled tracing path is a single None-check.

See docs/observability.md for the span taxonomy, flight-recorder
triggers, and the counter -> registry migration map.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, Dict, Optional, Tuple

from distributed_machine_learning_tpu.obs.flight import (
    FlightRecorder,
    dump_dir,
    dump_flight_recorder,
    get_flight_recorder,
    record_event,
    set_dump_dir,
)
from distributed_machine_learning_tpu.obs.registry import (
    MetricsRegistry,
    aggregate_scalars,
    get_registry,
)
from distributed_machine_learning_tpu.obs.trace import (
    Span,
    Tracer,
    active_span_stacks,
    add_complete,
    current_context,
    detached_span,
    disabled_path_overhead,
    get_tracer,
    install_tracer,
    set_process_context,
    span,
    tracing_enabled,
)
from distributed_machine_learning_tpu.obs.export import (
    chrome_trace,
    merge_trace_dir,
    read_trace_files,
    summarize_trace,
)

event = record_event  # ``obs.event("kind", {...})``: one flight-ring write

__all__ = [
    "FlightRecorder", "MetricsRegistry", "Span", "Tracer",
    "active_span_stacks", "add_complete", "aggregate_scalars",
    "chrome_trace", "configure", "configure_from_frame", "current_context",
    "detached_span", "disabled_path_overhead", "dump_dir",
    "dump_flight_recorder", "event",
    "flush", "get_flight_recorder", "get_registry", "get_tracer",
    "install_tracer", "maybe_profile_trial", "merge_trace_dir",
    "read_trace_files", "record_event", "set_dump_dir",
    "set_process_context", "shutdown", "span", "summarize_trace",
    "trace_context_frame", "tracing_enabled",
]


def configure(
    trace_dir: Optional[str] = None,
    label: str = "proc",
    trace_id: Optional[str] = None,
    parent_span_id: Optional[str] = None,
    dump_dir: Optional[str] = None,
    flight_mirror: Optional[str] = None,
) -> None:
    """Install the process's telemetry plane.

    ``trace_dir`` enables tracing (spans stream to a per-process JSONL
    file there); None leaves tracing in its current state.  ``dump_dir``
    sets where automatic flight-recorder dumps land.  ``flight_mirror``
    turns on the crash-safe per-event mirror (probe children).
    """
    if trace_dir is not None:
        install_tracer(Tracer(
            trace_dir, label=label, trace_id=trace_id,
            parent_span_id=parent_span_id,
        ))
    elif trace_id is not None or parent_span_id is not None:
        set_process_context(trace_id, parent_span_id)
    if dump_dir is not None:
        set_dump_dir(dump_dir)
    if flight_mirror is not None:
        get_flight_recorder().set_mirror(flight_mirror)


def flush() -> None:
    """Flush the tracer's file sink (if any) — call at report/teardown
    boundaries so a killed process loses at most the in-flight span."""
    t = get_tracer()
    if t is not None:
        t.flush()


def shutdown() -> None:
    """Flush + close + uninstall the tracer (driver teardown after the
    merge).  The flight recorder and registry stay — they are process
    lifetime by design."""
    install_tracer(None)


def trace_context_frame(
    parent: Optional[Tuple[str, str]] = None,
) -> Optional[Dict[str, Any]]:
    """The dict a driver attaches to a dispatch/init frame so the far
    process can join this trace: ``{"trace_dir", "trace_id",
    "parent_span_id", "dump_dir"}``.  ``parent`` overrides the parent
    span (the driver's per-trial dispatch span).  None when nothing is
    configured — frames stay exactly as they were before obs existed.
    """
    t = get_tracer()
    dumps = dump_dir()
    if t is None and dumps is None:
        return None
    ctx: Dict[str, Any] = {}
    if dumps:
        ctx["dump_dir"] = dumps
    if t is not None:
        if parent is not None:
            trace_id, parent_id = parent
        else:
            trace_id, parent_id = t.trace_id, t.default_parent
        ctx.update({
            "trace_dir": os.path.dirname(t.path) if t.path else None,
            "trace_id": trace_id,
            "parent_span_id": parent_id,
        })
    return ctx


def configure_from_frame(ctx: Optional[Dict[str, Any]],
                         label: str = "child") -> None:
    """Child-process side of :func:`trace_context_frame`."""
    if not ctx:
        return
    configure(
        trace_dir=ctx.get("trace_dir"),
        label=label,
        trace_id=ctx.get("trace_id"),
        parent_span_id=ctx.get("parent_span_id"),
        dump_dir=ctx.get("dump_dir"),
    )


# -- opt-in jax profiler capture ----------------------------------------------

_profile_lock = threading.Lock()
_profile_active = [False]


@contextlib.contextmanager
def maybe_profile_trial(profile_dir: Optional[str], trial_id: str):
    """Programmatic ``jax.profiler`` capture around one trial
    (``tune.run(trace_profile_trials=N)``): traces into
    ``profile_dir/<trial_id>/``.  The jax trace is process-global, so
    only one capture runs at a time — a second concurrent trial simply
    skips (counted), it never fails.  Any profiler error is absorbed:
    profiling is forensics, not a dependency."""
    if not profile_dir:
        yield
        return
    with _profile_lock:
        if _profile_active[0]:
            get_registry().add("profile_skips")
            claimed = False
        else:
            _profile_active[0] = claimed = True
    if not claimed:
        yield
        return
    started = False
    try:
        try:
            import jax

            target = os.path.join(profile_dir, str(trial_id))
            os.makedirs(target, exist_ok=True)
            jax.profiler.start_trace(target)
            started = True
            get_registry().add("profile_captures")
        except Exception:  # noqa: BLE001 - profiling must not fail trials
            get_registry().add("profile_errors")
        yield
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                get_registry().add("profile_errors")
        with _profile_lock:
            _profile_active[0] = False
