"""Structured tracing: monotonic spans, thread-local stacks, and trace
context that crosses every process boundary in the stack.

``obs.span(name, attrs)`` is the one instrumentation primitive.  Enabled
(a :class:`Tracer` installed via :func:`configure`), it opens a span on
the calling thread's stack; on exit the completed span is appended to the
process's trace buffer AND to a per-process JSON-lines file under
``trace_dir`` (crash-tolerant: every landed span survives the process).
The driver merges the per-process files into one Chrome-trace JSON at
experiment end (``obs/export.py``; ``dml-tpu trace export``).

Disabled (the default), ``span`` costs ONE global read + None-check and
returns a singleton no-op context manager — no allocation, a few hundred
ns, cheap enough to leave at every epoch/request/chunk boundary
(tests/test_obs_plane.py pins this with an allocation + latency guard).

Cross-boundary context: a span's identity is ``(trace_id, span_id)``.
The driver threads it through the existing frame protocols — the process
executor's init frame, the cluster dispatch frame, the serve batcher's
pending entries — and the far side either installs it as the process
default (:func:`set_process_context`: new root spans adopt it as parent)
or passes it explicitly (``span(..., parent=ctx)``).  Wall-clock span
timestamps + monotonic durations make per-process files mergeable on one
timeline while keeping durations NTP-step-proof.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.obs.registry import get_registry


class _NoopSpan:
    """Singleton returned on the disabled path: zero state, zero writes."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, key: str, value) -> "_NoopSpan":
        return self

    def end(self) -> None:
        return None

    @property
    def context(self) -> None:
        return None


_NOOP = _NoopSpan()


class Span:
    """One live span.  Use as a context manager or call :meth:`end`."""

    __slots__ = (
        "name", "attrs", "trace_id", "span_id", "parent_id",
        "_t0_mono", "_t0_wall", "_tracer", "_stacked", "_ended",
    )

    def __init__(self, tracer: "Tracer", name: str,
                 attrs: Optional[Dict[str, Any]],
                 trace_id: str, span_id: str, parent_id: Optional[str],
                 stacked: bool):
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self._tracer = tracer
        self._stacked = stacked
        self._ended = False
        self._t0_mono = time.monotonic()
        self._t0_wall = time.time()

    def set(self, key: str, value) -> "Span":
        self.attrs[key] = value
        return self

    @property
    def context(self) -> Tuple[str, str]:
        """``(trace_id, span_id)`` — hand this across a queue/frame and
        open the far side's span with ``parent=context``."""
        return (self.trace_id, self.span_id)

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self._tracer._finish(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.end()
        return False


class Tracer:
    """Per-process span collector with an optional JSONL file sink."""

    def __init__(self, trace_dir: Optional[str] = None, label: str = "proc",
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None,
                 buffer_limit: int = 100_000):
        self.label = label
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.default_parent = parent_span_id
        self._ids = itertools.count(1)
        self._tls = threading.local()
        # tid -> that thread's live stack: lets a dump thread report every
        # thread's CURRENT open spans (the "hang site" in a stall dump).
        self._stacks: Dict[int, List[Span]] = {}
        self._lock = named_lock("obs.tracer")
        self._records: List[Dict[str, Any]] = []
        self._buffer_limit = int(buffer_limit)
        self._dropped = 0
        self._file = None
        self.path = None
        if trace_dir:
            try:
                os.makedirs(trace_dir, exist_ok=True)
                self.path = os.path.join(
                    trace_dir, f"trace_{label}_{os.getpid()}.jsonl"
                )
                self._file = open(self.path, "a", buffering=1)
            except OSError:
                get_registry().add("export_failures")
                self.path = None

    # -- span lifecycle ------------------------------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
            with self._lock:
                self._stacks[threading.get_ident()] = stack
        return stack

    def start(self, name: str, attrs: Optional[Dict[str, Any]] = None,
              parent: Optional[Tuple[str, str]] = None) -> Span:
        stack = self._stack()
        if parent is not None:
            trace_id, parent_id = parent
        elif stack:
            trace_id, parent_id = stack[-1].trace_id, stack[-1].span_id
        else:
            trace_id, parent_id = self.trace_id, self.default_parent
        span = Span(self, name, attrs, trace_id, self._new_id(), parent_id,
                    stacked=True)
        stack.append(span)
        return span

    def start_detached(self, name: str,
                       attrs: Optional[Dict[str, Any]] = None,
                       parent: Optional[Tuple[str, str]] = None) -> Span:
        """A span that does NOT join the caller's thread stack — for
        driver-side activities (a trial's dispatch window) that begin and
        end on different event-loop iterations."""
        if parent is not None:
            trace_id, parent_id = parent
        else:
            trace_id, parent_id = self.trace_id, self.default_parent
        return Span(self, name, attrs, trace_id, self._new_id(), parent_id,
                    stacked=False)

    def _new_id(self) -> str:
        return f"{os.getpid():x}.{next(self._ids):x}"

    def _finish(self, span: Span) -> None:
        if span._stacked:
            stack = self._stack()
            # Tolerate out-of-order ends (a leaked child span): remove by
            # identity wherever it sits.
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is span:
                    del stack[i]
                    break
        self.add_record({
            "name": span.name,
            "ph": "X",
            "ts": round(span._t0_wall * 1e6, 1),
            "dur": round((time.monotonic() - span._t0_mono) * 1e6, 1),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": {
                **span.attrs,
                "trace_id": span.trace_id,
                "span_id": span.span_id,
                **({"parent_id": span.parent_id} if span.parent_id else {}),
            },
        })

    def add_complete(self, name: str, dur_s: float,
                     attrs: Optional[Dict[str, Any]] = None,
                     end_wall: Optional[float] = None) -> None:
        """Record an already-measured interval (e.g. a jax compile event,
        whose duration arrives via a monitoring listener)."""
        end = end_wall if end_wall is not None else time.time()
        self.add_record({
            "name": name,
            "ph": "X",
            "ts": round((end - dur_s) * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": {**(attrs or {}), "trace_id": self.trace_id},
        })

    def add_record(self, record: Dict[str, Any]) -> None:
        get_registry().add("spans_recorded")
        with self._lock:
            if len(self._records) < self._buffer_limit:
                self._records.append(record)
            else:
                self._dropped += 1
            f = self._file
        if f is not None:
            try:
                f.write(json.dumps(record, default=str) + "\n")
            except (OSError, ValueError):
                get_registry().add("export_failures")

    # -- queries / teardown --------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def span_stacks(self) -> Dict[str, List[Dict[str, Any]]]:
        """Every thread's currently-open spans, outermost first — the
        flight recorder embeds this in dumps so a stall names its site."""
        with self._lock:
            stacks = {tid: list(stack) for tid, stack in self._stacks.items()}
        now = time.monotonic()
        return {
            str(tid): [
                {
                    "name": s.name,
                    "age_s": round(now - s._t0_mono, 3),
                    "attrs": dict(s.attrs),
                    "span_id": s.span_id,
                    "trace_id": s.trace_id,
                }
                for s in stack
            ]
            for tid, stack in stacks.items()
            if stack
        }

    def flush(self) -> None:
        with self._lock:
            f = self._file
        if f is not None:
            try:
                f.flush()
            except OSError:
                get_registry().add("export_failures")

    def close(self) -> None:
        with self._lock:
            f, self._file = self._file, None
        if f is not None:
            try:
                f.close()
            except OSError:
                get_registry().add("export_failures")


# -- process-wide installation -------------------------------------------------

_tracer: Optional[Tracer] = None


def install_tracer(tracer: Optional[Tracer]) -> None:
    global _tracer
    old, _tracer = _tracer, tracer
    if old is not None and old is not tracer:
        old.close()


def get_tracer() -> Optional[Tracer]:
    return _tracer


def tracing_enabled() -> bool:
    return _tracer is not None


def span(name: str, attrs: Optional[Dict[str, Any]] = None,
         parent: Optional[Tuple[str, str]] = None):
    """THE instrumentation call.  Disabled: one global read, a None-check,
    and a shared no-op object back — nothing allocated (the perf guard
    in tests/test_obs_plane.py holds this to a few hundred ns/call)."""
    t = _tracer
    if t is None:
        return _NOOP
    return t.start(name, attrs, parent)


def detached_span(name: str, attrs: Optional[Dict[str, Any]] = None,
                  parent: Optional[Tuple[str, str]] = None):
    t = _tracer
    if t is None:
        return _NOOP
    return t.start_detached(name, attrs, parent)


def add_complete(name: str, dur_s: float,
                 attrs: Optional[Dict[str, Any]] = None) -> None:
    t = _tracer
    if t is not None:
        t.add_complete(name, dur_s, attrs)


def current_context() -> Optional[Tuple[str, str]]:
    """The calling thread's innermost span context (None when disabled or
    no span is open) — attach it to queued work so the far side's spans
    parent correctly."""
    t = _tracer
    if t is None:
        return None
    stack = getattr(t._tls, "stack", None)
    if stack:
        return stack[-1].context
    if t.default_parent:
        return (t.trace_id, t.default_parent)
    return None


def set_process_context(trace_id: Optional[str],
                        parent_span_id: Optional[str]) -> None:
    """Adopt a remote parent as this process's default span parent (child
    processes / cluster workers call this with the dispatch frame's
    context)."""
    t = _tracer
    if t is not None:
        if trace_id:
            t.trace_id = trace_id
        t.default_parent = parent_span_id


def active_span_stacks() -> Dict[str, List[Dict[str, Any]]]:
    t = _tracer
    return t.span_stacks() if t is not None else {}


def disabled_path_overhead(iters: int = 100_000) -> Dict[str, float]:
    """Measure the tracing-DISABLED ``span()`` path: ns per call and net
    allocated blocks across ``iters`` spans (must be ~0 — the disabled
    path returns a shared singleton and allocates nothing).

    This is the contract that makes always-on instrumentation acceptable
    in epoch/request/chunk hot paths.  Shared by the tier-1 perf guard
    (tests/test_obs_plane.py) and the CI gate (scripts/lint_gate.py with
    ``DML_OBS_PERF_GUARD=1``) so a regression gates the diff.  Any
    installed tracer is stashed and restored around the measurement.
    """
    import sys
    import time as _time

    global _tracer
    stashed, _tracer = _tracer, None
    try:
        for _ in range(1000):  # warm the bytecode/caches
            with span("warm"):
                pass
        blocks0 = sys.getallocatedblocks()
        t0 = _time.perf_counter()
        for _ in range(iters):
            with span("guard"):
                pass
        elapsed = _time.perf_counter() - t0
        net_blocks = sys.getallocatedblocks() - blocks0
    finally:
        _tracer = stashed
    return {
        "ns_per_span": round(elapsed / iters * 1e9, 1),
        "net_blocks": net_blocks,
        "iters": iters,
    }
