"""Shared trial-lifecycle core for the single-host and cluster drivers.

``tune.run`` (runner.py, thread executor on local devices) and
``cluster.run_distributed`` (cluster.py, remote host supervisors) differ only
in *where* trials execute; the lifecycle — sampling configs from the
searcher, stamping and persisting per-epoch results, routing them through the
scheduler, REQUEUE bookkeeping (PBT), retry-with-restore on failure — is one
state machine. This module owns it, so scheduler-protocol changes land in
exactly one place. (The reference delegated all of this to Ray Tune's trial
runner; SURVEY.md §1 L4.)
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from distributed_machine_learning_tpu.tune.schedulers.base import (
    CONTINUE,
    REQUEUE,
    STOP,
)
from distributed_machine_learning_tpu.tune.stoppers import stop_hit
from distributed_machine_learning_tpu.tune.trial import Trial, TrialStatus


def _summarize(value):
    """Collections collapse to their sizes — forensic shape, not payload
    (a BayesOpt X matrix in experiment_state.json would dwarf the trials)."""
    if isinstance(value, dict):
        return {str(k): _summarize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return len(value)
    return value


def scheduler_debug_block(searcher, scheduler) -> Dict[str, Any]:
    """The ``experiment_state.json["scheduler"]`` forensics block both
    drivers persist at report boundaries (throttled) and at completion
    boundaries: who is deciding, and the summarized shape of their state —
    the first thing a postmortem of a bad stop/exploit wants."""
    block: Dict[str, Any] = {
        "scheduler_type": type(scheduler).__name__,
        "searcher_type": type(searcher).__name__,
    }
    debug = getattr(scheduler, "debug_state", None)
    if callable(debug):
        try:
            block["scheduler_state"] = debug()
        except Exception:  # noqa: BLE001 - forensics never kill a run
            pass
    try:
        block["searcher_state"] = _summarize(searcher.save_state())
    except Exception:  # noqa: BLE001
        pass
    return block


class TrialLifecycle:
    """Single-threaded trial state machine shared by both drivers.

    The executor layer (threads or remote workers) calls in with events;
    this class mutates trial/searcher/scheduler/store state and answers
    with decisions. It never blocks and never touches sockets or devices.
    """

    def __init__(
        self,
        *,
        searcher,
        scheduler,
        store,
        metric: str,
        mode: str,
        num_samples: int,
        max_failures: int = 0,
        stop_rules: Optional[Dict[str, float]] = None,
        time_budget_s: Optional[float] = None,
        keep_checkpoints_num: int = 0,
        time_limit_per_trial_s: Optional[float] = None,
        log: Callable[[str], None] = lambda msg: None,
        config_overlay: Optional[Dict[str, Any]] = None,
        journal=None,
    ):
        self.searcher = searcher
        self.scheduler = scheduler
        self.store = store
        # Write-ahead log (tune/journal.ExperimentJournal, or None): every
        # scheduling decision is journaled with a post-decision
        # searcher/scheduler snapshot BEFORE its externally visible effect,
        # so a killed head resumes to bit-identical decision state.
        self.journal = journal
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.max_failures = max_failures
        self.stop_rules = stop_rules or {}
        self.time_budget_s = time_budget_s
        self.keep_checkpoints_num = keep_checkpoints_num
        self.time_limit_per_trial_s = time_limit_per_trial_s
        self.log = log
        # Driver-level config defaults under every sampled config (e.g.
        # tune.run(mesh_shape=...) stamping the sweep-wide mesh shape);
        # a key the search space samples always wins over the overlay.
        self.config_overlay = dict(config_overlay or {})

        self.trials: List[Trial] = []
        self.by_id: Dict[str, Trial] = {}
        self.pending: List[Trial] = []
        self.next_index = 0
        self.searcher_exhausted = False
        self.start_time = time.time()
        # Exactly-once epoch accounting after a journal-based resume:
        # trial_id -> journaled report watermark.  A requeued trial
        # restored from a checkpoint BELOW its watermark re-reports the
        # gap; those re-reports are suppressed (counted, never re-persisted
        # or re-observed) until the watermark is reached.
        self._suppress: Dict[str, int] = {}
        self.duplicate_reports_suppressed = 0

    # -- journal -----------------------------------------------------------

    def _snapshot(self) -> Dict[str, Any]:
        """The decision-state snapshot a journal record carries: restore it
        and the searcher/scheduler make bit-identical decisions from here."""
        return {
            "searcher": self.searcher.save_state(),
            "scheduler": self.scheduler.save_state(),
            "next_index": self.next_index,
        }

    # -- creation ----------------------------------------------------------

    def budget_exceeded(self) -> bool:
        return (
            self.time_budget_s is not None
            and time.time() - self.start_time > self.time_budget_s
        )

    def exhausted(self) -> bool:
        """No further trials will ever be created."""
        return (
            self.searcher_exhausted
            or self.next_index >= self.num_samples
            or self.budget_exceeded()
        )

    def create_trial(self, **trial_kwargs) -> Optional[Trial]:
        """Sample the next config; returns the new PENDING trial or None."""
        if self.exhausted():
            return None
        config = self.searcher.suggest(self.next_index)
        if config is None:
            self.searcher_exhausted = True
            return None
        if self.config_overlay:
            config = {**self.config_overlay, **config}
        trial = Trial(
            trial_id=f"trial_{self.next_index:05d}", config=config, **trial_kwargs
        )
        self.next_index += 1
        self.trials.append(trial)
        self.by_id[trial.trial_id] = trial
        self.pending.append(trial)
        self.scheduler.on_trial_add(trial)
        if self.journal is not None:
            # WAL: the create decision (searcher suggestion consumed, trial
            # registered with the scheduler) is durable before its first
            # external effect (params.json) — a crash here resumes with the
            # trial recreated from the journaled config.
            self.journal.record_create(
                trial.trial_id, dict(config), self._snapshot()
            )
        self.store.write_params(trial)
        return trial

    def restore_experiment(self, resources=None) -> Dict[str, int]:
        """Resume an interrupted experiment from its directory (Ray's
        ``tune.run(resume=True)`` semantics, which the reference relied on
        implicitly by re-running its driver against the same ``local_dir``).

        For every persisted trial: rebuild the Trial from params.json +
        result.jsonl, replay its metric stream through the scheduler and
        searcher (rung tables and model-based search see the full history;
        nothing is re-persisted), then either keep it finished
        (TERMINATED/ERROR) or requeue it from its newest checkpoint
        (PENDING/RUNNING/PAUSED at the interruption). Sampling continues
        afterwards until ``num_samples``.
        """
        from distributed_machine_learning_tpu.tune import checkpoint as ckpt_lib
        from distributed_machine_learning_tpu.tune.experiment import (
            iter_trial_records,
        )

        counts = {"finished": 0, "requeued": 0}
        for entry, config, records, meta in iter_trial_records(self.store.root):
            kwargs = {"resources": resources} if resources is not None else {}
            trial = Trial(trial_id=entry, config=config, **kwargs)
            self.trials.append(trial)
            self.by_id[entry] = trial
            try:
                self.next_index = max(
                    self.next_index, int(entry.rsplit("_", 1)[-1]) + 1
                )
            except ValueError:
                self.next_index = max(self.next_index, len(self.trials))
            self.scheduler.on_trial_add(trial)

            # A trial ABSENT from the state file was mid-flight when the
            # driver died (state snapshots are written on every completion,
            # so finished trials are always present): treat as interrupted,
            # never as finished — worst case a finished trial whose final
            # snapshot raced the crash re-runs from its last checkpoint.
            status = meta.get("status", "PENDING") if meta else "PENDING"
            finished = status in ("TERMINATED", "ERROR")
            # Start-of-run cleanup (safe here: no writer is live yet): a
            # sharded save the dead driver left half-written is deleted, so
            # find_latest below only ever names restorable generations.
            try:
                ckpt_lib.cleanup_uncommitted(
                    self.store.checkpoint_dir(trial), log=self.log
                )
            except Exception as exc:  # noqa: BLE001 - cleanup is best-effort
                self.log(f"uncommitted-checkpoint cleanup failed: {exc!r}")
            ck_path, ck_it = ckpt_lib.find_latest_checkpoint(
                self.store.checkpoint_dir(trial)
            )
            if not finished:
                # The re-run re-reports everything after the restore point;
                # drop the replayed tail past the checkpoint so the result
                # stream (and searcher observations) hold each epoch once —
                # on disk too, or the orphan tail would duplicate there.
                kept = [
                    r for r in records
                    if int(r.get("training_iteration", 0)) <= ck_it
                ]
                if len(kept) < len(records):
                    import json
                    import os

                    path = os.path.join(
                        self.store.trial_dir(trial), "result.jsonl"
                    )
                    with open(path, "w") as f:
                        for r in kept:
                            f.write(json.dumps(r) + "\n")
                records = kept

            # Replay: config snapshot guards against schedulers that mutate
            # on REQUEUE decisions during replay (PBT exploit) — replay must
            # only rebuild observer state, not re-run decisions.
            config_snapshot = dict(trial.config)
            for rec in records:
                trial.results.append(rec)
                trial.reports_since_restart += 1
                self.scheduler.on_trial_result(trial, rec)
                self.searcher.on_trial_result(
                    entry, config_snapshot, rec, self.metric, self.mode
                )
                if self.stop_rules is not None and callable(self.stop_rules):
                    # Warm STATEFUL stoppers (plateau windows/counters) with
                    # the replayed history; the returned decision is ignored
                    # — replay rebuilds observer state, it never re-decides.
                    stop_hit(self.stop_rules, trial.trial_id, rec)
            trial.config = config_snapshot
            # Clear anything replayed scheduler decisions left behind.
            trial._requeue_on_complete = False
            trial.restore_path = None
            trial.restore_base = 0
            trial.reports_since_restart = len(trial.results)
            if ck_path:
                trial.latest_checkpoint = ck_path
                trial.latest_checkpoint_iteration = ck_it

            if finished:
                trial.error = (meta or {}).get("error")
                self.finish(trial, TrialStatus(status))
                if status == "ERROR":
                    self.scheduler.on_trial_error(trial)
                counts["finished"] += 1
            else:
                # Interrupted mid-flight: rewind to the newest checkpoint
                # (training_iteration = restore_base once requeued).
                if ck_path:
                    trial.restore_path = ck_path
                    trial.restore_base = ck_it
                self.requeue(trial)
                counts["requeued"] += 1
        # Searchers with suggest-side state (GridSearch's cursor) advance
        # past the prefix of the space the prior run already proposed.
        self.searcher.fast_forward(self.next_index)
        return counts

    def restore_from_journal(self, replay, resources=None) -> Dict[str, int]:
        """Resume from the write-ahead log (``resume="auto"``): restore the
        journaled searcher/scheduler snapshot instead of replaying metric
        streams through their hooks, so the restored decision state is
        BIT-IDENTICAL to the moment of the last journaled decision — not a
        reconstruction of it.

        ``replay`` is a :class:`tune.journal.ReplayState`.  Ordering is
        load-bearing: (1) every live trial is rebuilt and registered via
        ``on_trial_add`` (PBT's live-ref table, ASHA's rung defaults);
        (2) THEN ``restore_state`` overwrites the defaults with the
        journaled snapshot; (3) trials are disposed — journaled-terminal
        trials get their status set directly (completion hooks already ran
        and are inside the snapshot), a trial whose watermark decision was
        "stop" is finished NOW (the decision was journaled but the crash
        ate its effect), everything else requeues from its newest valid
        checkpoint at-or-below the journaled report watermark, with
        re-reports below the watermark suppressed (exactly-once epoch
        accounting — see :meth:`process_result`).
        """
        from distributed_machine_learning_tpu.tune import checkpoint as ckpt_lib
        from distributed_machine_learning_tpu.tune.experiment import (
            iter_trial_records,
        )

        counts = {"finished": 0, "requeued": 0, "suppress_windows": 0}
        kwargs = {"resources": resources} if resources is not None else {}
        on_disk: Dict[str, Any] = {}
        for entry, config, records, _meta in iter_trial_records(
            self.store.root
        ):
            on_disk[entry] = (config, records)
        # Union: a journaled create whose params.json never landed (crash
        # inside the create→write_params window) is recreated from the
        # journaled config.
        trial_ids = sorted(set(on_disk) | set(replay.trials))
        pending_disposal = []
        for entry in trial_ids:
            jt = replay.trials.get(entry)
            if jt is not None and jt["config"] is None and entry not in on_disk:
                continue  # journal mentions it but holds no config (torn)
            config, records = on_disk.get(entry) or (
                dict(jt["config"]), []
            )
            trial = Trial(trial_id=entry, config=config, **kwargs)
            self.trials.append(trial)
            self.by_id[entry] = trial
            try:
                self.next_index = max(
                    self.next_index, int(entry.rsplit("_", 1)[-1]) + 1
                )
            except ValueError:
                self.next_index = max(self.next_index, len(self.trials))
            self.scheduler.on_trial_add(trial)
            if entry not in on_disk:
                self.store.write_params(trial)  # re-run the eaten effect

            watermark = int(jt["reported_through"]) if jt else 0
            terminal = jt["terminal"] if jt else None
            # Disk results past the journaled watermark are evidence of
            # work whose report never became a decision (crash between
            # append_result and the journal append): truncate, so the
            # re-reported epoch lands exactly once on disk too.
            if terminal is None:
                kept = [
                    r for r in records
                    if int(r.get("training_iteration", 0)) <= watermark
                ]
                if len(kept) < len(records):
                    import json
                    import os

                    path = os.path.join(
                        self.store.trial_dir(trial), "result.jsonl"
                    )
                    with open(path, "w") as f:
                        for r in kept:
                            f.write(json.dumps(r) + "\n")
                records = kept
            for rec in records:
                trial.results.append(rec)
                if self.stop_rules is not None and callable(self.stop_rules):
                    # Warm STATEFUL stoppers only; scheduler/searcher state
                    # comes from the snapshot, not from replaying hooks.
                    stop_hit(self.stop_rules, trial.trial_id, rec)
            trial.reports_since_restart = len(trial.results)
            pending_disposal.append((trial, jt, watermark))

        # The journaled snapshot is authoritative: it overwrites the
        # defaults on_trial_add just installed (ASHA rung cursors, PBT
        # history) and the searcher's model/cursor state.  next_index from
        # the snapshot covers creates whose params.json landed but whose
        # ids don't parse.
        snap = replay.snapshot
        if snap:
            self.searcher.restore_state(snap.get("searcher") or {})
            self.scheduler.restore_state(snap.get("scheduler") or {})
            self.next_index = max(
                self.next_index, int(snap.get("next_index", 0))
            )
        else:
            self.searcher.fast_forward(self.next_index)

        for trial, jt, watermark in pending_disposal:
            terminal = jt["terminal"] if jt else None
            if terminal is not None:
                # Completion hooks ran before the complete record was
                # journaled and their mutations are inside the snapshot:
                # set the status directly, never re-run finish().
                trial.status = TrialStatus(terminal.get("status", "TERMINATED"))
                trial.error = terminal.get("error")
                trial.finished_at = time.time()
                counts["finished"] += 1
                continue
            decision = jt["decision_at_watermark"] if jt else None
            if decision == "stop":
                # The stop decision is durable; the crash ate its effect.
                # finish() now runs the completion hooks exactly once (the
                # control run would have run them at this point too) and
                # journals the complete record.
                self.finish(trial, TrialStatus.TERMINATED)
                counts["finished"] += 1
                continue
            ck_dir = self.store.checkpoint_dir(trial)
            try:
                ckpt_lib.cleanup_uncommitted(ck_dir, log=self.log)
                # Checkpoints past the watermark hold epochs whose reports
                # never became decisions; quarantine so no later fallback
                # can resurrect them (the requeue_lost discipline).
                ckpt_lib.quarantine_unreported(
                    ck_dir, watermark, tag="head", log=self.log
                )
            except Exception as exc:  # noqa: BLE001 - best-effort hygiene
                self.log(f"checkpoint hygiene failed for "
                         f"{trial.trial_id}: {exc!r}")
            last_requeue = jt["last_requeue"] if jt else None
            trial._requeue_on_complete = False
            if last_requeue is not None:
                # A journaled PBT exploit owns this trial's current config
                # and restore target (its in-memory config died with the
                # head; params.json still holds the original).  Re-apply
                # the exploit verbatim — re-reports up to the watermark are
                # suppressed, so re-running the donor window is wasted
                # compute, never duplicate accounting.
                trial.config = dict(last_requeue.get("config") or trial.config)
                trial.restore_path = last_requeue.get("restore_path")
                trial.restore_base = int(last_requeue.get("restore_base") or 0)
            else:
                ck_path, ck_it = ckpt_lib.newest_valid_checkpoint(
                    ck_dir, max_iteration=watermark
                )
                if ck_path:
                    trial.restore_path = ck_path
                    trial.restore_base = ck_it
                    trial.latest_checkpoint = ck_path
                    trial.latest_checkpoint_iteration = ck_it
                else:
                    trial.restore_path = None
                    trial.restore_base = 0
            if trial.restore_base < watermark:
                self._suppress[trial.trial_id] = watermark
                counts["suppress_windows"] += 1
            self.requeue(trial)
            counts["requeued"] += 1

        if self.journal is not None:
            self.journal.record_replay(**counts)
        return counts

    # -- results -----------------------------------------------------------

    def process_result(
        self, trial: Trial, metrics: Dict[str, Any], extra: Optional[Dict] = None
    ) -> str:
        """Stamp + persist a result, run scheduler/searcher; returns
        "stop" or "continue" (REQUEUE is folded into stop + a flag consumed
        by :meth:`complete_trial`)."""
        metrics = dict(metrics)
        watermark = self._suppress.get(trial.trial_id)
        if watermark is not None:
            # Journal-resume duplicate window: this incarnation restored
            # from a checkpoint below the journaled report watermark, so it
            # re-reports epochs the control plane already observed.  The
            # iteration clock still advances (training_iteration must line
            # up when fresh reports start), but nothing is re-persisted,
            # re-observed, or re-decided — every such epoch was journaled
            # "continue" (a stop/requeue watermark is resolved at restore).
            trial.reports_since_restart += 1
            it = trial.training_iteration
            if it <= watermark:
                self.duplicate_reports_suppressed += 1
                if it == watermark:
                    del self._suppress[trial.trial_id]
                return "continue"
            # Already past the watermark (sparse reporting): fall through
            # to the normal path, undoing the early increment.
            del self._suppress[trial.trial_id]
            trial.reports_since_restart -= 1
        trial.reports_since_restart += 1
        metrics.setdefault("training_iteration", trial.training_iteration)
        metrics["trial_id"] = trial.trial_id
        metrics["timestamp"] = time.time()
        metrics["time_total_s"] = trial.runtime_s()
        if extra:
            metrics.update(extra)
        trial.results.append(metrics)
        self.store.append_result(trial, metrics)
        self._prune_checkpoints(trial)

        # Snapshot before the scheduler runs: PBT mutates trial.config in
        # place on REQUEUE, and the searcher must see the config that
        # actually produced these metrics.
        reported_config = dict(trial.config)
        decision = self.scheduler.on_trial_result(trial, metrics)
        self.searcher.on_trial_result(
            trial.trial_id, reported_config, metrics, self.metric, self.mode
        )
        if self.stop_rules:
            # Dict of key->threshold, or a callable/Stopper
            # (tune/stoppers.py) judging this trial's own trajectory.
            if stop_hit(self.stop_rules, trial.trial_id, metrics):
                decision = STOP if decision == CONTINUE else decision
        if trial.stop_requested or self.budget_exceeded():
            decision = STOP
        if (
            self.time_limit_per_trial_s is not None
            and trial.incarnation_runtime_s() > self.time_limit_per_trial_s
            and decision == CONTINUE
        ):
            # Soft per-trial time limit: stop at the report boundary.  Trials
            # that never reach a report boundary are reaped by the runner's
            # hard-kill path (process executor).  Measured per incarnation so
            # a retried trial gets a fresh clock.
            self.log(
                f"{trial.trial_id} hit time limit "
                f"({trial.incarnation_runtime_s():.0f}s); stopping"
            )
            decision = STOP
        requeued = decision == REQUEUE
        if requeued:
            trial._requeue_on_complete = True
            decision = STOP
        if self.journal is not None:
            # WAL: scheduler/searcher/stopper mutations are all in; journal
            # the decision (with the post-mutation snapshot) before it is
            # returned to the executor.  A crash after the append replays
            # to this exact state and re-applies the decision at resume.
            requeue_payload = None
            if requeued:
                # PBT exploit: the scheduler rewrote config/restore target
                # in place.  Journaled so resume re-applies the exploit even
                # if the complete event (which performs the requeue) never
                # got processed.
                requeue_payload = {
                    "config": dict(trial.config),
                    "restore_path": trial.restore_path,
                    "restore_base": trial.restore_base,
                }
            value = metrics.get(self.metric)
            self.journal.record_report(
                trial.trial_id,
                int(metrics.get("training_iteration",
                                trial.training_iteration)),
                "requeue" if requeued
                else ("stop" if decision == STOP else "continue"),
                float(value)
                if isinstance(value, (int, float)) else None,
                self._snapshot(),
                requeue=requeue_payload,
            )
        return "stop" if decision == STOP else "continue"

    def final_prune(self) -> None:
        """End-of-run retention pass over every trial. Call AFTER the
        executor's writer has drained (join_all): writes that landed after
        a trial's last in-run prune (the depth-2 pipeline keeps up to 2 in
        flight) converge to exactly ``keep_checkpoints_num`` on disk."""
        for trial in self.trials:
            self._prune_checkpoints(trial)

    def _prune_checkpoints(self, trial: Trial):
        """Retention: keep the last k checkpoints of ``trial``, never deleting
        one that any trial's pending restore (PBT exploit / retry) points at.

        Runs on the single lifecycle thread, so the protect set is consistent
        with every REQUEUE decision made so far."""
        if self.keep_checkpoints_num <= 0 or not trial.latest_checkpoint:
            return
        from distributed_machine_learning_tpu.tune import checkpoint as ckpt_lib

        protected = {t.restore_path for t in self.trials if t.restore_path}
        protected.add(trial.latest_checkpoint)
        directory = self.store.checkpoint_dir(trial)
        try:
            # latest may still be in the async writer's queue: the newest k
            # DURABLE files are retained against it (transient overshoot up
            # to k + the executor's write-pipeline depth while writes land;
            # later prunes and final_prune converge back to k).
            ckpt_lib.prune_checkpoints(
                directory, self.keep_checkpoints_num, protect=protected,
                pending_latest=trial.latest_checkpoint,
            )
        except Exception as e:  # retention must never kill a run
            self.log(f"checkpoint pruning failed for {trial.trial_id}: {e}")

    # -- terminal events ---------------------------------------------------

    def complete_trial(self, trial: Trial) -> bool:
        """Trial finished cleanly. Returns True if it was requeued (PBT)."""
        if getattr(trial, "_requeue_on_complete", False):
            trial._requeue_on_complete = False
            self.requeue(trial)
            return True
        self.finish(trial, TrialStatus.TERMINATED)
        return False

    def fail_trial(self, trial: Trial, why: str) -> bool:
        """Trial errored/preempted. Returns True if it will be retried."""
        trial.num_failures += 1
        # A PBT-style REQUEUE may be pending when the failure lands; the
        # trial is being requeued NOW, so consume the flag — otherwise its
        # eventual genuine completion would trigger a spurious extra re-run.
        pbt_requeue = getattr(trial, "_requeue_on_complete", False)
        trial._requeue_on_complete = False
        if trial.num_failures <= self.max_failures:
            if pbt_requeue and trial.restore_path:
                # A scheduler-chosen restore target (PBT exploit pointing at a
                # DONOR's checkpoint) is being applied right now — keep it;
                # the scheduler already set restore_base.
                pass
            elif (
                trial.latest_checkpoint
                and trial.latest_checkpoint_iteration >= trial.restore_base
            ):
                # Most-advanced restore point available: the trial's own
                # newest checkpoint — unless the current incarnation was
                # seeded by a donor exploit it hasn't checkpointed past yet
                # (own checkpoint older than restore_base), in which case
                # overwriting would silently undo the exploit's weights.
                trial.restore_path = trial.latest_checkpoint
                trial.restore_base = trial.latest_checkpoint_iteration
            elif not trial.restore_path:
                trial.restore_base = 0
            # else: keep the seed restore target (donor / previous retry).
            self.log(
                f"{trial.trial_id} failed "
                f"({trial.num_failures}/{self.max_failures}): {why.splitlines()[-1] if why else why}; retrying"
                + (" from checkpoint" if trial.restore_path else "")
            )
            if self.journal is not None:
                self.journal.record_error(
                    trial.trial_id, True, self._snapshot()
                )
            self.requeue(trial)
            return True
        trial.error = why
        self.finish(trial, TrialStatus.ERROR)
        self.scheduler.on_trial_error(trial)
        return False

    def finish(self, trial: Trial, status: TrialStatus):
        trial.status = status
        trial.finished_at = time.time()
        if status == TrialStatus.TERMINATED:
            self.searcher.on_trial_complete(
                trial.trial_id, trial.config, trial.last_result, self.metric, self.mode
            )
        else:
            # Errored trials complete with result=None: model-based
            # searchers skip the observation (their None-score guard), but
            # WRAPPING searchers still see the completion — a Repeater
            # group with a crashed member must dispatch its mean instead of
            # stalling forever on a report that will never come.
            self.searcher.on_trial_complete(
                trial.trial_id, trial.config, None, self.metric, self.mode
            )
        self.scheduler.on_trial_complete(trial)
        if self.journal is not None:
            # Journaled AFTER the completion hooks mutate searcher/scheduler
            # state, so the snapshot is the post-completion decision state
            # (a resume that finds this record sets status directly — the
            # hooks must not run twice).
            self.journal.record_complete(
                trial.trial_id, status.value, self._snapshot(),
                error=trial.error,
            )

    def requeue(self, trial: Trial):
        trial.status = TrialStatus.PENDING
        trial.reports_since_restart = 0
        self.pending.append(trial)

    def mark_running(self, trial: Trial, worker: Optional[str] = None):
        if self.journal is not None:
            # WAL: dispatch journaled before the launch frame/thread exists,
            # so resume knows this trial was in flight (no state snapshot —
            # dispatch decides nothing).
            self.journal.record_dispatch(trial.trial_id, worker=worker)
        trial.status = TrialStatus.RUNNING
        now = time.time()
        trial.started_at = trial.started_at or now
        trial.restarted_at = now
        trial.incarnation += 1
        trial.stop_requested = False
