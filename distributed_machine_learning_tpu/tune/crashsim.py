"""Head-crash simulation harness: SIGKILL the driver mid-sweep, resume.

The chaos plane can kill the head at an exact decision number
(``chaos.kill_head_at`` — the ``os._exit(86)`` fires right after the
decision record is fsync'd and BEFORE its effect happens), but a dead
head takes its test process with it.  This module runs the sweep in a
CHILD process so the kill is survivable and measurable:

* :func:`run_child` — execute one sweep (thread or cluster driver) in a
  subprocess built from a JSON spec; the child writes its result
  (best trial, counters, per-trial iteration streams) to a file, so a
  crashed child leaves no result and a clean child leaves exactly one.
* :func:`killed_then_resumed` — the full scenario: sweep killed at
  decision N (exit 86, or 87 for a torn journal append), uncommitted
  journal detected, ``resume="auto"`` child finishes the experiment.
  Returns the resumed result plus the recovery timings the bench
  ``head_recovery`` section reports (detect / replay / requeue seconds,
  all derived from journal record timestamps — no harness clocks inside
  the measured path).
* :func:`control_run` — the same spec uninterrupted, for
  crashed-equals-control assertions.
* :func:`suggestion_stream` — the journaled ``create`` stream
  ``[(trial_id, config), ...]``: the object restart-determinism tests
  compare between a killed+resumed sweep and its control.

Used by tests/test_head_crash.py, scripts/lint_gate.py's head-crash
smoke, and bench.py.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from distributed_machine_learning_tpu.tune import journal as journal_lib

TRAINABLE_REF = "distributed_machine_learning_tpu.tune.crashsim:crashsim_trainable"

#: exit codes the chaos plane uses for an injected head death
HEAD_KILL_EXIT = 86
TORN_JOURNAL_EXIT = 87


def crashsim_trainable(config):
    """Deterministic checkpointing trainable: score depends only on
    ``config['x']`` and the epoch, so a requeued re-run reports the
    exact values the killed run would have."""
    from distributed_machine_learning_tpu import tune

    ckpt = tune.get_checkpoint()
    start = int(ckpt["epoch"]) + 1 if ckpt else 1
    epochs = int(config.get("epochs", 5))
    for epoch in range(start, epochs + 1):
        time.sleep(float(config.get("epoch_s", 0.01)))
        score = (float(config["x"]) - 0.7) ** 2 + 0.1 / epoch
        tune.report(
            {"score": score, "training_iteration": epoch},
            checkpoint={"epoch": epoch},
        )


def _build_searcher(kind: Optional[str], seed: int):
    if not kind:
        return None
    from distributed_machine_learning_tpu import tune

    if kind == "bayes":
        return tune.BayesOptSearch(random_search_steps=4)
    raise ValueError(f"unknown crashsim searcher {kind!r}")


def _build_scheduler(kind: Optional[str], seed: int):
    if not kind:
        return None
    from distributed_machine_learning_tpu.tune import schedulers

    if kind == "asha":
        return schedulers.ASHAScheduler(
            max_t=8, grace_period=2, reduction_factor=2
        )
    if kind == "pbt":
        from distributed_machine_learning_tpu import tune

        return schedulers.PopulationBasedTraining(
            perturbation_interval=2,
            hyperparam_mutations={"x": tune.uniform(0.0, 1.0)},
            quantile_fraction=0.5,
            seed=seed,
        )
    raise ValueError(f"unknown crashsim scheduler {kind!r}")


def _child_main(spec_path: str) -> int:
    """Run ONE sweep per the JSON spec and write the result file.

    This IS the head process: an env-activated ``kill_head_at`` plan
    ``os._exit(86)``s it mid-journal-append, exactly like an OOM-kill."""
    from distributed_machine_learning_tpu import chaos, tune

    chaos.activate_from_env()
    with open(spec_path) as f:
        spec = json.load(f)

    space = {
        "x": tune.uniform(0.0, 1.0),
        "epochs": int(spec.get("epochs", 5)),
        "epoch_s": float(spec.get("epoch_s", 0.01)),
    }
    seed = int(spec.get("seed", 7))
    common = dict(
        metric=spec.get("metric", "score"),
        mode=spec.get("mode", "min"),
        num_samples=int(spec.get("num_samples", 6)),
        scheduler=_build_scheduler(spec.get("scheduler"), seed),
        search_alg=_build_searcher(spec.get("searcher"), seed),
        storage_path=spec["storage_path"],
        name=spec["name"],
        seed=seed,
        verbose=0,
        resume=spec.get("resume", False),
        trace=bool(spec.get("trace", False)),
    )
    if spec.get("driver") == "cluster":
        from distributed_machine_learning_tpu.tune import cluster

        analysis = cluster.run_distributed(
            TRAINABLE_REF,
            space,
            workers=spec["workers"],
            checkpoint_storage=spec.get("checkpoint_storage"),
            **common,
        )
    else:
        analysis = tune.run(
            crashsim_trainable,
            space,
            max_concurrent=spec.get("max_concurrent"),
            **common,
        )

    best = analysis.best_trial
    out = {
        "best_trial": best.trial_id if best else None,
        "best_config": dict(best.config) if best else None,
        "best_score": analysis.best_result.get(common["metric"])
        if best else None,
        "num_terminated": analysis.num_terminated(),
        "trial_iterations": {
            t.trial_id: [
                int(r.get("training_iteration", 0)) for r in t.results
            ]
            for t in analysis.trials
        },
    }
    tmp = spec["out"] + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=2)
    os.replace(tmp, spec["out"])
    return 0


def _child_env(chaos_plan: Optional[Dict[str, Any]]) -> Dict[str, str]:
    # Strip TPU-claiming sitecustomize entries (the child is CPU-only)
    # and any chaos plan inherited from the calling process.
    keep = [
        p
        for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    ]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(keep)
    env.pop("DML_CHAOS_PLAN", None)
    if chaos_plan is not None:
        env["DML_CHAOS_PLAN"] = json.dumps(chaos_plan)
    return env


def run_child(
    spec: Dict[str, Any],
    chaos_plan: Optional[Dict[str, Any]] = None,
    timeout: float = 300.0,
) -> Tuple[int, Optional[Dict[str, Any]]]:
    """Run one sweep in a subprocess; returns ``(returncode, result)``.

    ``result`` is the child's output document, or None when the child
    died before writing it (the crash phase of the scenario)."""
    spec = dict(spec)
    root = spec["storage_path"]
    os.makedirs(root, exist_ok=True)
    spec.setdefault("out", os.path.join(
        root, f"{spec['name']}_result_{spec.get('phase', 'run')}.json"
    ))
    fd, spec_path = tempfile.mkstemp(suffix=".json", dir=root)
    with os.fdopen(fd, "w") as f:
        json.dump(spec, f)
    try:
        proc = subprocess.run(
            [sys.executable, "-m",
             "distributed_machine_learning_tpu.tune.crashsim", spec_path],
            env=_child_env(chaos_plan),
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    finally:
        try:
            os.unlink(spec_path)
        except OSError:
            pass
    result = None
    if os.path.exists(spec["out"]):
        with open(spec["out"]) as f:
            result = json.load(f)
        os.unlink(spec["out"])
    if proc.returncode not in (0, HEAD_KILL_EXIT, TORN_JOURNAL_EXIT):
        raise RuntimeError(
            f"crashsim child rc={proc.returncode}\n"
            f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
        )
    return proc.returncode, result


def _recovery_timings(root: str) -> Dict[str, float]:
    """Replay/requeue durations from journal record timestamps: the
    resumed head's ``head_start`` → ``replay`` gap is the replay, the
    ``replay`` → first ``dispatch`` gap is the requeue."""
    records = journal_lib.read_records(root)
    head2 = replay_rec = first_dispatch = None
    for rec in records:
        if rec.get("type") == "head_start" and int(
            rec.get("incarnation", 1)
        ) >= 2 and head2 is None:
            head2 = rec
        elif head2 is not None and rec.get("type") == "replay" and (
            replay_rec is None
        ):
            replay_rec = rec
        elif replay_rec is not None and rec.get("type") == "dispatch" and (
            first_dispatch is None
        ):
            first_dispatch = rec
    out = {"replay_s": 0.0, "requeue_s": 0.0}
    if head2 and replay_rec:
        out["replay_s"] = round(
            float(replay_rec["at_unix"]) - float(head2["at_unix"]), 4
        )
    if replay_rec and first_dispatch:
        out["requeue_s"] = round(
            float(first_dispatch["at_unix"]) - float(replay_rec["at_unix"]), 4
        )
    return out


def killed_then_resumed(
    storage_path: str,
    name: str,
    *,
    driver: str = "thread",
    kill_at: int = 6,
    torn_write: bool = False,
    workers: Optional[List[str]] = None,
    checkpoint_storage: Optional[str] = None,
    searcher: Optional[str] = None,
    scheduler: Optional[str] = None,
    num_samples: int = 6,
    epochs: int = 5,
    seed: int = 7,
    max_concurrent: Optional[int] = None,
    trace: bool = False,
    timeout: float = 300.0,
) -> Dict[str, Any]:
    """Kill the head at decision ``kill_at``, auto-resume, report.

    Returns ``{crash_rc, detect_s, replay_s, requeue_s, resume_total_s,
    result, journal}`` where ``result`` is the RESUMED child's output
    and ``journal`` is :func:`tune.journal.journal_status` afterwards.
    """
    spec = {
        "driver": driver,
        "storage_path": storage_path,
        "name": name,
        "workers": workers,
        "checkpoint_storage": checkpoint_storage,
        "searcher": searcher,
        "scheduler": scheduler,
        "num_samples": num_samples,
        "epochs": epochs,
        "seed": seed,
        "max_concurrent": max_concurrent,
        "trace": trace,
    }
    plan_key = (
        "kill_head_during_journal_write" if torn_write else "kill_head_at"
    )
    rc, _ = run_child(
        {**spec, "phase": "crash"},
        chaos_plan={plan_key: kill_at},
        timeout=timeout,
    )
    expected = TORN_JOURNAL_EXIT if torn_write else HEAD_KILL_EXIT
    if rc != expected:
        raise RuntimeError(
            f"crash phase exited {rc}, expected {expected} "
            f"(plan {plan_key}={kill_at})"
        )

    root = os.path.join(storage_path, name)
    t0 = time.monotonic()
    uncommitted = journal_lib.is_uncommitted(root)
    detect_s = round(time.monotonic() - t0, 4)
    if not uncommitted:
        raise RuntimeError("killed head left a committed journal")

    t1 = time.monotonic()
    rc2, result = run_child(
        {**spec, "phase": "resume", "resume": "auto"}, timeout=timeout
    )
    resume_total_s = round(time.monotonic() - t1, 4)
    if rc2 != 0 or result is None:
        raise RuntimeError(f"resume phase exited {rc2} without a result")

    return {
        "crash_rc": rc,
        "detect_s": detect_s,
        "resume_total_s": resume_total_s,
        **_recovery_timings(root),
        "result": result,
        "journal": journal_lib.journal_status(root),
    }


def control_run(
    storage_path: str,
    name: str,
    *,
    driver: str = "thread",
    workers: Optional[List[str]] = None,
    checkpoint_storage: Optional[str] = None,
    searcher: Optional[str] = None,
    scheduler: Optional[str] = None,
    num_samples: int = 6,
    epochs: int = 5,
    seed: int = 7,
    max_concurrent: Optional[int] = None,
    trace: bool = False,
    timeout: float = 300.0,
) -> Dict[str, Any]:
    """The uninterrupted twin of :func:`killed_then_resumed`."""
    rc, result = run_child(
        {
            "driver": driver,
            "storage_path": storage_path,
            "name": name,
            "workers": workers,
            "checkpoint_storage": checkpoint_storage,
            "searcher": searcher,
            "scheduler": scheduler,
            "num_samples": num_samples,
            "epochs": epochs,
            "seed": seed,
            "max_concurrent": max_concurrent,
            "trace": trace,
            "phase": "control",
        },
        timeout=timeout,
    )
    if rc != 0 or result is None:
        raise RuntimeError(f"control run exited {rc} without a result")
    return result


def suggestion_stream(root: str) -> List[Tuple[str, Dict[str, Any]]]:
    """The journaled searcher output: ``(trial_id, config)`` per
    ``create`` decision, in journal order."""
    return [
        (rec["trial_id"], rec["config"])
        for rec in journal_lib.read_records(root)
        if rec.get("type") == "create"
    ]


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(_child_main(sys.argv[1]))
