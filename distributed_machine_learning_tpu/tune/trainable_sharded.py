"""Built-in multi-device (sharded) regression trainable.

The multi-core-per-trial path (BASELINE config 5: N cores per trial via
``resources_per_trial={"devices": N}``).  The executor leases N devices to
the trial; this trainable builds a named mesh over exactly those devices
and runs the whole epoch as ONE jitted program:

* layouts come from the model family's **partition-rule table**
  (``models/partition_rules.py`` -> ``parallel/partition.py``), not a
  hard-coded spec table: params born sharded (abstract convention probe ->
  rule shardings -> ``out_shardings`` on the jitted init, so an over-HBM
  flagship never materializes unsharded), optimizer moments inherit the
  layout, activations pinned at the residual-stream/attention boundaries
  (``models/layers.constrain_activation`` — the model gets the mesh);
* the **fused epoch loop**: ``lax.scan`` over pre-sharded batch chunks
  inside one program, ``donate_argnums`` covering params, opt-state,
  batch-stats AND the epoch's batch arrays — N per-step dispatches
  collapse to one, donated buffers are reused in place (audited: the
  ``donation_aliased_buffers`` counter records donated inputs observed
  consumed after the first call);
* the epoch program resolves through the **AOT executable cache** under a
  ``sharded_program_key`` that folds in the mesh shape and the rule-table
  fingerprint, so sharded programs compile-once/cross-worker-dedup like
  everything else (``compilecache/``);
* BatchNorm models get synchronized BN for free: under jit the batch mean
  over a dp-sharded axis is the *global* mean (GSPMD adds the psum).

Config keys beyond ``train_regressor``'s: ``mesh_shape`` — dict of mesh
axis sizes, e.g. ``{"dp": 4}`` (default: pure dp over all leased devices)
or ``{"dp": 2, "tp": 2}`` (also settable sweep-wide via
``tune.run(mesh_shape=...)``); ``remat``/``remat_policy`` — per-block
rematerialization and its ``jax.checkpoint_policies`` name;
``partition_rules`` — per-trial rule-table override.  ``batch_size`` is
the *global* batch and must be divisible by dp.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu import obs
from distributed_machine_learning_tpu.compilecache import (
    get_counters as get_compile_counters,
    sharded_program_key,
)
from distributed_machine_learning_tpu.data.loader import Dataset
from distributed_machine_learning_tpu.models import build_model
from distributed_machine_learning_tpu.models.partition_rules import rules_for
from distributed_machine_learning_tpu.ops.losses import get_loss
from distributed_machine_learning_tpu.ops.optimizers import (
    INJECTABLE_OPTIMIZERS,
    make_injected_optimizer,
    make_optimizer,
    set_injected_hyperparams,
)
from distributed_machine_learning_tpu.ops.schedules import get_schedule
from distributed_machine_learning_tpu.parallel.mesh import make_mesh
from distributed_machine_learning_tpu.parallel.partition import (
    mesh_axis_sizes,
    rules_fingerprint,
)
from distributed_machine_learning_tpu.parallel.sharding import (
    opt_state_shardings,
    param_shardings,
)
from distributed_machine_learning_tpu.perf.costmodel import (
    EpochPerfAccounting,
)
from distributed_machine_learning_tpu.tune import session
from distributed_machine_learning_tpu.tune._regression_program import (
    detect_call_convention,
    make_forward,
    make_indexed_chunk_fn,
    make_indexed_epoch_fn,
    per_example_losses,
)
from distributed_machine_learning_tpu.tune.checkpoint import restore_into
from distributed_machine_learning_tpu.utils.compile_cache import get_tracker
from distributed_machine_learning_tpu.utils.dispatch import (
    dispatch_lock,
    serialization_on,
)
from distributed_machine_learning_tpu.utils.seeding import (
    fold_seed,
    init_rngs_for,
)


def _host(tree):
    return jax.tree.map(lambda a: np.asarray(a), tree)


def _host_template(tree):
    """A restore TEMPLATE matching ``tree``'s structure/shapes/dtypes with
    no device readback: ``restore_into`` takes every value from the
    checkpoint, so zeros serve — and a process-SPANNING array (multihost
    gang trials) cannot be ``np.asarray``'d at all."""
    return jax.tree.map(
        lambda a: np.zeros(a.shape, a.dtype) if hasattr(a, "shape") else a,
        tree,
    )


@functools.lru_cache(maxsize=1)
def _epoch_aot_cache():
    """One process-wide AOT store for fused epoch programs: a second trial
    of the same shape class (or a restarted runner) deserializes the
    finished executable instead of re-tracing (``compilecache/aot.py``)."""
    from distributed_machine_learning_tpu.compilecache.aot import (
        ExecutableCache,
    )

    return ExecutableCache()


def _partitionable_threefry():
    """Scope ``jax_threefry_partitionable`` over this trainable's programs.

    Params are born sharded (``out_shardings`` on the init jit), and the
    default threefry lowering makes sharded random draws depend on the
    OUTPUT LAYOUT — the same seed would produce a different model on a
    dp×tp mesh than on pure dp (observed: tp-sharded kernels diverged,
    breaking the "TP is a layout, not a numerics change" contract).
    Partitionable threefry is jax's mesh-invariant stream: same key ⇒
    same values on any mesh, any sharding.  Scoped here (thread-local)
    so the unsharded trainables' recorded numerics stay untouched.
    """
    try:
        from jax._src.config import threefry_partitionable

        return threefry_partitionable(True)
    except Exception:  # noqa: BLE001 - private flag moved; fall through
        import contextlib

        return contextlib.nullcontext()


def train_sharded_regressor(
    config: Dict[str, Any],
    train_data: Optional[Dataset] = None,
    val_data: Optional[Dataset] = None,
):
    """Multi-device trainable. Bind datasets with ``tune.with_parameters``."""
    if train_data is None or val_data is None:
        raise ValueError("train_sharded_regressor needs train_data/val_data")
    with _partitionable_threefry():
        return _train_sharded(config, train_data, val_data)


def _train_sharded(
    config: Dict[str, Any],
    train_data: Dataset,
    val_data: Dataset,
):

    from distributed_machine_learning_tpu.multihost import runtime as mh

    n_procs = jax.process_count()
    if n_procs > 1:
        # Gang trial (multihost/): ONE mesh over every process's devices.
        # This process traces the same global program as its peers, loads
        # only the batch slices its devices address (stage_global), and
        # checkpoints only the shards it holds (host_snapshot + the
        # sharded format).  The budget probe must read a LOCAL device —
        # a peer's device has no memory stats here (dmlint DML016).
        devices = list(jax.devices())
        mesh_shape = dict(config.get("mesh_shape") or {"dp": len(devices)})
        mesh = mh.spanning_mesh(mesh_shape)
        budget_device = jax.local_devices()[0]
    else:
        devices = session.get_devices() or list(jax.devices())
        mesh_shape = dict(config.get("mesh_shape") or {"dp": len(devices)})
        mesh = make_mesh(mesh_shape, devices)
        budget_device = devices[0]
    dp = int(mesh.shape.get("dp", 1))
    rules = rules_for(config)
    rules_fp = rules_fingerprint(rules)

    num_epochs = int(config.get("num_epochs", 20))
    seed = int(config.get("seed", 0))
    loss_name = str(config.get("loss_function", "mse"))
    global_batch = int(config.get("batch_size", 32))
    if global_batch % dp != 0:
        raise ValueError(
            f"global batch_size={global_batch} must be divisible by dp={dp}"
        )

    x_np = np.asarray(train_data.x, np.float32)
    y_np = np.asarray(train_data.y, np.float32)
    n_train = len(x_np)
    if n_train < global_batch:
        raise ValueError(
            f"train set ({n_train} rows) is smaller than the global "
            f"batch_size ({global_batch}); lower batch_size (it must stay "
            f"divisible by dp={dp})"
        )
    num_batches = n_train // global_batch
    steps_per_epoch = num_batches

    # Input-mode resolution (data/pipeline.py): the staged epoch arrays'
    # batch axis spreads over dp, so the resident footprint PER DEVICE is
    # the dataset over dp — streaming engages when even that slice
    # exceeds the engage fraction of one device's budget; explicit
    # "resident" over budget raises.
    from distributed_machine_learning_tpu.data import pipeline as hostpipe

    dataset_bytes = (
        x_np.nbytes + y_np.nbytes
        + int(val_data.x.size + val_data.y.size) * 4
    )
    if n_procs > 1 and str(config.get("input_mode") or "") == "streaming":
        raise ValueError(
            "input_mode='streaming' is not supported on a process-spanning "
            "mesh yet: the prefetch ring stages whole slabs per process "
            "and would double-buffer every host's full epoch (use "
            "'resident', or run the trial single-process)"
        )
    input_mode = hostpipe.resolve_input_mode(
        config, dataset_bytes, budget_device, shards=dp
    )
    streaming = input_mode == "streaming" and n_procs == 1
    if streaming:
        hostpipe.get_host_input_counters().add("streams_engaged")
        per_dev_row_nbytes = max(
            (int(np.prod(x_np.shape[1:], dtype=np.int64)) * 4
             + int(np.prod(y_np.shape[1:], dtype=np.int64)) * 4) // dp,
            1,
        )
        chunk_plan = hostpipe.plan_chunks(
            num_batches, global_batch, per_dev_row_nbytes,
            device=devices[0], config=config,
        )
    else:
        chunk_plan = None

    accum = max(int(config.get("accumulate_grad_batches", 1)), 1)
    total_steps = int(
        config.get(
            "total_steps", num_epochs * max(steps_per_epoch // accum, 1)
        )
    )
    lr = float(config["learning_rate"])
    wd = float(config.get("weight_decay", 0.0))
    opt_name = str(config.get("optimizer", "adam")).lower()
    # Same-architecture trials share ONE traced program when lr/wd ride in
    # the optimizer state instead of being baked as HLO constants — see
    # tune/trainable.py (the identical logic) and ops/optimizers.py.
    injected = (
        opt_name in INJECTABLE_OPTIMIZERS
        and accum == 1
        and bool(config.get("inject_hyperparams", True))
    )
    if injected:
        shape_schedule = get_schedule(
            str(config.get("lr_schedule", "warmup_linear_decay")),
            learning_rate=1.0,
            warmup_steps=int(config.get("warmup_steps", 0)),
            total_steps=max(total_steps, 1),
        )
        tx = make_injected_optimizer(
            opt_name,
            shape_schedule,
            momentum=float(config.get("momentum", 0.0)),
            gradient_clipping=float(config.get("gradient_clipping", 0.0)),
        )
    else:
        schedule = get_schedule(
            str(config.get("lr_schedule", "warmup_linear_decay")),
            learning_rate=lr,
            warmup_steps=int(config.get("warmup_steps", 0)),
            total_steps=max(total_steps, 1),
        )
        tx = make_optimizer(
            opt_name,
            learning_rate=schedule,
            weight_decay=wd,
            momentum=float(config.get("momentum", 0.0)),
            gradient_clipping=float(config.get("gradient_clipping", 0.0)),
            accumulate_grad_batches=accum,
        )
    loss_fn = get_loss(loss_name)

    # The model carries the mesh so the activation sharding constraints
    # (residual stream, attention q/k/v — models/layers.py) are live; the
    # local copy keeps Mesh objects out of the stored trial config.
    model = build_model(dict(config, mesh=mesh))
    sample_x = x_np[:1]
    repl = NamedSharding(mesh, P())

    # Device-call section (init dispatch, shard placement, jit init):
    # serialized across concurrent trial threads on fragile backends
    # (utils/dispatch.py — the tunnel-wedge mitigation, same coverage
    # as tune/trainable.py's init block).
    with dispatch_lock():
        # Abstract convention probe: flag kwarg + BN detection via
        # eval_shape — nothing allocated, so the rule shardings below
        # exist BEFORE any parameter is materialized (an over-HBM
        # flagship must be born sharded, not placed then re-placed).
        abstract_vars, flag_name = detect_call_convention(
            model, sample_x, abstract=True,
        )
        has_bn = "batch_stats" in abstract_vars
        forward = make_forward(model, flag_name, has_bn)

        p_shardings = param_shardings(
            abstract_vars["params"], mesh, rules
        )
        bs_shardings = jax.tree.map(
            lambda _: repl, abstract_vars.get("batch_stats", {})
        )
        v_shardings = jax.tree.map(lambda _: repl, abstract_vars)
        v_shardings = dict(v_shardings, params=p_shardings)
        if has_bn:
            v_shardings["batch_stats"] = bs_shardings
        init_kwargs = {
            flag_name: True if flag_name == "deterministic" else False
        }
        # Per-trial init diversity, same as train_regressor (the rng is a
        # traced argument — one compiled init program per architecture);
        # out_shardings = the rule layout, so params are born sharded.
        variables = jax.jit(
            lambda r, x: model.init(r, x, **init_kwargs),
            out_shardings=v_shardings,
        )(init_rngs_for(seed), sample_x)
        params = variables["params"]
        o_shardings = opt_state_shardings(
            jax.eval_shape(tx.init, params), p_shardings, mesh
        )
        opt_state = jax.jit(
            tx.init, in_shardings=(p_shardings,), out_shardings=o_shardings
        )(params)
        if injected:
            opt_state = set_injected_hyperparams(opt_state, lr, wd)
        batch_stats = variables.get("batch_stats", {})

    # Batched-epoch shardings: [num_batches, global_batch, ...] with the
    # in-batch dim over dp.
    def batched_sharding(ndim):
        return NamedSharding(mesh, P(*([None, "dp"] + [None] * (ndim - 2))))

    xb_sharding = batched_sharding(x_np.ndim + 1)
    yb_sharding = batched_sharding(y_np.ndim + 1)
    xv_sharding = NamedSharding(mesh, P("dp"))
    xb_shape = (num_batches, global_batch) + x_np.shape[1:]
    yb_shape = (num_batches, global_batch) + y_np.shape[1:]

    # Program bodies live in _regression_program.py (make_indexed_*) so the
    # jaxlint donation/hygiene audits lower the EXACT programs this
    # trainable runs; the streaming chunk twin threads the global batch
    # counter through ``i0`` so ``fold_in(epoch_key, i)`` matches the
    # resident program bit for bit across chunk boundaries.
    epoch_fn = make_indexed_epoch_fn(forward, tx, loss_fn)
    chunk_fn = make_indexed_chunk_fn(forward, tx, loss_fn)

    # The fused epoch program: donation covers EVERY large input — params
    # (0), opt_state (1), batch_stats (2), and the staged epoch batches
    # (3, 4): the batch chunks are consumed exactly once per epoch, so
    # donating them saves a full epoch-sized HBM copy per epoch.
    _EPOCH_DONATE = (0, 1, 2, 3, 4)
    # Chunk donation: state plus the consumed slab (4, 5) — each staged
    # chunk's buffers free at the chunk boundary (the ring's memory
    # bound); i0 and epoch_key are scalars.
    _CHUNK_DONATE = (0, 1, 2, 4, 5)
    # out_shardings pinned to the SAME rule layout as the inputs: without
    # the pin GSPMD may propagate a different layout onto the returned
    # params (observed: head params pulled onto 'tp' by the head-kernel
    # rule), which both breaks the next call's in_shardings contract and
    # defeats donation (an input can only alias an identically-laid-out
    # output).
    epoch_jit_kwargs = {
        "in_shardings": (
            p_shardings, o_shardings, bs_shardings,
            xb_sharding, yb_sharding, repl,
        ),
        "out_shardings": (p_shardings, o_shardings, bs_shardings, repl),
    }

    def jit_epoch():
        return jax.jit(
            epoch_fn, donate_argnums=_EPOCH_DONATE, **epoch_jit_kwargs
        )

    # AOT tier: the program key folds in mesh shape + rule-table
    # fingerprint (sharded_program_key) so a reshaped mesh or edited rule
    # table can never alias a stale executable; any resolution failure
    # degrades to the plain jit (persistent XLA cache still applies).
    program_key = sharded_program_key(
        config,
        mesh_shape=mesh_axis_sizes(mesh),
        rules_fingerprint=rules_fp,
        batch_shape=[list(xb_shape), list(yb_shape)],
        dtype=str(config.get("compute_dtype") or "float32"),
        donation=_EPOCH_DONATE,
        # A loaded executable is bound to CONCRETE devices: two same-class
        # trials leased onto different 4-device groups of one host must
        # not share an AOT entry (the collision hands trial B outputs
        # placed on trial A's devices).  Cross-worker dedup is unaffected
        # — it rides the persistent-cache/artifact-origin key, not this
        # executable-level one.  On a process-spanning mesh the PROCESS
        # TOPOLOGY folds in too: the same mesh shape decomposed over a
        # different process layout lowers different cross-process
        # collectives (reshaping the gang must split the key; the same
        # topology elsewhere must not).
        extra={
            "device_ids": [
                int(getattr(d, "id", i)) for i, d in enumerate(devices)
            ],
            **({"process_topology": mh.process_topology()}
               if n_procs > 1 else {}),
        },
    )
    chunk_jit_kwargs = {
        "in_shardings": (
            p_shardings, o_shardings, bs_shardings, repl,
            xb_sharding, yb_sharding, repl,
        ),
        "out_shardings": (p_shardings, o_shardings, bs_shardings, repl),
    }

    def jit_chunk():
        return jax.jit(
            chunk_fn, donate_argnums=_CHUNK_DONATE, **chunk_jit_kwargs
        )

    train_epoch = train_chunk = None
    if streaming:
        # Chunked programs carry their OWN cache identity: slab rows fold
        # in (the scan trip count baked into the trace), the chunk COUNT
        # does not (the host loops) — so dataset length never splits the
        # key.  One jitted callable serves full and tail slabs (jit
        # retraces per shape: at most two traces per geometry); the
        # full-slab trace resolves through the AOT tier.
        chunk_shape = (
            (chunk_plan.chunk_batches, global_batch) + x_np.shape[1:],
            (chunk_plan.chunk_batches, global_batch) + y_np.shape[1:],
        )
        chunk_key = sharded_program_key(
            config,
            mesh_shape=mesh_axis_sizes(mesh),
            rules_fingerprint=rules_fp,
            batch_shape=[list(chunk_shape[0]), list(chunk_shape[1])],
            dtype=str(config.get("compute_dtype") or "float32"),
            donation=_CHUNK_DONATE,
            extra={
                "stream_chunk_rows": chunk_plan.chunk_batches,
                "device_ids": [
                    int(getattr(d, "id", i)) for i, d in enumerate(devices)
                ],
            },
        )
        with dispatch_lock():
            try:
                train_chunk = _epoch_aot_cache().get_or_compile(
                    chunk_key, chunk_fn,
                    params, opt_state, batch_stats, jnp.int32(0),
                    jax.ShapeDtypeStruct(chunk_shape[0], jnp.float32),
                    jax.ShapeDtypeStruct(chunk_shape[1], jnp.float32),
                    jax.random.key(0),
                    donate_argnums=_CHUNK_DONATE,
                    jit_kwargs=chunk_jit_kwargs,
                )
            except Exception:  # noqa: BLE001 - AOT must never fail a trial
                train_chunk = jit_chunk()
        train_chunk_tail = jit_chunk() if chunk_plan.tail_batches else None
    elif n_procs > 1:
        # Process-spanning programs skip the AOT executable tier (a
        # serialized executable pins concrete devices of ONE process
        # view); compile-once still holds through the persistent XLA
        # cache + artifact origin, whose keys fold the process topology.
        train_epoch = jit_epoch()
    else:
      with dispatch_lock():
        try:
            train_epoch = _epoch_aot_cache().get_or_compile(
                program_key, epoch_fn,
                params, opt_state, batch_stats,
                jax.ShapeDtypeStruct(xb_shape, jnp.float32),
                jax.ShapeDtypeStruct(yb_shape, jnp.float32),
                jax.random.key(0),
                donate_argnums=_EPOCH_DONATE,
                jit_kwargs=epoch_jit_kwargs,
            )
        except Exception:  # noqa: BLE001 - AOT must never fail a trial
            train_epoch = jit_epoch()

    # Eval: pad the val set to a multiple of dp, mask the padding out.
    xv_np = np.asarray(val_data.x, np.float32)
    yv_np = np.asarray(val_data.y, np.float32)
    n_val = len(xv_np)
    pad = (-n_val) % dp
    if pad:
        xv_np = np.concatenate([xv_np, np.zeros_like(xv_np[:pad])])
        yv_np = np.concatenate([yv_np, np.ones_like(yv_np[:pad])])
    mask_np = (np.arange(len(xv_np)) < n_val).astype(np.float32)

    def eval_fn(params, batch_stats, xv, yv, mask):
        preds, _, _ = forward(params, batch_stats, xv, jax.random.key(0), False)
        se, ae, ape = per_example_losses(preds.astype(jnp.float32), yv)
        denom = mask.sum()
        return {
            "validation_loss": (se * mask).sum() / denom,
            "validation_mae": (ae * mask).sum() / denom,
            "validation_mape": 100.0 * (ape * mask).sum() / denom,
        }

    evaluate = jax.jit(
        eval_fn, in_shardings=(None, None, xv_sharding, xv_sharding, xv_sharding)
    )
    # Validation staging is device traffic too — same hold discipline
    # (utils/dispatch.py).  stage_global = device_put single-process; on a
    # spanning mesh each process stages only its addressable slices.
    with dispatch_lock():
        xv = mh.stage_global(xv_np, xv_sharding)
        yv = mh.stage_global(yv_np, xv_sharding)
        mask = mh.stage_global(mask_np, xv_sharding)

    # ---- restore (PBT exploit / fault retry) -------------------------------
    start_epoch = 0
    ckpt = session.get_checkpoint()
    if ckpt is not None:
      # Restore readbacks (_host) + re-sharding device_puts serialized
      # like every other device-call section (utils/dispatch.py).
      with dispatch_lock():
        template = {
            "params": _host_template(params),
            "opt_state": _host_template(opt_state),
            "batch_stats": _host_template(batch_stats),
            "epoch": 0,
        }
        try:
            restored = restore_into(template, ckpt)
        except (ValueError, KeyError, TypeError, AttributeError):
            if not injected:
                raise
            # Legacy checkpoint from the pre-injection (baked) optimizer
            # layout — rebuild the baked chain for this incarnation (same
            # fallback as tune/trainable.py), then rebuild the program
            # bodies over the new `tx` and re-jit (plain jit: the AOT key
            # describes the injected layout, not this incarnation's).
            injected = False
            schedule = get_schedule(
                str(config.get("lr_schedule", "warmup_linear_decay")),
                learning_rate=lr,
                warmup_steps=int(config.get("warmup_steps", 0)),
                total_steps=max(total_steps, 1),
            )
            tx = make_optimizer(
                opt_name,
                learning_rate=schedule,
                weight_decay=wd,
                momentum=float(config.get("momentum", 0.0)),
                gradient_clipping=float(
                    config.get("gradient_clipping", 0.0)
                ),
                accumulate_grad_batches=accum,
            )
            o_shardings = opt_state_shardings(
                jax.eval_shape(tx.init, params), p_shardings, mesh
            )
            opt_state = jax.jit(
                tx.init, in_shardings=(p_shardings,),
                out_shardings=o_shardings,
            )(params)
            epoch_fn = make_indexed_epoch_fn(forward, tx, loss_fn)
            chunk_fn = make_indexed_chunk_fn(forward, tx, loss_fn)
            epoch_jit_kwargs["in_shardings"] = (
                p_shardings, o_shardings, bs_shardings,
                xb_sharding, yb_sharding, repl,
            )
            epoch_jit_kwargs["out_shardings"] = (
                p_shardings, o_shardings, bs_shardings, repl,
            )
            chunk_jit_kwargs["in_shardings"] = (
                p_shardings, o_shardings, bs_shardings, repl,
                xb_sharding, yb_sharding, repl,
            )
            chunk_jit_kwargs["out_shardings"] = (
                p_shardings, o_shardings, bs_shardings, repl,
            )
            if streaming:
                train_chunk = jit_chunk()
                train_chunk_tail = (
                    jit_chunk() if chunk_plan.tail_batches else None
                )
            else:
                train_epoch = jit_epoch()
            template["opt_state"] = _host_template(opt_state)
            restored = restore_into(template, ckpt)
        # Re-shard restored host arrays into the live mesh layout.
        params = jax.device_put(restored["params"], p_shardings)
        opt_state = jax.device_put(restored["opt_state"], o_shardings)
        if injected:
            # This trial's config lr/wd win over restored slots (PBT
            # explore semantics — same as tune/trainable.py).
            opt_state = set_injected_hyperparams(opt_state, lr, wd)
        batch_stats = jax.device_put(
            restored["batch_stats"],
            jax.tree.map(lambda _: repl, restored["batch_stats"]),
        )
        start_epoch = int(restored["epoch"]) + 1

    checkpoint_freq = int(config.get("checkpoint_freq", 1))

    # ---- per-epoch MFU/roofline accounting (perf/costmodel.py) -------------
    # Same helper as tune/trainable.py; the sharded paths additionally
    # carry their AOT program key so the captured XLA cost is
    # cross-checked against the analytic model and the records report
    # ``roofline_bound`` (process-spanning programs skip the AOT tier —
    # and the audit — by construction).
    seq_len = int(x_np.shape[1]) if x_np.ndim == 3 else 1
    feats = int(x_np.shape[-1])
    perf_acct = EpochPerfAccounting(
        config,
        batch_size=global_batch,
        seq_len=seq_len,
        features=feats,
        steps_per_epoch=steps_per_epoch,
        eval_rows=n_val,
        device=budget_device,
        num_devices=len(devices),
        program_key=(
            chunk_key if streaming
            else program_key if n_procs == 1
            else None
        ),
        program_steps=(
            chunk_plan.chunk_batches if streaming else steps_per_epoch
        ),
        trial_id=session.current_trial_id(),
    )
    tracker = get_tracker()

    def epoch_perm(epoch: int) -> np.ndarray:
        """Per-EPOCH-keyed shuffle (not one sequential stream from trial
        start): a restored incarnation resuming at epoch k must draw
        epoch k's permutation, not replay epoch 0's — the property that
        makes an interrupted+requeued trial (gang teardown, preemption)
        finish bit-identical to an uninterrupted control.  Same keying
        convention as the in-program threefry chain
        (``fold_seed(seed, "epoch", epoch)``)."""
        return np.random.default_rng(
            fold_seed(seed, "shuffle", epoch)
        ).permutation(n_train)[: num_batches * global_batch]

    audit_donation = True

    if streaming:
        # ---- streaming epoch loop: consume chunk k while k+1 stages --------
        import time as _time

        depth = hostpipe.prefetch_depth(config)
        deadline_s = float(config.get(
            "streaming_producer_deadline_s",
            hostpipe.DEFAULT_PRODUCER_DEADLINE_S,
        ))

        def _stage(arr, sharding):
            if serialization_on():
                with dispatch_lock():
                    return jax.device_put(arr, sharding)
            return jax.device_put(arr, sharding)

        def _source():
            # The resident loop's OWN per-epoch shuffle keys, consumed in
            # the same epoch order — identical batches in identical order
            # is the determinism contract.
            for _epoch in range(start_epoch, num_epochs):
                perm = epoch_perm(_epoch)
                for start, rows in chunk_plan.chunk_sizes():
                    idx = perm[
                        start * global_batch:(start + rows) * global_batch
                    ]
                    xg, yg = hostpipe.gather_batches(
                        x_np, y_np, idx, rows, global_batch
                    )
                    yield _stage(xg, xb_sharding), _stage(yg, yb_sharding)

        prefetcher = hostpipe.ChunkPrefetcher(
            _source(), depth=depth, deadline_s=deadline_s,
            name=f"stream-{session.get_trial_id()}",
        )
        try:
            for epoch in range(start_epoch, num_epochs):
                step_count = (epoch + 1) * steps_per_epoch
                opt_steps = (epoch + 1) * max(steps_per_epoch // accum, 1)
                epoch_span = obs.span(
                    "epoch", {"epoch": epoch, "mode": "streaming"}
                )
                epoch_span.__enter__()
                with dispatch_lock():
                    epoch_key = jax.random.key(
                        fold_seed(seed, "epoch", epoch)
                    )
                    lr_now = (
                        lr * float(
                            shape_schedule(min(opt_steps, total_steps))
                        )
                        if injected
                        else float(schedule(min(opt_steps, total_steps)))
                    )
                wait0 = prefetcher.wait_s
                c0 = tracker.thread_seconds()
                t0 = _time.monotonic()
                loss_parts = []
                probes = None
                for start, rows in chunk_plan.chunk_sizes():
                    # The ring get stays OUTSIDE the dispatch hold — the
                    # producer's device_put takes the same lock under
                    # serialization.
                    xb, yb = prefetcher.get()
                    with dispatch_lock():
                        if audit_donation and probes is None:
                            probes = [xb, yb] \
                                + jax.tree.leaves(params)[:1] \
                                + jax.tree.leaves(opt_state)[:1]
                        prog = (
                            train_chunk
                            if rows == chunk_plan.chunk_batches
                            else train_chunk_tail
                        )
                        params, opt_state, batch_stats, losses = prog(
                            params, opt_state, batch_stats,
                            jnp.int32(start), xb, yb, epoch_key,
                        )
                    loss_parts.append(losses)
                    # A consumed chunk IS progress for the trial watchdog.
                    session.heartbeat()
                with dispatch_lock():
                    metrics = evaluate(params, batch_stats, xv, yv, mask)
                    train_loss = float(jnp.concatenate(loss_parts).mean())
                    metrics = {k: float(v) for k, v in metrics.items()}
                    if audit_donation and probes is not None:
                        audit_donation = False
                        consumed = sum(
                            1 for a in probes
                            if isinstance(a, jax.Array) and a.is_deleted()
                        )
                        if consumed:
                            get_compile_counters().add(
                                "donation_aliased_buffers", consumed
                            )
                wait_s = prefetcher.wait_s - wait0
                wall = _time.monotonic() - t0
                compile_s = tracker.thread_seconds() - c0
                exec_s = max(wall - compile_s - wait_s, 1e-9)
                prefetcher.note_consume(max(wall - wait_s, 0.0))
                record = {
                    "epoch": epoch,
                    "train_loss": train_loss,
                    "lr": lr_now,
                    "steps": step_count,
                    "num_devices": len(devices),
                    "mesh_shape": dict(mesh_shape),
                    "input_mode": "streaming",
                    **metrics,
                }
                # Wait rides in observe_s (a starved consumer must read
                # as slow to the anomaly detector), never in the MFU
                # numerator — same convention as tune/trainable.py.
                perf_acct.annotate(
                    record, exec_s, device=budget_device,
                    observe_s=max(wall - compile_s, 1e-9),
                )
                checkpoint = None
                if checkpoint_freq and (epoch + 1) % checkpoint_freq == 0:
                    with dispatch_lock():
                        checkpoint = {
                            "params": _host(params),
                            "opt_state": _host(opt_state),
                            "batch_stats": _host(batch_stats),
                            "epoch": epoch,
                        }
                # Close before report (scheduler wait is not epoch time);
                # an exception above leaves it open — the stall dump then
                # names the in-flight epoch as the hang site.
                epoch_span.__exit__(None, None, None)
                session.report(record, checkpoint=checkpoint)
        finally:
            # Early stop, crash, or clean finish: the producer thread and
            # its staged slabs must never outlive the trial.
            prefetcher.close()
        return None

    # ---- epoch loop: host-driven so the scheduler can interrupt ------------
    import time as _time

    for epoch in range(start_epoch, num_epochs):
        perm = epoch_perm(epoch)
        # Serialized across concurrent trial threads on fragile backends
        # (utils/dispatch.py — the tunnel-wedge mitigation). The epoch
        # batches' host->device transfer — the loop's largest single
        # transfer — rides inside the same hold, and the scalar
        # readbacks sync BEFORE release (jit returns futures; an
        # unsynced exit would let the next thread's traffic overlap
        # this epoch still streaming through the relay).
        step_count = (epoch + 1) * steps_per_epoch
        # Schedule is indexed by optimizer steps (micro-steps // accum).
        opt_steps = (epoch + 1) * max(steps_per_epoch // accum, 1)
        with obs.span("epoch", {"epoch": epoch}), dispatch_lock():
            epoch_key = jax.random.key(fold_seed(seed, "epoch", epoch))
            # Optax schedules are jnp-based — evaluating one is a small
            # device dispatch, so it stays inside the hold (advisor r5:
            # an unlocked eval per epoch is exactly the concurrent
            # multi-thread traffic the serialization exists to prevent).
            lr_now = (
                lr * float(shape_schedule(min(opt_steps, total_steps)))
                if injected
                else float(schedule(min(opt_steps, total_steps)))
            )
            # One whole-epoch slab per epoch by design (streaming is the
            # over-budget path); stage_global = device_put on one process,
            # addressable-slices-only on a spanning mesh — every host
            # gathers the same permutation, so the global batches are
            # IDENTICAL to the single-process run's (the bit-identity
            # contract).
            xb = mh.stage_global(
                x_np[perm].reshape(xb_shape), xb_sharding,
            )
            yb = mh.stage_global(
                y_np[perm].reshape(yb_shape), yb_sharding,
            )
            if audit_donation:
                # Donation audit probes: references to donated inputs,
                # checked for consumption right after the first call —
                # runtime proof the buffer aliases took effect.
                probes = [xb, yb] + jax.tree.leaves(params)[:1] \
                    + jax.tree.leaves(opt_state)[:1]
            # Stamps AFTER staging (the slab transfer is input time, not
            # epoch execute time) and INSIDE the hold — same MFU-clock
            # discipline as tune/trainable.py's resident loop.
            c0 = tracker.thread_seconds()
            t0 = _time.monotonic()
            params, opt_state, batch_stats, train_loss = train_epoch(
                params, opt_state, batch_stats, xb, yb, epoch_key
            )
            metrics = evaluate(params, batch_stats, xv, yv, mask)
            train_loss = float(train_loss)
            metrics = {k: float(v) for k, v in metrics.items()}
            exec_s = max(
                _time.monotonic() - t0
                - (tracker.thread_seconds() - c0),
                1e-9,
            )
            if audit_donation:
                audit_donation = False
                consumed = sum(
                    1 for a in probes
                    if isinstance(a, jax.Array) and a.is_deleted()
                )
                if consumed:
                    get_compile_counters().add(
                        "donation_aliased_buffers", consumed
                    )
        record = {
            "epoch": epoch,
            "train_loss": train_loss,
            "lr": lr_now,
            "steps": step_count,
            "num_devices": len(devices),
            "mesh_shape": dict(mesh_shape),
            **metrics,
        }
        perf_acct.annotate(record, exec_s, device=budget_device)
        if n_procs > 1 and bool(config.get("perf_gang_skew", True)):
            # Per-gang-member skew: allgather each member's epoch wall
            # and name a sustained straggler by PROCESS ID (counter +
            # flight dump — perf/anomaly.py).  One small collective per
            # epoch, device traffic, so it rides the dispatch hold.
            with dispatch_lock():
                stragglers = mh.check_gang_skew(exec_s, label="epoch")
            if stragglers:
                record["gang_stragglers"] = [
                    int(p) for p, _ in stragglers
                ]
        checkpoint = None
        if checkpoint_freq and (epoch + 1) % checkpoint_freq == 0:
            # Checkpoint readback is device traffic too — same hold
            # discipline as the epoch dispatch (utils/dispatch.py).
            # host_snapshot copies fully-addressable leaves and leaves
            # process-SPANNING leaves sharded: each gang member then
            # serializes exactly the shards it holds (ckpt/format.py).
            with dispatch_lock():
                checkpoint = {
                    "params": mh.host_snapshot(params),
                    "opt_state": mh.host_snapshot(opt_state),
                    "batch_stats": mh.host_snapshot(batch_stats),
                    "epoch": epoch,
                }
        session.report(record, checkpoint=checkpoint)

    return None
