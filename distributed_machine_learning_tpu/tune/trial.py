"""Trial state: the unit of work the scheduler and executor reason about.

A Trial owns its sampled config, a monotonically growing result stream (the
per-epoch metric records the reference never produced — it reported once at
trial end, `ray-tune-hpo-regression.py:373`, leaving ASHA inert; SURVEY.md
§3.1), resource requirements, and checkpoint bookkeeping for PBT/fault
recovery.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class TrialStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    # Dispatched but past its progress deadline (liveness.py watchdog): the
    # trial is *probably* wedged but may still be alive.  A beat flips it
    # back to RUNNING (recovery); a kill/requeue follows the ordinary error
    # path.  On resume, STALLED counts as interrupted — requeued from its
    # newest checkpoint like RUNNING.
    STALLED = "STALLED"
    TERMINATED = "TERMINATED"  # finished or early-stopped, successfully
    ERROR = "ERROR"


@dataclass
class Resources:
    """Per-trial resource request, parity with ``resources_per_trial``
    (`ray-tune-hpo-regression.py:475`) translated to TPU terms."""

    devices: int = 1  # TPU cores (or CPU virtual devices in tests)
    cpus: int = 1

    @classmethod
    def parse(cls, spec) -> "Resources":
        if spec is None:
            return cls()
        if isinstance(spec, Resources):
            return spec
        if isinstance(spec, dict):
            return cls(
                devices=int(spec.get("devices", spec.get("tpu", spec.get("gpu", 1)))) or 1,
                cpus=int(spec.get("cpu", 1)),
            )
        raise TypeError(f"Cannot parse resources from {spec!r}")


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    resources: Resources = field(default_factory=Resources)
    status: TrialStatus = TrialStatus.PENDING

    results: List[Dict[str, Any]] = field(default_factory=list)
    error: Optional[str] = None
    num_failures: int = 0

    # Checkpoint/restore bookkeeping (PBT exploit, fault recovery).
    restore_path: Optional[str] = None
    latest_checkpoint: Optional[str] = None
    # Training iteration the latest checkpoint was taken at — PBT uses it to
    # refuse exploiting donor state ahead of a laggard's own progress.
    latest_checkpoint_iteration: int = 0

    # Progress accounting. ``training_iteration`` must mean *restorable
    # progress*, not "reports ever made": a respawned trial restored from an
    # epoch-e checkpoint continues at e+1, so its iteration counter has to
    # rewind with it — otherwise schedulers comparing iterations (PBT's
    # budget gate, ASHA rungs) mix incompatible units after any respawn.
    restore_base: int = 0  # progress at the last (re)start
    reports_since_restart: int = 0
    # Monotone (re)start counter.  Executor events are tagged with the
    # incarnation that produced them so the runner can drop a dead
    # incarnation's late events instead of applying them to a retry.
    incarnation: int = 0

    # Liveness bookkeeping (liveness.py): how many times this trial's
    # dispatch went silent past the progress deadline, and how many of
    # those episodes later produced a beat again ("slow, not dead").
    stall_count: int = 0
    stall_recoveries: int = 0

    # Runtime bookkeeping.  ``started_at`` is the FIRST start (total-runtime
    # accounting); ``restarted_at`` is the current incarnation's start —
    # per-trial time limits measure against it so a retried trial gets a
    # fresh budget instead of being instantly over-limit.
    created_at: float = field(default_factory=time.time)
    started_at: Optional[float] = None
    restarted_at: Optional[float] = None
    finished_at: Optional[float] = None
    stop_requested: bool = False
    pause_requested: bool = False
    assigned_devices: List[Any] = field(default_factory=list)

    @property
    def last_result(self) -> Optional[Dict[str, Any]]:
        return self.results[-1] if self.results else None

    @property
    def training_iteration(self) -> int:
        """Current restorable progress (see field comment above); equals
        ``len(results)`` for a trial that never restored."""
        return self.restore_base + self.reports_since_restart

    def metric_history(self, metric: str) -> List[float]:
        return [r[metric] for r in self.results if metric in r]

    def runtime_s(self) -> float:
        if self.started_at is None:
            return 0.0
        end = self.finished_at or time.time()
        return end - self.started_at

    def incarnation_runtime_s(self) -> float:
        """Runtime of the current (re)start only — the time-limit clock."""
        if self.restarted_at is None:
            return self.runtime_s()
        end = self.finished_at or time.time()
        return end - self.restarted_at

    def __repr__(self) -> str:  # keep logs compact
        return f"Trial({self.trial_id}, {self.status.value}, iters={self.training_iteration})"
