"""Shared pieces of the built-in regression workload.

Single source of truth for the forward-call convention, the jittable
epoch/eval program bodies, and validation padding — used by both the
per-trial trainable (``tune/trainable.py``) and the vmapped population
runner (``tune/vectorized.py``), so a numerics change lands in both paths.

Capability lineage: this is the reference's L2 training loop
(`/root/reference/ray-tune-hpo-regression.py:260-373`) re-shaped for XLA —
an epoch is one ``lax.scan`` program, eval is a padded masked scan with
static shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax


def make_forward(model, flag_name: str, has_bn: bool) -> Callable:
    """Unified apply() over the zoo's two call conventions.

    Returns ``forward(params, batch_stats, x, dropout_key, train) ->
    (preds, new_batch_stats, aux_loss)``.  ``aux_loss`` collects everything
    the model sowed into the ``"moe"`` collection (the MoE load-balance
    terms, already scaled by their coefficient — models/moe.py); it is 0.0
    for dense models and is added to the training objective only.
    """

    from distributed_machine_learning_tpu.models.moe import collect_aux

    def forward(params, batch_stats, x, dropout_key, train: bool):
        vs = {"params": params}
        if has_bn:
            vs["batch_stats"] = batch_stats
        kwargs = {flag_name: (not train) if flag_name == "deterministic" else train}
        rngs = {"dropout": dropout_key} if train else None
        mutable = ["moe"] + (["batch_stats"] if has_bn and train else [])
        out, mut = model.apply(vs, x, rngs=rngs, mutable=mutable, **kwargs)
        new_bs = mut["batch_stats"] if (has_bn and train) else batch_stats
        return out, new_bs, collect_aux(mut)

    return forward


def per_example_losses(preds: jnp.ndarray, targets: jnp.ndarray):
    """Per-example squared error, absolute error, and APE (for masked eval)."""
    se = jnp.mean((preds - targets) ** 2, axis=-1)
    ae = jnp.mean(jnp.abs(preds - targets), axis=-1)
    ape = jnp.mean(jnp.abs(targets - preds) / (jnp.abs(targets) + 1e-8), axis=-1)
    return se, ae, ape


def make_epoch_fn(
    forward: Callable,
    tx: optax.GradientTransformation,
    loss_fn: Callable,
    n_train: int,
    num_batches: int,
    batch_size: int,
) -> Callable:
    """One training epoch as a pure function: shuffle + scan over batches.

    ``epoch(params, opt_state, batch_stats, x_all, y_all, epoch_key) ->
    (params, opt_state, batch_stats, mean_loss)``.  Jit/vmap at the call
    site.
    """

    def epoch(params, opt_state, batch_stats, x_all, y_all, epoch_key):
        perm_key, drop0 = jax.random.split(epoch_key)
        perm = jax.random.permutation(perm_key, n_train)
        perm = perm[: num_batches * batch_size].reshape(num_batches, batch_size)

        def step(carry, idx):
            params, opt_state, batch_stats, key = carry
            key, dkey = jax.random.split(key)
            xb, yb = x_all[idx], y_all[idx]

            def loss_of(p):
                preds, new_bs, aux = forward(p, batch_stats, xb, dkey, train=True)
                return loss_fn(preds.astype(jnp.float32), yb) + aux, new_bs

            (loss, new_bs), grads = jax.value_and_grad(loss_of, has_aux=True)(
                params
            )
            updates, new_opt = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, new_opt, new_bs, key), loss

        (params, opt_state, batch_stats, _), losses = jax.lax.scan(
            step, (params, opt_state, batch_stats, drop0), perm
        )
        return params, opt_state, batch_stats, losses.mean()

    return epoch


def make_chunk_epoch_fn(
    forward: Callable,
    tx: optax.GradientTransformation,
    loss_fn: Callable,
) -> Callable:
    """One streaming CHUNK of an epoch as a pure function (out-of-core
    path, ``data/pipeline.py``): a scan over a staged slab of pre-gathered
    batches.

    ``chunk(params, opt_state, batch_stats, key, xb, yb) -> (params,
    opt_state, batch_stats, key, losses)`` where ``xb``/``yb`` are
    ``[rows, batch_size, ...]`` slabs.  The step body is kept IDENTICAL to
    :func:`make_epoch_fn`'s (same split order, same loss closure, same
    update sequence) and the PRNG key rides the carry ACROSS chunk calls,
    so a streaming epoch executes bit-for-bit the computation the resident
    epoch program executes — the host gathers the batches the resident
    program's in-program gather would have produced (same permutation:
    threefry draws are identical eager vs jit), and the chunk boundary is
    invisible to the numerics.  Jit at the call site with
    ``donate_argnums`` covering state AND the slab (the consumed chunk's
    buffers free at the boundary — the ring's memory bound depends on it).
    """

    def chunk(params, opt_state, batch_stats, key, xb, yb):
        def step(carry, batch):
            params, opt_state, batch_stats, key = carry
            key, dkey = jax.random.split(key)
            xb_, yb_ = batch

            def loss_of(p):
                preds, new_bs, aux = forward(p, batch_stats, xb_, dkey,
                                             train=True)
                return loss_fn(preds.astype(jnp.float32), yb_) + aux, new_bs

            (loss, new_bs), grads = jax.value_and_grad(loss_of, has_aux=True)(
                params
            )
            updates, new_opt = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, new_opt, new_bs, key), loss

        (params, opt_state, batch_stats, key), losses = jax.lax.scan(
            step, (params, opt_state, batch_stats, key), (xb, yb)
        )
        return params, opt_state, batch_stats, key, losses

    return chunk


def make_indexed_epoch_fn(
    forward: Callable,
    tx: optax.GradientTransformation,
    loss_fn: Callable,
) -> Callable:
    """The SHARDED trainable's fused epoch body (tune/trainable_sharded.py):
    a scan over pre-gathered ``[num_batches, global_batch, ...]`` slabs
    whose per-step dropout key is ``fold_in(epoch_key, i)`` on an integer
    step counter riding the carry — the indexed twin of
    :func:`make_epoch_fn` (which draws keys by splitting along the carry).

    ``epoch(params, opt_state, batch_stats, xb, yb, epoch_key) ->
    (params, opt_state, batch_stats, mean_loss)``.  Jit at the call site
    with donation + in/out shardings; extracted here so the jaxlint
    donation/hygiene audits (analysis/jaxlint/) lower the EXACT program
    the trainable runs, not a reimplementation that could drift.
    """

    def epoch(params, opt_state, batch_stats, xb, yb, epoch_key):
        def step(carry, batch):
            params, opt_state, batch_stats, i = carry
            x, y = batch
            key = jax.random.fold_in(epoch_key, i)

            def loss_of(p):
                preds, new_bs, aux = forward(p, batch_stats, x, key, True)
                return loss_fn(preds.astype(jnp.float32), y) + aux, new_bs

            (loss, new_bs), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, new_bs, i + 1), loss

        (params, opt_state, batch_stats, _), losses = jax.lax.scan(
            step, (params, opt_state, batch_stats, jnp.int32(0)), (xb, yb)
        )
        return params, opt_state, batch_stats, losses.mean()

    return epoch


def make_indexed_chunk_fn(
    forward: Callable,
    tx: optax.GradientTransformation,
    loss_fn: Callable,
) -> Callable:
    """The sharded trainable's streaming CHUNK body: the same step body as
    :func:`make_indexed_epoch_fn` scanned over a staged slab, with the
    global batch counter entering as ``i0`` so ``fold_in(epoch_key, i)``
    matches the resident program bit for bit across chunk boundaries.

    ``chunk(params, opt_state, batch_stats, i0, xb, yb, epoch_key) ->
    (params, opt_state, batch_stats, losses)``.  Jit at the call site.
    """

    def chunk(params, opt_state, batch_stats, i0, xb, yb, epoch_key):
        def step(carry, batch):
            params, opt_state, batch_stats, i = carry
            x, y = batch
            key = jax.random.fold_in(epoch_key, i)

            def loss_of(p):
                preds, new_bs, aux = forward(p, batch_stats, x, key, True)
                return loss_fn(preds.astype(jnp.float32), y) + aux, new_bs

            (loss, new_bs), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, new_bs, i + 1), loss

        (params, opt_state, batch_stats, _), losses = jax.lax.scan(
            step, (params, opt_state, batch_stats, i0), (xb, yb)
        )
        return params, opt_state, batch_stats, losses

    return chunk


def make_chunk_eval_fn(forward: Callable) -> Callable:
    """Masked eval over ONE streamed chunk of validation blocks: ``(params,
    batch_stats, xb, yb, mb) -> (se_sum, ae_sum, ape_sum, hub_sum, count)``
    partial sums the host accumulates across chunks before forming the
    :func:`make_eval_fn` metric set (same per-example terms; only the
    cross-block summation moves to the host)."""

    def evaluate_chunk(params, batch_stats, xb, yb, mb):
        def step(_, batch):
            x, y, m = batch
            preds, _, _ = forward(
                params, batch_stats, x, jax.random.key(0), train=False
            )
            preds = preds.astype(jnp.float32)
            se, ae, ape = per_example_losses(preds, y)
            hub = jnp.mean(optax.huber_loss(preds, y, delta=1.0), axis=-1)
            return None, (
                (se * m).sum(), (ae * m).sum(), (ape * m).sum(),
                (hub * m).sum(),
            )

        _, (se, ae, ape, hub) = jax.lax.scan(step, None, (xb, yb, mb))
        return se.sum(), ae.sum(), ape.sum(), hub.sum(), mb.sum()

    return evaluate_chunk


def eval_metrics_from_sums(
    loss_name: str, se: float, ae: float, ape: float, hub: float, count: float
) -> Dict[str, float]:
    """:func:`make_eval_fn`'s metric dict from host-accumulated partial
    sums (the streamed-validation path)."""
    count = max(float(count), 1e-9)
    mse = se / count
    mae = ae / count
    mape = 100.0 * ape / count
    huber = hub / count
    rmse = float(np.sqrt(mse))
    by_name = {
        "mse": mse, "mae": mae, "mape": mape, "huber": huber, "rmse": rmse,
    }
    return {
        "validation_loss": float(by_name.get(loss_name, mse)),
        "validation_mse": float(mse),
        "validation_rmse": float(rmse),
        "validation_mae": float(mae),
        "validation_mape": float(mape),
    }


# Metric names make_eval_fn produces (plus "train_loss" from the epoch fn):
# the keys a compiled PBT generation scan can rank on.  Kept next to the
# eval body so a metric rename cannot silently desynchronize the validator.
EVAL_METRIC_KEYS = (
    "validation_loss", "validation_mse", "validation_rmse",
    "validation_mae", "validation_mape",
)


def make_eval_fn(
    forward: Callable, loss_name: str, n_blocks: int, eval_bs: int
) -> Callable:
    """Masked blockwise eval: ``(params, batch_stats, x, y, mask) ->
    {validation_loss, _mse, _rmse, _mae, _mape}``.  Jit/vmap at the call
    site."""

    def evaluate(params, batch_stats, x_all, y_all, mask):
        xb = x_all.reshape(n_blocks, eval_bs, *x_all.shape[1:])
        yb = y_all.reshape(n_blocks, eval_bs, *y_all.shape[1:])
        mb = mask.reshape(n_blocks, eval_bs)

        def step(_, batch):
            x, y, m = batch
            preds, _, _ = forward(
                params, batch_stats, x, jax.random.key(0), train=False
            )
            preds = preds.astype(jnp.float32)
            se, ae, ape = per_example_losses(preds, y)
            hub = jnp.mean(optax.huber_loss(preds, y, delta=1.0), axis=-1)
            return None, (
                (se * m).sum(), (ae * m).sum(), (ape * m).sum(), (hub * m).sum()
            )

        _, (se, ae, ape, hub) = jax.lax.scan(step, None, (xb, yb, mb))
        count = mask.sum()
        mse = se.sum() / count
        mae = ae.sum() / count
        mape = 100.0 * ape.sum() / count
        huber = hub.sum() / count
        rmse = jnp.sqrt(mse)
        by_name = {
            "mse": mse, "mae": mae, "mape": mape, "huber": huber, "rmse": rmse,
        }
        return {
            "validation_loss": by_name.get(loss_name, mse),
            "validation_mse": mse,
            "validation_rmse": rmse,
            "validation_mae": mae,
            "validation_mape": mape,
        }

    return evaluate


@dataclass
class StagedData:
    """Device-resident dataset + padded validation block layout."""

    x_train: jnp.ndarray
    y_train: jnp.ndarray
    x_val: jnp.ndarray
    y_val: jnp.ndarray
    val_mask: jnp.ndarray
    n_train: int
    num_batches: int
    batch_size: int
    n_val_blocks: int
    eval_bs: int


def stage_data(
    train_data, val_data, batch_size: int, compute_dtype
) -> StagedData:
    """Stage both splits to device once; pad validation to whole blocks."""
    n_train = len(train_data)
    batch_size = int(min(batch_size, n_train))
    num_batches = max(n_train // batch_size, 1)

    n_val = len(val_data)
    eval_bs = int(min(max(batch_size, 1), n_val))
    n_val_pad = -(-n_val // eval_bs) * eval_bs
    pad = n_val_pad - n_val

    x_val = (
        np.concatenate(
            [val_data.x, np.zeros((pad, *val_data.x.shape[1:]), val_data.x.dtype)]
        )
        if pad
        else val_data.x
    )
    y_val = (
        np.concatenate(
            [val_data.y, np.zeros((pad, *val_data.y.shape[1:]), val_data.y.dtype)]
        )
        if pad
        else val_data.y
    )
    return StagedData(
        x_train=jnp.asarray(train_data.x, dtype=compute_dtype),
        y_train=jnp.asarray(train_data.y, dtype=jnp.float32),
        x_val=jnp.asarray(x_val, dtype=compute_dtype),
        y_val=jnp.asarray(y_val, dtype=jnp.float32),
        val_mask=jnp.asarray(
            np.concatenate([np.ones(n_val, np.float32), np.zeros(pad, np.float32)])
        ),
        n_train=n_train,
        num_batches=num_batches,
        batch_size=batch_size,
        n_val_blocks=n_val_pad // eval_bs,
        eval_bs=eval_bs,
    )


def make_pbt_generation_fn(
    epoch_fn: Callable,
    eval_fn: Callable,
    spec: Dict[str, Any],
    *,
    interval: int,
    num_epochs_total: int,
    metric: str,
    n_rows: int,
    n_valid: int,
):
    """The whole-PBT-sweep program body: a ``lax.scan`` over generations.

    Each generation = ``interval`` epochs of the fused per-row epoch scan
    (vmapped over the population) -> in-program quantile ranking over the
    per-row metric -> exploit as gather (bottom-quantile rows adopt
    top-quantile rows' params AND optimizer state) -> explore as
    PRNG-driven per-row perturbation of the injected lr/wd (per-row keys
    travel with their rows; a lagger keeps its own identity/seed).  This
    is the Podracer "Anakin" shape applied to HPO: the host dispatches
    once per generation CHUNK, not once per perturbation.

    Every decision op is chosen for bit-parity with
    ``schedulers.pbt.reference_generation_step``: threefry draws (jit ==
    eager), stable lexsort ranking, IEEE f32 multiply/clip, and grid-gather
    resampling (no transcendentals — XLA's fused exp is not bit-stable vs
    eager).  Per-generation decisions come back as stacked scan outputs
    (scores, src, new lr/wd, exploited) so the driver reconstructs trial
    records, ``pbt_exploited_from`` notes, and TB streams exactly as rich
    as the host-boundary path.

    Returns ``run(params, opt_state, batch_stats, base_keys, pbt_keys,
    lr, wd, x, y, xv, yv, mask, gen_ids, obj_scale)`` for the caller to
    jit with ``donate_argnums=(0, 1, 2)``.  ``obj_scale`` is the host-
    measured objective scalarization factor (latency/param terms — a
    constant row multiplier, so in-population ranking is unchanged but
    emitted scores are the deployability-scalarized objective).
    """
    if metric != "train_loss" and metric not in EVAL_METRIC_KEYS:
        raise ValueError(
            f"PBT metric {metric!r} is not produced by this trainable "
            f"(have: train_loss, {', '.join(EVAL_METRIC_KEYS)})"
        )
    from distributed_machine_learning_tpu.ops.optimizers import (
        set_injected_hyperparams,
    )
    from distributed_machine_learning_tpu.tune.schedulers.pbt import (
        generation_draw_count,
        resample_grid,
    )

    sign = np.float32(spec["sign"])
    q = max(1, int(n_valid * spec["quantile"]))
    lag_start = max(q, n_valid - q)
    exploit_possible = n_valid >= 4 and lag_start < n_valid
    n_draws = generation_draw_count(spec)
    n_factors = len(spec["factors"])
    factors_c = np.asarray(spec["factors"], np.float32)
    grids = {e["key"]: resample_grid(e, spec["grid_points"])
             for e in spec["specs"]}
    invalid_c = (np.arange(n_rows) >= n_valid).astype(np.int8)
    resample_p = np.float32(spec["resample_p"])

    def exploit_explore(scores, lr, wd, draws, fire):
        if not exploit_possible:
            return (
                jnp.arange(n_rows),
                lr, wd,
                jnp.zeros((n_rows,), bool),
            )
        rank = jnp.where(
            jnp.isfinite(scores * sign), scores * sign, jnp.inf
        ).astype(jnp.float32)
        # Stable three-key sort: valid rows first, best score first, ties
        # by row index — identical to the reference's sorted() tuple key.
        order = jnp.lexsort((jnp.arange(n_rows), rank, invalid_c))
        donors = order[:q]
        donor_ok = jnp.isfinite(rank[donors])
        n_ok = donor_ok.sum()
        enabled = fire & jnp.isfinite(rank[order[0]]) & (n_ok > 0)
        # Finite donors, original donor order first (stable partition).
        fd = donors[jnp.lexsort((jnp.arange(q),
                                 (~donor_ok).astype(jnp.int8)))]
        laggers = order[lag_start:n_valid]
        u0 = draws[laggers, 0]
        d_idx = jnp.clip(
            (u0 * n_ok.astype(jnp.float32)).astype(jnp.int32),
            0, jnp.maximum(n_ok - 1, 0),
        )
        donor_rows = fd[d_idx]
        src = jnp.arange(n_rows).at[laggers].set(
            jnp.where(enabled, donor_rows, laggers)
        )
        exploited = jnp.zeros((n_rows,), bool).at[laggers].set(enabled)
        vals = {"learning_rate": lr, "weight_decay": wd}
        out = {}
        for m, e in enumerate(spec["specs"]):
            base = vals[e["key"]]
            donor_v = base[src]
            u_res = draws[:, 1 + 2 * m]
            u_val = draws[:, 2 + 2 * m]
            grid = jnp.asarray(grids[e["key"]])
            gi = jnp.clip(
                (u_val * np.float32(len(grids[e["key"]]))).astype(jnp.int32),
                0, len(grids[e["key"]]) - 1,
            )
            resampled = grid[gi]
            fi = jnp.clip(
                (u_val * np.float32(n_factors)).astype(jnp.int32),
                0, n_factors - 1,
            )
            stepped = jnp.clip(
                donor_v * jnp.asarray(factors_c)[fi],
                np.float32(e["lo"]), np.float32(e["hi"]),
            )
            cand = jnp.where(u_res < resample_p, resampled, stepped)
            out[e["key"]] = jnp.where(exploited, cand, base)
        for key in ("learning_rate", "weight_decay"):
            if key not in spec["keys"]:
                # Exploit copies the donor's whole config: an unmutated
                # hyperparam still adopts the donor's value.
                out[key] = jnp.where(exploited, vals[key][src], vals[key])
        return src, out["learning_rate"], out["weight_decay"], exploited

    def run(params, opt_state, batch_stats, base_keys, pbt_keys, lr, wd,
            x, y, xv, yv, mask, gen_ids, obj_scale):
        def one_row(p, o, b, key, epoch_ids):
            def ebody(carry, e):
                p, o, b = carry
                k = jax.random.fold_in(key, e)
                p, o, b, tl = epoch_fn(p, o, b, x, y, k)
                m = eval_fn(p, b, xv, yv, mask)
                return (p, o, b), (tl, m)

            (p, o, b), (tls, ms) = jax.lax.scan(ebody, (p, o, b), epoch_ids)
            return p, o, b, tls, ms

        v_epochs = jax.vmap(one_row, in_axes=(0, 0, 0, 0, None))

        def gen_body(carry, gen):
            p, o, b, lr, wd = carry
            epoch_ids = gen * interval + jnp.arange(interval)
            p, o, b, tls, ms = v_epochs(p, o, b, base_keys, epoch_ids)
            sel = tls if metric == "train_loss" else ms[metric]
            scores = sel[:, -1] * obj_scale
            draws = jax.vmap(
                lambda k2: jax.random.uniform(
                    jax.random.fold_in(k2, gen), (n_draws,)
                )
            )(pbt_keys)
            # No perturbation after the sweep's final epoch (matching the
            # boundary path's `epoch0 < num_epochs` guard).
            fire = ((gen + 1) * interval) < num_epochs_total
            src, new_lr, new_wd, exploited = exploit_explore(
                scores, lr, wd, draws, fire
            )
            p, o, b = jax.tree.map(lambda a: a[src], (p, o, b))
            o = set_injected_hyperparams(o, new_lr, new_wd)
            return (p, o, b, new_lr, new_wd), (
                tls, ms, scores, src, new_lr, new_wd, exploited
            )

        (p, o, b, lr, wd), ys = jax.lax.scan(
            gen_body, (params, opt_state, batch_stats, lr, wd), gen_ids
        )
        return p, o, b, lr, wd, ys

    return run


def _call_lacks_deterministic(model) -> bool:
    """Whether ``model.__call__`` provably has no ``deterministic``
    parameter (explicit signature, no ``**kwargs``).  Inconclusive
    signatures return False — the caller then re-raises rather than
    guessing."""
    import inspect

    try:
        params = inspect.signature(type(model).__call__).parameters
    except (TypeError, ValueError):
        return False
    if any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    ):
        return False
    return "deterministic" not in params


def detect_call_convention(model, sample_x, init_rngs=None,
                           abstract=False):
    """Init the model and learn (variables, train-flag kwarg name).

    The init is jitted: eager ``model.init`` dispatches hundreds of tiny ops
    one by one, which is pathological on a remote/tunneled TPU backend; one
    compiled executable makes trial startup near-constant.  The rng dict is
    a traced ARGUMENT, so trials with different ``init_rngs`` (per-trial
    init diversity — the reference's torch trials each start from their own
    random init) share one compiled init program.

    ``abstract=True`` runs the probe under ``jax.eval_shape`` instead:
    ``variables`` come back as ShapeDtypeStructs and NOTHING is allocated —
    the sharded trainable uses this to derive partition-rule shardings
    BEFORE the real init, so an over-HBM flagship's params are born sharded
    (a concrete unsharded init would be the OOM).
    """
    rng = init_rngs or {
        "params": jax.random.key(0), "dropout": jax.random.key(1)
    }

    def run(f):
        if abstract:
            return jax.eval_shape(f, rng, sample_x)
        return jax.jit(f)(rng, sample_x)

    try:
        variables = run(lambda r, x: model.init(r, x, deterministic=True))
        return variables, "deterministic"
    except TypeError as exc:
        # Only a rejected 'deterministic' kwarg means "wrong convention".
        # Any other TypeError (e.g. a positional-encoding broadcast
        # mismatch when max_seq_length < the data's window length) is the
        # model's REAL failure: retrying with train= would just fail on
        # the unknown kwarg and mask the actual error behind a confusing
        # "unexpected keyword argument 'train'".  The match is deliberately
        # loose — any wording that names the flag as an argument problem
        # (CPython's current phrasing, a future rewording, a wrapper's
        # re-raise) counts — and a signature probe covers a TypeError that
        # names neither (a __call__ provably without the flag cannot have
        # run its body, so the error can only be the kwarg rejection).
        msg = str(exc)
        mentions_flag = "deterministic" in msg and (
            "argument" in msg or "keyword" in msg
        )
        if not mentions_flag and not _call_lacks_deterministic(model):
            raise
        variables = run(lambda r, x: model.init(r, x, train=False))
        return variables, "train"
