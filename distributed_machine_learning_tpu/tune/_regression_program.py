"""Shared pieces of the built-in regression workload.

Single source of truth for the forward-call convention, the jittable
epoch/eval program bodies, and validation padding — used by both the
per-trial trainable (``tune/trainable.py``) and the vmapped population
runner (``tune/vectorized.py``), so a numerics change lands in both paths.

Capability lineage: this is the reference's L2 training loop
(`/root/reference/ray-tune-hpo-regression.py:260-373`) re-shaped for XLA —
an epoch is one ``lax.scan`` program, eval is a padded masked scan with
static shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax


def make_forward(model, flag_name: str, has_bn: bool) -> Callable:
    """Unified apply() over the zoo's two call conventions.

    Returns ``forward(params, batch_stats, x, dropout_key, train) ->
    (preds, new_batch_stats, aux_loss)``.  ``aux_loss`` collects everything
    the model sowed into the ``"moe"`` collection (the MoE load-balance
    terms, already scaled by their coefficient — models/moe.py); it is 0.0
    for dense models and is added to the training objective only.
    """

    from distributed_machine_learning_tpu.models.moe import collect_aux

    def forward(params, batch_stats, x, dropout_key, train: bool):
        vs = {"params": params}
        if has_bn:
            vs["batch_stats"] = batch_stats
        kwargs = {flag_name: (not train) if flag_name == "deterministic" else train}
        rngs = {"dropout": dropout_key} if train else None
        mutable = ["moe"] + (["batch_stats"] if has_bn and train else [])
        out, mut = model.apply(vs, x, rngs=rngs, mutable=mutable, **kwargs)
        new_bs = mut["batch_stats"] if (has_bn and train) else batch_stats
        return out, new_bs, collect_aux(mut)

    return forward


def per_example_losses(preds: jnp.ndarray, targets: jnp.ndarray):
    """Per-example squared error, absolute error, and APE (for masked eval)."""
    se = jnp.mean((preds - targets) ** 2, axis=-1)
    ae = jnp.mean(jnp.abs(preds - targets), axis=-1)
    ape = jnp.mean(jnp.abs(targets - preds) / (jnp.abs(targets) + 1e-8), axis=-1)
    return se, ae, ape


def make_epoch_fn(
    forward: Callable,
    tx: optax.GradientTransformation,
    loss_fn: Callable,
    n_train: int,
    num_batches: int,
    batch_size: int,
) -> Callable:
    """One training epoch as a pure function: shuffle + scan over batches.

    ``epoch(params, opt_state, batch_stats, x_all, y_all, epoch_key) ->
    (params, opt_state, batch_stats, mean_loss)``.  Jit/vmap at the call
    site.
    """

    def epoch(params, opt_state, batch_stats, x_all, y_all, epoch_key):
        perm_key, drop0 = jax.random.split(epoch_key)
        perm = jax.random.permutation(perm_key, n_train)
        perm = perm[: num_batches * batch_size].reshape(num_batches, batch_size)

        def step(carry, idx):
            params, opt_state, batch_stats, key = carry
            key, dkey = jax.random.split(key)
            xb, yb = x_all[idx], y_all[idx]

            def loss_of(p):
                preds, new_bs, aux = forward(p, batch_stats, xb, dkey, train=True)
                return loss_fn(preds.astype(jnp.float32), yb) + aux, new_bs

            (loss, new_bs), grads = jax.value_and_grad(loss_of, has_aux=True)(
                params
            )
            updates, new_opt = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, new_opt, new_bs, key), loss

        (params, opt_state, batch_stats, _), losses = jax.lax.scan(
            step, (params, opt_state, batch_stats, drop0), perm
        )
        return params, opt_state, batch_stats, losses.mean()

    return epoch


def make_eval_fn(
    forward: Callable, loss_name: str, n_blocks: int, eval_bs: int
) -> Callable:
    """Masked blockwise eval: ``(params, batch_stats, x, y, mask) ->
    {validation_loss, _mse, _rmse, _mae, _mape}``.  Jit/vmap at the call
    site."""

    def evaluate(params, batch_stats, x_all, y_all, mask):
        xb = x_all.reshape(n_blocks, eval_bs, *x_all.shape[1:])
        yb = y_all.reshape(n_blocks, eval_bs, *y_all.shape[1:])
        mb = mask.reshape(n_blocks, eval_bs)

        def step(_, batch):
            x, y, m = batch
            preds, _, _ = forward(
                params, batch_stats, x, jax.random.key(0), train=False
            )
            preds = preds.astype(jnp.float32)
            se, ae, ape = per_example_losses(preds, y)
            hub = jnp.mean(optax.huber_loss(preds, y, delta=1.0), axis=-1)
            return None, (
                (se * m).sum(), (ae * m).sum(), (ape * m).sum(), (hub * m).sum()
            )

        _, (se, ae, ape, hub) = jax.lax.scan(step, None, (xb, yb, mb))
        count = mask.sum()
        mse = se.sum() / count
        mae = ae.sum() / count
        mape = 100.0 * ape.sum() / count
        huber = hub.sum() / count
        rmse = jnp.sqrt(mse)
        by_name = {
            "mse": mse, "mae": mae, "mape": mape, "huber": huber, "rmse": rmse,
        }
        return {
            "validation_loss": by_name.get(loss_name, mse),
            "validation_mse": mse,
            "validation_rmse": rmse,
            "validation_mae": mae,
            "validation_mape": mape,
        }

    return evaluate


@dataclass
class StagedData:
    """Device-resident dataset + padded validation block layout."""

    x_train: jnp.ndarray
    y_train: jnp.ndarray
    x_val: jnp.ndarray
    y_val: jnp.ndarray
    val_mask: jnp.ndarray
    n_train: int
    num_batches: int
    batch_size: int
    n_val_blocks: int
    eval_bs: int


def stage_data(
    train_data, val_data, batch_size: int, compute_dtype
) -> StagedData:
    """Stage both splits to device once; pad validation to whole blocks."""
    n_train = len(train_data)
    batch_size = int(min(batch_size, n_train))
    num_batches = max(n_train // batch_size, 1)

    n_val = len(val_data)
    eval_bs = int(min(max(batch_size, 1), n_val))
    n_val_pad = -(-n_val // eval_bs) * eval_bs
    pad = n_val_pad - n_val

    x_val = (
        np.concatenate(
            [val_data.x, np.zeros((pad, *val_data.x.shape[1:]), val_data.x.dtype)]
        )
        if pad
        else val_data.x
    )
    y_val = (
        np.concatenate(
            [val_data.y, np.zeros((pad, *val_data.y.shape[1:]), val_data.y.dtype)]
        )
        if pad
        else val_data.y
    )
    return StagedData(
        x_train=jnp.asarray(train_data.x, dtype=compute_dtype),
        y_train=jnp.asarray(train_data.y, dtype=jnp.float32),
        x_val=jnp.asarray(x_val, dtype=compute_dtype),
        y_val=jnp.asarray(y_val, dtype=jnp.float32),
        val_mask=jnp.asarray(
            np.concatenate([np.ones(n_val, np.float32), np.zeros(pad, np.float32)])
        ),
        n_train=n_train,
        num_batches=num_batches,
        batch_size=batch_size,
        n_val_blocks=n_val_pad // eval_bs,
        eval_bs=eval_bs,
    )


def _call_lacks_deterministic(model) -> bool:
    """Whether ``model.__call__`` provably has no ``deterministic``
    parameter (explicit signature, no ``**kwargs``).  Inconclusive
    signatures return False — the caller then re-raises rather than
    guessing."""
    import inspect

    try:
        params = inspect.signature(type(model).__call__).parameters
    except (TypeError, ValueError):
        return False
    if any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    ):
        return False
    return "deterministic" not in params


def detect_call_convention(model, sample_x, init_rngs=None,
                           abstract=False):
    """Init the model and learn (variables, train-flag kwarg name).

    The init is jitted: eager ``model.init`` dispatches hundreds of tiny ops
    one by one, which is pathological on a remote/tunneled TPU backend; one
    compiled executable makes trial startup near-constant.  The rng dict is
    a traced ARGUMENT, so trials with different ``init_rngs`` (per-trial
    init diversity — the reference's torch trials each start from their own
    random init) share one compiled init program.

    ``abstract=True`` runs the probe under ``jax.eval_shape`` instead:
    ``variables`` come back as ShapeDtypeStructs and NOTHING is allocated —
    the sharded trainable uses this to derive partition-rule shardings
    BEFORE the real init, so an over-HBM flagship's params are born sharded
    (a concrete unsharded init would be the OOM).
    """
    rng = init_rngs or {
        "params": jax.random.key(0), "dropout": jax.random.key(1)
    }

    def run(f):
        if abstract:
            return jax.eval_shape(f, rng, sample_x)
        return jax.jit(f)(rng, sample_x)

    try:
        variables = run(lambda r, x: model.init(r, x, deterministic=True))
        return variables, "deterministic"
    except TypeError as exc:
        # Only a rejected 'deterministic' kwarg means "wrong convention".
        # Any other TypeError (e.g. a positional-encoding broadcast
        # mismatch when max_seq_length < the data's window length) is the
        # model's REAL failure: retrying with train= would just fail on
        # the unknown kwarg and mask the actual error behind a confusing
        # "unexpected keyword argument 'train'".  The match is deliberately
        # loose — any wording that names the flag as an argument problem
        # (CPython's current phrasing, a future rewording, a wrapper's
        # re-raise) counts — and a signature probe covers a TypeError that
        # names neither (a __call__ provably without the flag cannot have
        # run its body, so the error can only be the kwarg rejection).
        msg = str(exc)
        mentions_flag = "deterministic" in msg and (
            "argument" in msg or "keyword" in msg
        )
        if not mentions_flag and not _call_lacks_deterministic(model):
            raise
        variables = run(lambda r, x: model.init(r, x, train=False))
        return variables, "train"
