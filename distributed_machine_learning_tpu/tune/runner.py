"""The tune.run() driver loop.

Native, single-process replacement for ``tune.run(...)``
(`ray-tune-hpo-regression.py:469-478`): samples trial configs from the search
algorithm, leases TPU cores from the DeviceManager, streams per-epoch results
through the scheduler, early-stops / requeues / retries, persists everything to
the experiment store, and returns an ExperimentAnalysis with ``best_config``
(`:480`).

Event-driven: trial threads block in ``report`` until this loop answers, so
all scheduler/searcher state is mutated from exactly one thread.
"""

from __future__ import annotations

import queue
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Union

from distributed_machine_learning_tpu.tune.executor import (
    DeviceManager,
    ProcessTrialExecutor,
    ThreadTrialExecutor,
)
from distributed_machine_learning_tpu.tune.experiment import (
    ExperimentAnalysis,
    ExperimentStore,
)
from distributed_machine_learning_tpu.tune._driver import (
    TrialLifecycle,
    scheduler_debug_block,
)
from distributed_machine_learning_tpu.tune.schedulers.base import (
    FIFOScheduler,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.search.base import (
    RandomSearch,
    Searcher,
    maybe_warm_start,
)
from distributed_machine_learning_tpu.tune.search_space import SearchSpace
from distributed_machine_learning_tpu.tune.trial import (
    Resources,
    Trial,
    TrialStatus,
)

DEFAULT_STORAGE = "~/dml_tpu_results"


def _validate_resume(storage_path: str, name: Optional[str]) -> None:
    """Shared resume precondition for both drivers: an explicit name whose
    experiment directory actually exists — a typo'd name must not silently
    start (and pay for) a fresh experiment while claiming to resume."""
    import os

    if not name:
        raise ValueError(
            "resume=True needs the explicit experiment `name` to resume"
        )
    root = ExperimentStore.root_for(storage_path, name)
    if not os.path.isdir(root):
        raise FileNotFoundError(
            f"resume=True but no experiment directory at {root}"
        )


def run(
    trainable: Callable,
    param_space: Union[Dict[str, Any], SearchSpace],
    *,
    metric: str,
    mode: str = "min",
    num_samples: int = 10,
    scheduler: Optional[TrialScheduler] = None,
    search_alg: Optional[Searcher] = None,
    resources_per_trial: Optional[Dict[str, int]] = None,
    mesh_shape: Optional[Dict[str, int]] = None,
    input_mode: Optional[str] = None,
    max_concurrent: Optional[int] = None,
    storage_path: str = DEFAULT_STORAGE,
    name: Optional[str] = None,
    seed: int = 0,
    max_failures: int = 0,
    stop=None,
    time_budget_s: Optional[float] = None,
    devices: Optional[List] = None,
    verbose: int = 1,
    callbacks: Optional[List] = None,
    keep_checkpoints_num: int = 0,
    checkpoint_storage: Optional[str] = None,
    checkpoint_format: str = "msgpack",
    compile_cache_dir: Optional[str] = "auto",
    time_limit_per_trial_s: Optional[float] = None,
    trial_executor: str = "thread",
    prewarm_runners: int = 0,
    resume: Union[bool, str] = False,
    points_to_evaluate: Optional[List[Dict[str, Any]]] = None,
    progress_deadline_s: Optional[float] = None,
    progress_grace_s: Optional[float] = None,
    trace: bool = False,
    trace_profile_trials: int = 0,
) -> ExperimentAnalysis:
    """Run an HPO experiment; see module docstring.

    ``points_to_evaluate``: configs (possibly partial — missing keys are
    sampled) run as the first trials before the searcher proposes its own;
    model-based searchers observe their results (Ray's knob of the same
    name).

    ``mesh_shape``: sweep-wide 2-D (or N-D) device mesh per trial, e.g.
    ``{"dp": 2, "tp": 4}`` — stamped into every sampled config (a config
    that carries its own ``mesh_shape`` wins) and, when
    ``resources_per_trial`` is omitted, the per-trial device lease
    defaults to the mesh's total size, so
    ``tune.run(trainable, space, mesh_shape={"dp": 2, "tp": 4})`` leases
    8 devices per trial and the sharded trainable builds the mesh from
    its model family's partition rules (``models/partition_rules.py``).
    ``input_mode``: sweep-wide data staging mode stamped into every sampled
    config (a config carrying its own ``input_mode`` wins) — ``"resident"``
    (HBM-resident epochs; raises when the staged dataset exceeds the
    device budget), ``"streaming"`` (the out-of-core prefetch ring,
    ``data/pipeline.py``), or ``"auto"`` (the default: streaming engages
    when the dataset exceeds ``streaming_engage_fraction`` of the budget).
    Streaming runs publish the ``host_input`` counter block
    (prefetch hits, producer/consumer waits, overlap efficiency) into
    ``experiment_state.json`` and TensorBoard ``host_input/*``.
    ``stop``: dict of result-key -> threshold (a trial stops once any key's
    reported value reaches it, e.g. ``{"training_iteration": 20}``), a
    callable ``(trial_id, result) -> bool``, or a ``tune.Stopper``
    (``TrialPlateauStopper``, ``MaximumIterationStopper`` —
    tune/stoppers.py).
    ``max_failures``: per-trial retry budget; retries restore from the trial's
    latest checkpoint when one exists (preemption tolerance, SURVEY.md §5).
    ``keep_checkpoints_num``: retention — keep only the newest k checkpoints
    per trial (0 = keep all); checkpoints referenced by a pending PBT exploit
    or retry are never pruned.
    ``checkpoint_storage``: alternate root for checkpoints (``gs://...`` for
    shared pod storage, ``mem://...`` in tests); metrics stay local.
    ``checkpoint_format``: ``"msgpack"`` (legacy single-blob flax msgpack,
    the default and what existing experiment directories hold) or
    ``"sharded"`` (the ``ckpt/`` chunked format: per-shard files + JSON
    index + atomic COMMIT marker — async-friendly and restorable onto a
    different mesh/device count).  Restores handle both regardless, so an
    experiment can be resumed across the switch; save/restore wall, bytes,
    and async-overlap counters land in
    ``experiment_state.json["checkpoint"]`` and TensorBoard either way.
    ``compile_cache_dir``: persistent XLA compile-cache directory ("auto" =
    ``$DML_TPU_COMPILE_CACHE`` or ``~/.cache/dml_tpu/xla_cache``; None
    disables).  The framework owns compile-time amortization (SURVEY.md §7):
    identical-architecture trials skip XLA backend compilation, and every
    result record carries ``compile_time_s`` / ``compile_cache_hits``.
    ``time_limit_per_trial_s``: per-trial wall-clock budget.  Enforced softly
    at every report boundary (both executors), and enforced HARD — SIGTERM,
    then SIGKILL — for trials that stop reporting (a wedged jit, a stuck
    epoch loop) when ``trial_executor="process"``.  A killed trial follows
    the normal error path: retried within ``max_failures`` (restoring its
    latest checkpoint) or marked ERROR, and its devices are re-leased either
    way.
    ``trial_executor``: "thread" (default; lowest overhead, no preemption) or
    "process" (one OS process per trial with per-process device visibility;
    requires picklable trainables).
    ``prewarm_runners``: with ``trial_executor="process"``, keep this many
    PRE-WARMED runner children pooled: spawned before any trial is
    assigned, they front-load jax import + device enumeration + compile-
    cache attach, so dispatch-to-first-step latency collapses to frame
    parsing.  During scheduler think-time the runner also asks an idle
    warm child to PRE-COMPILE the next pending trial's program (it stops
    at the first report boundary), so a cold program key is hot in the
    shared persistent/AOT caches before its trial ever launches.
    Counters (``prewarmed_spawns``/``cold_spawns``/``prewarm_compiles``)
    land in ``experiment_state.json["compile"]``.  0 disables (default).
    ``progress_deadline_s``: fail-SLOW detection (liveness.py).  Where
    ``time_limit_per_trial_s`` bounds total runtime, this bounds SILENCE:
    a trial that produces no progress signal (``tune.report`` or
    ``tune.heartbeat``) for this long is marked STALLED — and, under the
    process executor, killed and restarted from its newest checkpoint
    within ``max_failures`` (the thread executor cannot preempt; the stall
    is marked, counted, and cleared if the trial recovers).  Counters land
    in ``experiment_state.json["liveness"]`` and TensorBoard.  Size it
    comfortably above the slowest legitimate report gap (or call
    ``tune.heartbeat()`` inside long epochs).
    ``progress_grace_s``: extra allowance before each incarnation's FIRST
    progress signal (process spawn, jax import, cold compile; default
    ``max(3 * deadline, 30)``) so startup latency is never misread as a
    stall.
    ``resume``: continue an interrupted experiment (requires an explicit
    ``name`` pointing at its directory): finished trials are kept and their
    metric streams replayed into the scheduler/searcher, interrupted trials
    re-run from their newest checkpoint, and sampling continues to
    ``num_samples`` — driver-crash / preemption recovery for the whole
    experiment, not just single trials.
    ``trace``: structured tracing (``obs/``, docs/observability.md; also
    enabled by ``DML_OBS_TRACE=1``): every process in the run — driver,
    process-executor children — streams spans (trial lifecycle, epochs,
    compiles, checkpoint save/restore, prefetch waits) to per-process
    files under ``<experiment>/trace/``, merged into a Chrome-trace/
    Perfetto ``trace.json`` at experiment end (``dml-tpu trace`` to
    export/summarize).  Trace ids are consistent across the process
    boundary.  Off (the default), the instrumentation costs one
    None-check per span.  Either way the run points the always-on flight
    recorder at the experiment root: a stall, kill, or SIGTERM dumps the
    last ~2048 events (``flightrec_*.json``) with per-thread open-span
    stacks — the hang site, not just a counter.
    ``trace_profile_trials``: programmatically ``jax.profiler``-capture
    the first N trials into ``<experiment>/profile/<trial_id>/`` (one at
    a time; concurrent candidates skip, counted).  Independent of
    ``trace``.
    """
    if mode not in ("min", "max"):
        raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
    from distributed_machine_learning_tpu.tune import journal as journal_lib

    # resume="auto": resume IFF a prior head left an uncommitted decision
    # journal behind (crash mid-sweep); a committed journal or no journal
    # means the experiment either finished cleanly or never started, and the
    # run proceeds fresh.  Unlike resume=True this never raises on a missing
    # directory — "auto" is safe to pass unconditionally in supervisor loops.
    journal_resume = False
    if resume == "auto":
        if not name:
            raise ValueError(
                'resume="auto" needs the explicit experiment `name`'
            )
        journal_resume = journal_lib.is_uncommitted(
            ExperimentStore.root_for(storage_path, name)
        )
        resume = journal_resume
    if resume:
        _validate_resume(storage_path, name)
    if compile_cache_dir is not None:
        from distributed_machine_learning_tpu.utils.compile_cache import (
            enable_persistent_cache,
        )

        enable_persistent_cache(
            None if compile_cache_dir == "auto" else compile_cache_dir
        )
    space = (
        param_space
        if isinstance(param_space, SearchSpace)
        else SearchSpace(param_space)
    )
    from distributed_machine_learning_tpu.tune.stoppers import resolve_stop

    stop = resolve_stop(stop)  # validate dict/callable/Stopper up front
    searcher = maybe_warm_start(search_alg or RandomSearch(), points_to_evaluate)
    searcher.set_search_space(space, seed)
    sched = scheduler or FIFOScheduler()
    sched.set_experiment(metric, mode)
    if mesh_shape is not None and resources_per_trial is None:
        # The mesh IS the resource request: lease exactly as many devices
        # as the axes multiply out to.
        import math

        resources_per_trial = {
            "devices": math.prod(int(v) for v in mesh_shape.values())
        }
    resources = Resources.parse(resources_per_trial)

    name = name or f"exp_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:6]}"
    store = ExperimentStore(
        storage_path, name, checkpoint_storage,
        checkpoint_format=checkpoint_format,
    )
    store.set_context(metric, mode)
    from distributed_machine_learning_tpu.ckpt import get_metrics
    from distributed_machine_learning_tpu import compilecache

    ckpt_metrics_base = get_metrics().snapshot()
    # Scope the process-wide compile registries to THIS run (same
    # discipline as the checkpoint counters).
    compile_tracker_base = compilecache.get_tracker().snapshot()
    compile_counters_base = compilecache.get_counters().snapshot()
    from distributed_machine_learning_tpu.data import pipeline as hostpipe

    if input_mode is not None and input_mode not in hostpipe.INPUT_MODES:
        raise ValueError(
            f"input_mode must be one of {hostpipe.INPUT_MODES}, "
            f"got {input_mode!r}"
        )
    host_input_base = hostpipe.get_host_input_counters().snapshot()
    # Observability plane (obs/): flight-recorder dumps land in the
    # experiment root for THIS run; tracing (opt-in) streams spans to
    # <root>/trace/ per process, merged at teardown.
    import os as _os

    from distributed_machine_learning_tpu import obs as obs_lib

    trace = trace or _os.environ.get("DML_OBS_TRACE") == "1"
    trace_dir = _os.path.join(store.root, "trace") if trace else None
    prev_dump_dir = obs_lib.dump_dir()
    # Journal-based resume adopts the dead head's trace identity BEFORE the
    # tracer is configured, so one trace id spans both head incarnations —
    # the resumed sweep's spans merge into the same trace.json.
    replay = journal_lib.parse_journal(store.root) if journal_resume else None
    prior_frame = (replay.trace_frame if replay is not None else None) or {}
    obs_lib.configure(trace_dir=trace_dir, label="driver",
                      dump_dir=store.root,
                      trace_id=prior_frame.get("trace_id"),
                      parent_span_id=prior_frame.get("parent_span_id"))
    # Every scheduling decision is journaled (write-ahead) before it takes
    # effect; `journal.commit()` at clean teardown is what "auto" checks for.
    journal = journal_lib.ExperimentJournal(store.root)
    head_incarnation = journal.open(obs_frame=obs_lib.trace_context_frame())
    profile_dir = (
        _os.path.join(store.root, "profile")
        if trace_profile_trials > 0 else None
    )
    profile_budget = [max(int(trace_profile_trials), 0)]
    obs_counters_base = obs_lib.get_registry().counters_snapshot()
    device_mgr = DeviceManager(devices)
    events: "queue.Queue" = queue.Queue()
    watchdog = None
    if progress_deadline_s is not None:
        from distributed_machine_learning_tpu.liveness import DispatchWatchdog

        # Polled from the event loop below (which ticks every <=0.5s); no
        # monitor thread needed.
        watchdog = DispatchWatchdog(
            progress_deadline_s, first_beat_grace_s=progress_grace_s
        )
    if trial_executor == "thread":
        executor = ThreadTrialExecutor(store, events, watchdog=watchdog)
    elif trial_executor == "process":
        executor = ProcessTrialExecutor(store, events, watchdog=watchdog,
                                        prewarm=prewarm_runners)
    else:
        raise ValueError(
            f"trial_executor must be 'thread' or 'process', got {trial_executor!r}"
        )
    from distributed_machine_learning_tpu.tune.callbacks import (
        with_default_reporter,
    )

    callbacks = with_default_reporter(callbacks, verbose)

    max_concurrent = max_concurrent or device_mgr.num_devices
    running: Dict[str, List] = {}  # trial_id -> leased devices
    last_status_print = 0.0
    last_sched_persist = 0.0

    def log(msg: str):
        if verbose:
            print(f"[tune] {msg}", flush=True)

    lifecycle = TrialLifecycle(
        searcher=searcher,
        scheduler=sched,
        store=store,
        metric=metric,
        mode=mode,
        num_samples=num_samples,
        max_failures=max_failures,
        stop_rules=stop,
        time_budget_s=time_budget_s,
        keep_checkpoints_num=keep_checkpoints_num,
        time_limit_per_trial_s=time_limit_per_trial_s,
        log=log,
        config_overlay={
            **({"mesh_shape": dict(mesh_shape)} if mesh_shape else {}),
            **({"input_mode": input_mode} if input_mode else {}),
        } or None,
        journal=journal,
    )
    trials = lifecycle.trials
    pending = lifecycle.pending
    start_time = lifecycle.start_time

    liveness_counters = {"stall_kills": 0, "stall_requeues": 0}
    if watchdog is not None:
        # The liveness family in the unified registry: watchdog counters +
        # the runner's kill/requeue responses, live (the published
        # experiment_state.json block keeps its existing shape below).
        obs_lib.get_registry().register_family(
            "liveness",
            lambda: {**watchdog.snapshot(), **liveness_counters},
        )

    if journal_resume and replay is not None:
        counts = lifecycle.restore_from_journal(replay, resources=resources)
        log(
            f"resumed {name} from journal (head incarnation "
            f"{head_incarnation}): {counts['finished']} finished trials "
            f"kept, {counts['requeued']} interrupted trials requeued, "
            f"{counts['suppress_windows']} replay suppression windows"
        )
    elif resume:
        counts = lifecycle.restore_experiment(resources=resources)
        log(
            f"resumed {name}: {counts['finished']} finished trials kept, "
            f"{counts['requeued']} interrupted trials requeued"
        )

    def safe_cb(hook: str, *args):
        from distributed_machine_learning_tpu.tune.callbacks import (
            dispatch_safely,
        )

        dispatch_safely(callbacks, hook, *args, log=log)

    trial_spans: Dict[str, Any] = {}  # trial_id -> open dispatch span

    def launch_ready():
        while pending and len(running) < max_concurrent:
            leased = device_mgr.acquire(pending[0].resources.devices)
            if leased is None:
                return
            trial = pending.pop(0)
            lifecycle.mark_running(trial)
            running[trial.trial_id] = leased
            if watchdog is not None:
                watchdog.track(trial.trial_id)
            # Driver-side dispatch span (detached: it closes on a later
            # event-loop iteration); the executor parents the in-trial
            # span under it — across the process boundary too.
            span = obs_lib.detached_span(
                "trial.dispatch",
                {"trial_id": trial.trial_id,
                 "incarnation": trial.incarnation},
                parent=obs_lib.current_context(),
            )
            trial_spans[trial.trial_id] = span
            trial._obs_parent = span.context
            if profile_dir is not None and profile_budget[0] > 0:
                profile_budget[0] -= 1
                trial._obs_profile_dir = profile_dir
            else:
                trial._obs_profile_dir = None
            obs_lib.event("trial_dispatch", {"trial_id": trial.trial_id})
            safe_cb("on_trial_start", trial)
            executor.start_trial(trial, trainable, leased)

    def release_devices(trial: Trial):
        leased = running.pop(trial.trial_id, None)
        if leased:
            device_mgr.release(leased)
        if watchdog is not None:
            watchdog.untrack(trial.trial_id)
        span = trial_spans.pop(trial.trial_id, None)
        if span is not None:
            span.end()

    # -------- main event loop ------------------------------------------------
    last_enforce = [0.0]
    _STALL_PREFIX = "stalled: no progress signal"

    def enforce_liveness():
        """Turn watchdog expiries into actions: kill+restart under the
        process executor (preemption-safe — the error path restores the
        newest checksum-valid checkpoint within max_failures), mark
        STALLED under the thread executor (threads can't be preempted;
        a later beat flips the trial back to RUNNING)."""
        if watchdog is None:
            return
        # Reconcile recoveries first: a beat may have arrived straight from
        # the trial thread (tune.heartbeat()) since the stall was flagged.
        for tid in list(running):
            trial = lifecycle.by_id[tid]
            if (
                trial.status == TrialStatus.STALLED
                and not watchdog.is_stalled(tid)
            ):
                trial.status = TrialStatus.RUNNING
                trial.stall_recoveries += 1
                log(f"{tid} recovered after stall (progress resumed)")
        for event in watchdog.expired():
            trial = lifecycle.by_id.get(event.key)
            if trial is None or trial.trial_id not in running:
                watchdog.untrack(event.key)
                continue
            trial.stall_count += 1
            # Forensics BEFORE the response: the dump carries the last
            # ~2048 process events plus every thread's open-span stack —
            # under the thread executor that includes the stalled trial
            # thread's innermost span, i.e. the hang site.
            obs_lib.dump_flight_recorder(
                f"stall_{trial.trial_id}",
                extra={"trial_id": trial.trial_id,
                       "age_s": round(event.age_s, 2),
                       "deadline_s": event.deadline_s},
            )
            if getattr(executor, "supports_kill", False):
                why = (
                    f"{_STALL_PREFIX} in {event.age_s:.1f}s "
                    f"(deadline {event.deadline_s:.1f}s)"
                )
                log(f"{trial.trial_id} {why}; killing incarnation "
                    f"{trial.incarnation}")
                liveness_counters["stall_kills"] += 1
                executor.kill(trial, why)
            else:
                trial.status = TrialStatus.STALLED
                log(
                    f"{trial.trial_id} STALLED: no progress signal in "
                    f"{event.age_s:.1f}s (deadline {event.deadline_s:.1f}s; "
                    f"thread executor cannot preempt — the mark clears if "
                    f"it beats again; use trial_executor='process' for "
                    f"kill/restart)"
                )

    def enforce_time_limits():
        """Hard preemption: a trial past its time limit that has gone quiet
        (no report) is killed outright when the executor can (process
        executor); the thread executor can only flag it for stop at its next
        report.  Runs on EVERY loop iteration (rate-limited), not just idle
        ones — a busy event stream must not starve enforcement."""
        if time_limit_per_trial_s is None:
            return
        now = time.time()
        if now - last_enforce[0] < 1.0:
            return
        last_enforce[0] = now
        grace = max(2.0, 0.25 * time_limit_per_trial_s)
        for tid in list(running):
            trial = lifecycle.by_id[tid]
            overdue = trial.incarnation_runtime_s() - time_limit_per_trial_s
            if overdue <= grace or not executor.is_alive(trial):
                continue
            if getattr(executor, "supports_kill", False):
                log(
                    f"{trial.trial_id} exceeded time limit "
                    f"({trial.incarnation_runtime_s():.0f}s > "
                    f"{time_limit_per_trial_s:.0f}s); killing"
                )
                executor.kill(
                    trial,
                    f"time limit exceeded ({time_limit_per_trial_s:.0f}s)",
                )
            else:
                trial.stop_requested = True

    def event_loop():
        nonlocal last_status_print, last_sched_persist
        while True:
            while not lifecycle.exhausted() and (
                len(pending) + len(running) < max_concurrent + 2
            ):
                if lifecycle.create_trial(resources=resources) is None:
                    break
            launch_ready()

            if not running and not pending:
                if lifecycle.exhausted():
                    break
                if len(trials) == 0 and lifecycle.next_index == 0:
                    break  # nothing to do at all
                continue

            enforce_time_limits()
            enforce_liveness()
            try:
                event = events.get(timeout=0.5)
            except queue.Empty:
                # Scheduler think-time: ask an idle pre-warmed runner to
                # compile the next pending trial's program so its launch
                # finds every cache hot (no-op without a warm pool; deduped
                # per program key inside the executor).
                if pending and hasattr(executor, "prewarm_program"):
                    cand = pending[0]
                    executor.prewarm_program(
                        trainable, cand.config,
                        compilecache.program_key(cand.config),
                    )
                if verbose and time.time() - last_status_print > 15:
                    last_status_print = time.time()
                    log(
                        f"{sum(t.status == TrialStatus.TERMINATED for t in trials)}"
                        f"/{num_samples} done, {len(running)} running, "
                        f"{device_mgr.num_free}/{device_mgr.num_devices} cores free"
                    )
                # Reap trials whose executor died without a terminal event
                # (shouldn't happen: both executors post one on every path).
                # Routed through fail_trial so the retry budget and error
                # reporting behave exactly like an ordinary trial error.
                for tid in list(running):
                    trial = lifecycle.by_id[tid]
                    if not executor.is_alive(trial):
                        why = "trial executor died without reporting"
                        safe_cb("on_trial_error", trial, why)
                        release_devices(trial)
                        lifecycle.fail_trial(trial, why)
                safe_cb("on_heartbeat")
                continue

            kind = event[0]
            # Stale-event guard: a dead incarnation's late events (kill/EOF
            # races, reaped trials) must not be applied — especially not to
            # a relaunched retry of the same trial.  Anything whose
            # incarnation tag doesn't match the trial's current incarnation,
            # or whose trial is no longer running, is dropped.
            if kind == "result":
                ev_trial, ev_inc = event[1].trial, event[1].incarnation
            else:
                ev_trial = event[1]
                ev_inc = event[3] if len(event) > 3 else ev_trial.incarnation
            if (
                ev_trial.trial_id not in running
                or ev_inc != ev_trial.incarnation
            ):
                if kind == "result":
                    event[1].decision = "stop"
                    event[1].done.set()
                continue

            if kind == "result":
                result_event = event[1]
                trial = result_event.trial
                if watchdog is not None:
                    # A report IS progress: beat before deciding, and a
                    # STALLED-but-reporting trial is a recovery, not a kill.
                    watchdog.beat(trial.trial_id)
                    if trial.status == TrialStatus.STALLED:
                        trial.status = TrialStatus.RUNNING
                        trial.stall_recoveries += 1
                        log(f"{trial.trial_id} recovered after stall "
                            f"(report resumed)")
                result_event.decision = lifecycle.process_result(
                    trial, result_event.metrics
                )
                # Unblock the trial thread BEFORE observers run: a slow or
                # buggy callback must not stall (or hang) training.
                result_event.done.set()
                safe_cb("on_trial_result", trial, trial.last_result)
                # Forensics (satellite of the durable-control-plane work):
                # persist the scheduler/searcher debug snapshot at report
                # boundaries, throttled so a chatty sweep doesn't rewrite
                # experiment_state.json on every epoch.
                if time.time() - last_sched_persist > 2.0:
                    last_sched_persist = time.time()
                    store.write_state(trials, extra={
                        "scheduler": scheduler_debug_block(searcher, sched),
                    })

            elif kind == "complete":
                trial = event[1]
                release_devices(trial)
                if not lifecycle.complete_trial(trial):
                    safe_cb("on_trial_complete", trial)
                store.write_state(trials, extra={
                    "scheduler": scheduler_debug_block(searcher, sched),
                })

            elif kind == "error":
                trial, tb = event[1], event[2]
                trial.error = tb
                # Every failure is observable, including ones that will be
                # retried (preemptions are exactly what observers watch for).
                safe_cb("on_trial_error", trial, tb)
                release_devices(trial)
                retried = lifecycle.fail_trial(trial, tb)
                if retried and tb and tb.startswith(_STALL_PREFIX):
                    liveness_counters["stall_requeues"] += 1
                if not retried and verbose:
                    log(f"{trial.trial_id} errored:\n{tb}")
                store.write_state(trials, extra={
                    "scheduler": scheduler_debug_block(searcher, sched),
                })

    # Teardown always runs (Ctrl-C, store errors, a callback's setup raising):
    # callbacks must see experiment end so e.g. ProfilerCallback stops the
    # process-global trace and JsonlCallback closes its file.
    clean_end = False
    try:
        # The experiment root span: every driver-side span (trial
        # dispatches) and, via frame context, every child/worker span
        # shares its trace id.
        with obs_lib.span("experiment", {"name": name}):
            for cb in callbacks:
                cb.setup(store.root, metric, mode)
            event_loop()
        # Reaching here means the sweep drained normally — only then is the
        # journal committed below; an exception (Ctrl-C, store failure)
        # leaves it uncommitted so resume="auto" picks the run back up.
        clean_end = True
    finally:
        # Clock first (teardown time is not experiment time), then tear the
        # executor down: an interrupted sweep must not leave orphan trial
        # processes holding devices (process executor terminates children;
        # thread executor best-effort joins).
        wall = time.time() - start_time
        try:
            executor.join_all(timeout=5.0)
        except Exception as exc:  # noqa: BLE001
            log(f"executor teardown failed: {exc!r}")
        # Final retention pass: join_all flushed the async writer, so
        # writes that landed AFTER each trial's last in-run prune now
        # converge to exactly keep_checkpoints_num on disk.
        lifecycle.final_prune()
        utilization = device_mgr.utilization(wall)
        from distributed_machine_learning_tpu import chaos
        from distributed_machine_learning_tpu.utils import compile_cache as cc

        extra = {
            "wall_clock_s": wall,
            "device_utilization": utilization,
            "compile_time_total_s": round(cc.get_tracker().total_seconds(), 3),
            "compile_cache_hits": cc.get_tracker().total_cache_hits(),
            "compile_cache_entries": cc.cache_entry_count(),
            # The compile counter family for THIS run (tracker event counts
            # + artifact-layer counters) — the block the compile-once
            # acceptance checks read.
            "compile": compilecache.state_block(
                compile_tracker_base, compile_counters_base
            ),
        }
        if watchdog is not None:
            # Fail-slow observability next to the fail-fast counters: how
            # many silences were detected, killed, requeued, or recovered.
            extra["liveness"] = {**watchdog.snapshot(), **liveness_counters}
        # Checkpoint I/O accounting for THIS run (the registry is
        # process-wide): save/restore wall and bytes, fallbacks taken, and
        # the async-overlap counters that prove training ran while writes
        # were in flight.
        ckpt_counters = get_metrics().delta_since(ckpt_metrics_base)
        if any(ckpt_counters.values()):
            extra["checkpoint"] = ckpt_counters
        # Host-input accounting for THIS run (out-of-core streaming +
        # dataset cache): prefetch hits, producer/consumer waits, and the
        # derived overlap efficiency — present only when something
        # streamed or the dataset cache was touched.
        hi_block = hostpipe.host_input_block(host_input_base)
        if hi_block is not None:
            extra["host_input"] = hi_block
        plan = chaos.active_plan()
        if plan is not None:
            # A chaos run's state snapshot records what was injected, so
            # "it survived N faults" is a property of the artifact, not of
            # test logs.
            extra["injected_faults"] = plan.snapshot()
        from distributed_machine_learning_tpu.tune.schedulers.pbt import (
            pbt_state_block,
        )

        pbt_block = pbt_state_block(sched)
        if pbt_block is not None:
            # The pbt counter family (exploit/explore accounting) — the
            # respawn driver's slice of what run_vectorized reports richer
            # (generations/host_dispatches only exist in-device).
            extra["pbt"] = pbt_block
        # Observability-plane accounting + trace merge: close any spans
        # still open (teardown), merge the per-process span files into
        # one Chrome-trace JSON, and publish the obs counter delta.
        for span in trial_spans.values():
            span.end()
        trial_spans.clear()
        merged_trace = None
        if trace_dir is not None:
            obs_lib.flush()
            merged_trace = obs_lib.merge_trace_dir(trace_dir)
            obs_lib.shutdown()
        # Control-plane forensics: final scheduler/searcher snapshot plus
        # the journal counters the crash-recovery runbook keys off
        # (docs/operations.md — head_incarnations / journal_replays /
        # duplicate_reports_suppressed).
        extra["scheduler"] = scheduler_debug_block(searcher, sched)
        extra["journal"] = {
            "head_incarnation": head_incarnation,
            "decisions": journal.n,
            "journal_replays": (
                (replay.replays if replay is not None else 0)
                + (1 if journal_resume else 0)
            ),
            "duplicate_reports_suppressed":
                lifecycle.duplicate_reports_suppressed,
            "committed": clean_end,
        }
        obs_delta = obs_lib.get_registry().delta_since(obs_counters_base)
        obs_block = {k: v for k, v in obs_delta.items() if v}
        if merged_trace is not None:
            obs_block["trace"] = merged_trace
        if obs_block:
            extra["obs"] = obs_block
        if watchdog is not None:
            obs_lib.get_registry().unregister_family("liveness")
        obs_lib.set_dump_dir(prev_dump_dir)
        try:
            store.write_state(trials, extra=extra)
            store.close()
        except Exception as exc:  # noqa: BLE001 - callbacks still tear down
            log(f"experiment store teardown failed: {exc!r}")
        # Commit AFTER the final state write: once the commit record lands,
        # resume="auto" treats the experiment as finished, so everything it
        # would need must already be durable.
        try:
            if clean_end:
                journal.commit()
            journal.close()
        except Exception as exc:  # noqa: BLE001
            log(f"journal teardown failed: {exc!r}")
        counter_scalars = {
            **{f"liveness/{k}": v
               for k, v in (extra.get("liveness") or {}).items()},
            **{f"faults/{k}": v
               for k, v in (extra.get("injected_faults") or {}).items()},
            **{f"checkpoint/{k}": v
               for k, v in (extra.get("checkpoint") or {}).items()},
            **{f"compile/{k}": v
               for k, v in (extra.get("compile") or {}).items()},
            **{f"host_input/{k}": v
               for k, v in (extra.get("host_input") or {}).items()},
            **{f"pbt/{k}": v
               for k, v in (extra.get("pbt") or {}).items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)},
            **{f"obs/{k}": v
               for k, v in (extra.get("obs") or {}).items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)},
            **{f"journal/{k}": v
               for k, v in (extra.get("journal") or {}).items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)},
        }
        if counter_scalars:
            safe_cb("on_experiment_counters", counter_scalars)
        safe_cb("on_experiment_end", trials, wall)
    analysis = ExperimentAnalysis(
        trials, metric=metric, mode=mode, root=store.root, wall_clock_s=wall,
        device_utilization=utilization,
    )
    n_done = analysis.num_terminated()
    log(
        f"experiment {name}: {n_done}/{len(trials)} trials terminated in "
        f"{wall:.1f}s ({analysis.trials_per_hour():.1f} trials/hour, "
        f"{100 * utilization:.0f}% device utilization)"
    )
    return analysis
