"""The tune.run() driver loop.

Native, single-process replacement for ``tune.run(...)``
(`ray-tune-hpo-regression.py:469-478`): samples trial configs from the search
algorithm, leases TPU cores from the DeviceManager, streams per-epoch results
through the scheduler, early-stops / requeues / retries, persists everything to
the experiment store, and returns an ExperimentAnalysis with ``best_config``
(`:480`).

Event-driven: trial threads block in ``report`` until this loop answers, so
all scheduler/searcher state is mutated from exactly one thread.
"""

from __future__ import annotations

import queue
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Union

from distributed_machine_learning_tpu.tune.executor import (
    DeviceManager,
    ThreadTrialExecutor,
)
from distributed_machine_learning_tpu.tune.experiment import (
    ExperimentAnalysis,
    ExperimentStore,
)
from distributed_machine_learning_tpu.tune.schedulers.base import (
    CONTINUE,
    FIFOScheduler,
    REQUEUE,
    STOP,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.search.base import RandomSearch, Searcher
from distributed_machine_learning_tpu.tune.search_space import SearchSpace
from distributed_machine_learning_tpu.tune.trial import (
    Resources,
    Trial,
    TrialStatus,
)

DEFAULT_STORAGE = "~/dml_tpu_results"


def run(
    trainable: Callable,
    param_space: Union[Dict[str, Any], SearchSpace],
    *,
    metric: str,
    mode: str = "min",
    num_samples: int = 10,
    scheduler: Optional[TrialScheduler] = None,
    search_alg: Optional[Searcher] = None,
    resources_per_trial: Optional[Dict[str, int]] = None,
    max_concurrent: Optional[int] = None,
    storage_path: str = DEFAULT_STORAGE,
    name: Optional[str] = None,
    seed: int = 0,
    max_failures: int = 0,
    stop: Optional[Dict[str, float]] = None,
    time_budget_s: Optional[float] = None,
    devices: Optional[List] = None,
    verbose: int = 1,
    callbacks: Optional[List] = None,
) -> ExperimentAnalysis:
    """Run an HPO experiment; see module docstring.

    ``stop``: dict of result-key -> threshold; a trial stops once any key's
    reported value reaches the threshold (e.g. ``{"training_iteration": 20}``).
    ``max_failures``: per-trial retry budget; retries restore from the trial's
    latest checkpoint when one exists (preemption tolerance, SURVEY.md §5).
    """
    if mode not in ("min", "max"):
        raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
    space = (
        param_space
        if isinstance(param_space, SearchSpace)
        else SearchSpace(param_space)
    )
    searcher = search_alg or RandomSearch()
    searcher.set_search_space(space, seed)
    sched = scheduler or FIFOScheduler()
    sched.set_experiment(metric, mode)
    resources = Resources.parse(resources_per_trial)

    name = name or f"exp_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:6]}"
    store = ExperimentStore(storage_path, name)
    device_mgr = DeviceManager(devices)
    events: "queue.Queue" = queue.Queue()
    executor = ThreadTrialExecutor(store, events)
    callbacks = list(callbacks or [])

    max_concurrent = max_concurrent or device_mgr.num_devices
    trials: List[Trial] = []
    pending: List[Trial] = []
    running: Dict[str, List] = {}  # trial_id -> leased devices
    next_index = 0
    searcher_exhausted = False
    start_time = time.time()
    last_status_print = 0.0

    def log(msg: str):
        if verbose:
            print(f"[tune] {msg}", flush=True)

    def safe_cb(hook: str, *args):
        """Observers must never wedge the sweep: a raising callback is logged
        and dropped for that event (the trial thread may be blocked in
        ``report`` waiting on this loop — see executor.ResultEvent)."""
        for cb in callbacks:
            try:
                getattr(cb, hook)(*args)
            except Exception as exc:  # noqa: BLE001 - observer isolation
                log(f"{type(cb).__name__}.{hook} raised: {exc!r}")

    def budget_exceeded() -> bool:
        return time_budget_s is not None and time.time() - start_time > time_budget_s

    def maybe_create_trial():
        nonlocal next_index, searcher_exhausted
        if searcher_exhausted or next_index >= num_samples or budget_exceeded():
            return
        config = searcher.suggest(next_index)
        if config is None:
            searcher_exhausted = True
            return
        trial = Trial(
            trial_id=f"trial_{next_index:05d}",
            config=config,
            resources=resources,
        )
        next_index += 1
        trials.append(trial)
        pending.append(trial)
        sched.on_trial_add(trial)
        store.write_params(trial)

    def launch_ready():
        while pending and len(running) < max_concurrent:
            leased = device_mgr.acquire(pending[0].resources.devices)
            if leased is None:
                return
            trial = pending.pop(0)
            trial.status = TrialStatus.RUNNING
            trial.started_at = trial.started_at or time.time()
            trial.stop_requested = False
            running[trial.trial_id] = leased
            safe_cb("on_trial_start", trial)
            executor.start_trial(trial, trainable, leased)

    def finish_trial(trial: Trial, status: TrialStatus):
        leased = running.pop(trial.trial_id, None)
        if leased:
            device_mgr.release(leased)
        trial.status = status
        trial.finished_at = time.time()
        if status == TrialStatus.TERMINATED:
            searcher.on_trial_complete(
                trial.trial_id, trial.config, trial.last_result, metric, mode
            )
        sched.on_trial_complete(trial)

    def requeue_trial(trial: Trial):
        leased = running.pop(trial.trial_id, None)
        if leased:
            device_mgr.release(leased)
        trial.status = TrialStatus.PENDING
        pending.append(trial)

    # -------- main event loop ------------------------------------------------
    def event_loop():
        nonlocal last_status_print
        while True:
            while len(trials) < num_samples and not searcher_exhausted and (
                len(pending) + len(running) < max_concurrent + 2
            ):
                before = len(trials)
                maybe_create_trial()
                if len(trials) == before:
                    break
            launch_ready()

            if not running and not pending:
                if (
                    searcher_exhausted
                    or len(trials) >= num_samples
                    or budget_exceeded()
                ):
                    break
                if len(trials) == 0 and next_index == 0:
                    break  # nothing to do at all
                continue

            try:
                event = events.get(timeout=0.5)
            except queue.Empty:
                if verbose and time.time() - last_status_print > 15:
                    last_status_print = time.time()
                    log(
                        f"{sum(t.status == TrialStatus.TERMINATED for t in trials)}"
                        f"/{num_samples} done, {len(running)} running, "
                        f"{device_mgr.num_free}/{device_mgr.num_devices} cores free"
                    )
                # Reap threads that died without reporting (shouldn't happen).
                for tid in list(running):
                    trial = next(t for t in trials if t.trial_id == tid)
                    if not executor.is_alive(trial):
                        finish_trial(trial, TrialStatus.ERROR)
                        safe_cb(
                            "on_trial_error",
                            trial,
                            "trial thread died without reporting",
                        )
                safe_cb("on_heartbeat")
                continue

            kind = event[0]
            if kind == "result":
                result_event = event[1]
                trial = result_event.trial
                metrics = dict(result_event.metrics)
                metrics.setdefault(
                    "training_iteration", trial.training_iteration + 1
                )
                metrics["trial_id"] = trial.trial_id
                metrics["timestamp"] = time.time()
                metrics["time_total_s"] = trial.runtime_s()
                trial.results.append(metrics)
                store.append_result(trial, metrics)

                # Snapshot before the scheduler runs: PBT mutates trial.config
                # in place on REQUEUE, and the searcher must see the config
                # that actually produced these metrics.
                reported_config = dict(trial.config)
                decision = sched.on_trial_result(trial, metrics)
                searcher.on_trial_result(
                    trial.trial_id, reported_config, metrics, metric, mode
                )
                if stop and any(
                    k in metrics and float(metrics[k]) >= v
                    for k, v in stop.items()
                ):
                    decision = STOP if decision == CONTINUE else decision
                if trial.stop_requested or budget_exceeded():
                    decision = STOP
                if decision == REQUEUE:
                    trial._requeue_on_complete = True
                    decision = STOP
                result_event.decision = "stop" if decision == STOP else "continue"
                # Unblock the trial thread BEFORE observers run: a slow or
                # buggy callback must not stall (or hang) training.
                result_event.done.set()
                safe_cb("on_trial_result", trial, metrics)

            elif kind == "complete":
                trial = event[1]
                if getattr(trial, "_requeue_on_complete", False):
                    trial._requeue_on_complete = False
                    requeue_trial(trial)
                else:
                    finish_trial(trial, TrialStatus.TERMINATED)
                    safe_cb("on_trial_complete", trial)
                store.write_state(trials)

            elif kind == "error":
                trial, tb = event[1], event[2]
                trial.error = tb
                trial.num_failures += 1
                # Every failure is observable, including ones that will be
                # retried (preemptions are exactly what observers watch for).
                safe_cb("on_trial_error", trial, tb)
                if trial.num_failures <= max_failures:
                    log(
                        f"{trial.trial_id} failed "
                        f"({trial.num_failures}/{max_failures}); retrying"
                        + (" from checkpoint" if trial.latest_checkpoint else "")
                    )
                    if trial.latest_checkpoint:
                        trial.restore_path = trial.latest_checkpoint
                    requeue_trial(trial)
                else:
                    if verbose:
                        log(f"{trial.trial_id} errored:\n{tb}")
                    finish_trial(trial, TrialStatus.ERROR)
                    sched.on_trial_error(trial)
                store.write_state(trials)

    # Teardown always runs (Ctrl-C, store errors, a callback's setup raising):
    # callbacks must see experiment end so e.g. ProfilerCallback stops the
    # process-global trace and JsonlCallback closes its file.
    try:
        for cb in callbacks:
            cb.setup(store.root, metric, mode)
        event_loop()
    finally:
        wall = time.time() - start_time
        utilization = device_mgr.utilization(wall)
        try:
            store.write_state(
                trials,
                extra={"wall_clock_s": wall, "device_utilization": utilization},
            )
            store.close()
        except Exception as exc:  # noqa: BLE001 - callbacks still tear down
            log(f"experiment store teardown failed: {exc!r}")
        safe_cb("on_experiment_end", trials, wall)
    analysis = ExperimentAnalysis(
        trials, metric=metric, mode=mode, root=store.root, wall_clock_s=wall,
        device_utilization=utilization,
    )
    n_done = analysis.num_terminated()
    log(
        f"experiment {name}: {n_done}/{len(trials)} trials terminated in "
        f"{wall:.1f}s ({analysis.trials_per_hour():.1f} trials/hour, "
        f"{100 * utilization:.0f}% device utilization)"
    )
    return analysis
