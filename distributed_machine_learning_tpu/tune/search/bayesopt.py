"""Bayesian-optimization search over the continuous subspace.

The reference used ``BayesOptSearch(random_search_steps=10)``
(`ray-tune-hpo-regression.py:474`) over a categorical-heavy space — a latent
incompatibility, since upstream ``bayes_opt`` only models continuous params
(SURVEY.md §2b D2).  Here the mixed-space strategy is deliberate: a Gaussian
process with expected-improvement acquisition models the *continuous* keys
(uniform/loguniform, normalized to the unit cube); categorical/integer keys are
sampled randomly per suggestion.  Pure numpy — no GP library dependency.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from distributed_machine_learning_tpu.tune.search.base import Searcher
from distributed_machine_learning_tpu.tune.search_space import SearchSpace
from distributed_machine_learning_tpu.utils.seeding import rng_from


def _rbf_kernel(a: np.ndarray, b: np.ndarray, lengthscale: float) -> np.ndarray:
    d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
    return np.exp(-0.5 * d2 / lengthscale**2)


def gp_posterior(X: np.ndarray, y: np.ndarray, cand: np.ndarray,
                 lengthscale: float, noise: float):
    """(mu, sigma) of an RBF-GP posterior at ``cand``, fitted on (X, y).

    y is normalized internally; mu, sigma, and the returned normalized
    targets ``yn`` share that scale (ranking-equivalent, which is all the
    acquisitions need).  Shared by ``BayesOptSearch`` (EI) and the PB2
    scheduler (UCB).  Raises ``np.linalg.LinAlgError`` when the kernel is
    degenerate — callers fall back to their non-model behavior.
    """
    yn = (y - y.mean()) / (y.std() + 1e-9)
    K = _rbf_kernel(X, X, lengthscale) + noise * np.eye(len(X))
    L = np.linalg.cholesky(K)
    alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
    Ks = _rbf_kernel(cand, X, lengthscale)
    mu = Ks @ alpha
    v = np.linalg.solve(L, Ks.T)
    sigma = np.sqrt(np.clip(1.0 - (v**2).sum(axis=0), 1e-12, None))
    return mu, sigma, yn


class BayesOptSearch(Searcher):
    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        random_search_steps: int = 10,
        num_candidates: int = 512,
        lengthscale: float = 0.2,
        noise: float = 1e-4,
        xi: float = 0.01,
    ):
        self.metric = metric
        self.mode = mode
        self.random_steps = random_search_steps
        self.num_candidates = num_candidates
        self.lengthscale = lengthscale
        self.noise = noise
        self.xi = xi
        self._X: List[np.ndarray] = []  # observed unit-cube points
        self._y: List[float] = []       # observed scores (lower = better)
        self._pending: Dict[str, np.ndarray] = {}

    def set_search_space(self, space: SearchSpace, seed: int):
        super().set_search_space(space, seed)
        self._cont_keys = space.continuous_keys()

    # -- encode/decode continuous subspace -----------------------------------
    def _encode(self, config: Dict[str, Any]) -> np.ndarray:
        return np.array(
            [self.space.domain(k).to_unit(config[k]) for k in self._cont_keys],
            dtype=np.float64,
        )

    def _apply(self, config: Dict[str, Any], u: np.ndarray) -> Dict[str, Any]:
        out = dict(config)
        for k, ui in zip(self._cont_keys, u):
            out[k] = self.space.domain(k).from_unit(float(ui))
        return out

    # -- searcher API --------------------------------------------------------
    def suggest(self, trial_index: int) -> Optional[Dict[str, Any]]:
        base = self.space.sample(("bayesopt", self.seed, trial_index))
        if not self._cont_keys or len(self._y) < self.random_steps:
            return base  # bootstrap phase: pure random (random_search_steps)

        rng = rng_from("bayesopt-acq", self.seed, trial_index)
        X = np.stack(self._X)
        y = np.array(self._y)
        cand = rng.random((self.num_candidates, len(self._cont_keys)))
        try:
            mu, sigma, yn = gp_posterior(
                X, y, cand, self.lengthscale, self.noise
            )
        except np.linalg.LinAlgError:
            return base  # degenerate kernel: stay with the random sample

        # Expected improvement (minimization of normalized score).
        best = yn.min()
        from math import erf, sqrt

        z = (best - self.xi - mu) / sigma
        cdf = 0.5 * (1.0 + np.vectorize(erf)(z / sqrt(2.0)))
        pdf = np.exp(-0.5 * z**2) / np.sqrt(2 * np.pi)
        ei = sigma * (z * cdf + pdf)
        u_best = cand[int(np.argmax(ei))]
        config = self._apply(base, u_best)

        # Re-check joint constraints after the GP overrides continuous keys.
        if not all(c(config) for c in self.space.constraints):
            return base
        return config

    def on_trial_complete(self, trial_id, config, result, metric, mode):
        score = self._effective_score(result, metric, mode)
        if score is None or not self._cont_keys:
            return
        self._X.append(self._encode(config))
        self._y.append(score)
