"""Bayesian-optimization search over the continuous subspace.

The reference used ``BayesOptSearch(random_search_steps=10)``
(`ray-tune-hpo-regression.py:474`) over a categorical-heavy space — a latent
incompatibility, since upstream ``bayes_opt`` only models continuous params
(SURVEY.md §2b D2).  Here the mixed-space strategy is deliberate: a Gaussian
process with expected-improvement acquisition models the *continuous* keys
(uniform/loguniform, normalized to the unit cube); categorical/integer keys are
sampled randomly per suggestion.  Pure numpy — no GP library dependency.

Async-safe by construction: the runner keeps up to ``max_concurrent + 2``
trials in flight, so at suggest time the most recent proposals have no
observations yet.  Naively ignoring them makes the acquisition re-propose
the same optimum for every in-flight slot AND makes suggestions depend on
completion timing (which trials happen to be observed varies with machine
load — the full-suite flake this guards against).  Suggested-but-unfinished
points are therefore kept as PENDING and fed to the GP with a "constant
liar" target (the running mean): the posterior variance collapses around
in-flight points, EI moves elsewhere, and the proposal stream is far less
sensitive to when observations land.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from distributed_machine_learning_tpu.tune.search.base import Searcher
from distributed_machine_learning_tpu.tune.search_space import SearchSpace
from distributed_machine_learning_tpu.utils.seeding import rng_from


def _rbf_kernel(a: np.ndarray, b: np.ndarray, lengthscale: float) -> np.ndarray:
    d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
    return np.exp(-0.5 * d2 / lengthscale**2)


def gp_posterior(X: np.ndarray, y: np.ndarray, cand: np.ndarray,
                 lengthscale: float, noise: float):
    """(mu, sigma) of an RBF-GP posterior at ``cand``, fitted on (X, y).

    y is normalized internally; mu, sigma, and the returned normalized
    targets ``yn`` share that scale (ranking-equivalent, which is all the
    acquisitions need).  Shared by ``BayesOptSearch`` (EI) and the PB2
    scheduler (UCB).  Raises ``np.linalg.LinAlgError`` when the kernel is
    degenerate — callers fall back to their non-model behavior.
    """
    yn = (y - y.mean()) / (y.std() + 1e-9)
    K = _rbf_kernel(X, X, lengthscale) + noise * np.eye(len(X))
    L = np.linalg.cholesky(K)
    alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
    Ks = _rbf_kernel(cand, X, lengthscale)
    mu = Ks @ alpha
    v = np.linalg.solve(L, Ks.T)
    sigma = np.sqrt(np.clip(1.0 - (v**2).sum(axis=0), 1e-12, None))
    return mu, sigma, yn


class BayesOptSearch(Searcher):
    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        random_search_steps: int = 10,
        num_candidates: int = 512,
        lengthscale: float = 0.2,
        noise: float = 1e-4,
        xi: float = 0.01,
    ):
        self.metric = metric
        self.mode = mode
        self.random_steps = random_search_steps
        self.num_candidates = num_candidates
        self.lengthscale = lengthscale
        self.noise = noise
        self.xi = xi
        self._X: List[np.ndarray] = []  # observed unit-cube points
        self._y: List[float] = []       # observed scores (lower = better)
        # trial_index -> suggested-but-unobserved unit-cube point
        # (constant-liar pending set; see module docstring).
        self._pending: Dict[int, np.ndarray] = {}

    def set_search_space(self, space: SearchSpace, seed: int):
        super().set_search_space(space, seed)
        self._cont_keys = space.continuous_keys()

    # -- encode/decode continuous subspace -----------------------------------
    def _encode(self, config: Dict[str, Any]) -> np.ndarray:
        return np.array(
            [self.space.domain(k).to_unit(config[k]) for k in self._cont_keys],
            dtype=np.float64,
        )

    def _apply(self, config: Dict[str, Any], u: np.ndarray) -> Dict[str, Any]:
        out = dict(config)
        for k, ui in zip(self._cont_keys, u):
            out[k] = self.space.domain(k).from_unit(float(ui))
        return out

    # -- searcher API --------------------------------------------------------
    def suggest(self, trial_index: int) -> Optional[Dict[str, Any]]:
        base = self.space.sample(("bayesopt", self.seed, trial_index))
        if not self._cont_keys:
            return base
        if len(self._y) < self.random_steps:
            # Bootstrap phase: pure random (random_search_steps).  Pending
            # registration still matters — the first GP suggestion must
            # know which random points are already in flight.
            self._pending[trial_index] = self._encode(base)
            return base

        rng = rng_from("bayesopt-acq", self.seed, trial_index)
        # Constant liar: in-flight points enter the fit at the observed
        # MEAN score, pinning the posterior there so EI explores elsewhere
        # instead of stacking every concurrent slot on one argmax (and so
        # the proposal depends far less on completion timing).
        X_obs = np.stack(self._X)
        y_obs = np.array(self._y)
        if self._pending:
            lie = float(y_obs.mean())
            X = np.concatenate(
                [X_obs, np.stack(list(self._pending.values()))]
            )
            y = np.concatenate(
                [y_obs, np.full(len(self._pending), lie)]
            )
        else:
            X, y = X_obs, y_obs
        cand = rng.random((self.num_candidates, len(self._cont_keys)))
        try:
            mu, sigma, yn = gp_posterior(
                X, y, cand, self.lengthscale, self.noise
            )
        except np.linalg.LinAlgError:
            self._pending[trial_index] = self._encode(base)
            return base  # degenerate kernel: stay with the random sample

        # Expected improvement (minimization of normalized score), judged
        # against the best OBSERVED point — liars must not shift the
        # improvement baseline, only the posterior shape.
        best = yn[: len(y_obs)].min()
        from math import erf, sqrt

        z = (best - self.xi - mu) / sigma
        cdf = 0.5 * (1.0 + np.vectorize(erf)(z / sqrt(2.0)))
        pdf = np.exp(-0.5 * z**2) / np.sqrt(2 * np.pi)
        ei = sigma * (z * cdf + pdf)
        u_best = cand[int(np.argmax(ei))]
        config = self._apply(base, u_best)

        # Re-check joint constraints after the GP overrides continuous keys.
        if not all(c(config) for c in self.space.constraints):
            self._pending[trial_index] = self._encode(base)
            return base
        self._pending[trial_index] = u_best
        return config

    @staticmethod
    def _trial_index_of(trial_id) -> Optional[int]:
        # Both drivers name trials "trial_<index>"; pending bookkeeping
        # falls back to nearest-point matching when the id doesn't parse.
        try:
            return int(str(trial_id).rsplit("_", 1)[-1])
        except ValueError:
            return None

    def _clear_pending(self, trial_id, config) -> None:
        idx = self._trial_index_of(trial_id)
        if idx is not None:
            self._pending.pop(idx, None)
            return
        if not self._pending:
            return
        u = self._encode(config)
        nearest = min(
            self._pending,
            key=lambda k: float(((self._pending[k] - u) ** 2).sum()),
        )
        self._pending.pop(nearest, None)

    def save_state(self) -> Dict[str, Any]:
        # float64 → JSON shortest-repr → float64 round-trips exactly, so
        # the restored GP fit (and therefore the next suggestion) is
        # bit-identical to the uninterrupted run's.
        return {
            "X": [[float(v) for v in x] for x in self._X],
            "y": [float(v) for v in self._y],
            "pending": {
                str(k): [float(v) for v in u]
                for k, u in self._pending.items()
            },
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        self._X = [np.array(x, dtype=np.float64)
                   for x in state.get("X", [])]
        self._y = [float(v) for v in state.get("y", [])]
        self._pending = {
            int(k): np.array(u, dtype=np.float64)
            for k, u in state.get("pending", {}).items()
        }

    def on_trial_complete(self, trial_id, config, result, metric, mode):
        if not self._cont_keys:
            return
        # Errored trials observe nothing but must still leave the pending
        # set — a permanently-pending liar would dent the posterior there
        # for the rest of the sweep.
        self._clear_pending(trial_id, config)
        score = self._effective_score(result, metric, mode)
        if score is None:
            return
        self._X.append(self._encode(config))
        self._y.append(score)
