"""Search-algorithm interface: proposes configs, learns from completed trials.

Native replacement for Ray Tune's search algs (random sampling of the space
dict; ``BayesOptSearch`` at `ray-tune-hpo-regression.py:474`; SURVEY.md §2b D2).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from distributed_machine_learning_tpu.tune.search_space import SearchSpace


class Searcher:
    def set_search_space(self, space: SearchSpace, seed: int):
        self.space = space
        self.seed = seed

    def suggest(self, trial_index: int) -> Optional[Dict[str, Any]]:
        """Propose a config for trial #``trial_index``; None when exhausted."""
        raise NotImplementedError

    def fast_forward(self, num_trials: int) -> None:
        """Called on experiment resume with the number of trials already
        created in the prior run. Index-seeded searchers (random, TPE,
        BayesOpt) need nothing — suggest(i) is deterministic per index —
        but searchers with suggest-side state (GridSearch's cursor) must
        advance past configs already proposed or resume would re-propose
        the covered prefix of the space."""

    def _effective_score(self, result: Optional[Dict[str, Any]], metric: str,
                         mode: str) -> Optional[float]:
        """Resolve searcher-level metric/mode overrides against the experiment
        defaults and normalize so LOWER is always better; None if absent."""
        own_metric = getattr(self, "metric", None)
        own_mode = getattr(self, "mode", None)
        metric = own_metric if own_metric is not None else metric
        mode = own_mode if own_mode is not None else mode
        if not result or metric not in result:
            return None
        score = float(result[metric])
        return -score if mode == "max" else score

    def on_trial_result(self, trial_id: str, config: Dict[str, Any],
                        result: Dict[str, Any], metric: str, mode: str):
        """Per-epoch observation hook (multi-fidelity searchers, e.g. TPE/BOHB)."""
        pass

    def on_trial_complete(self, trial_id: str, config: Dict[str, Any],
                          result: Optional[Dict[str, Any]], metric: str, mode: str):
        pass

    def save_state(self) -> Dict[str, Any]:
        """JSON-serializable snapshot of ALL decision-relevant mutable
        state — journaled by ``tune/journal.py`` after every decision so
        a restarted head restores a bit-identical searcher (the WAL
        contract: ``restore_state(save_state())`` followed by
        ``suggest(i)`` must equal the uninterrupted ``suggest(i)``).
        Stateless searchers (RandomSearch — suggest is pure in the trial
        index) inherit this empty default."""
        return {}

    def restore_state(self, state: Dict[str, Any]) -> None:
        pass


class WarmStartSearcher(Searcher):
    """Evaluate given configs first, then delegate to the wrapped searcher.

    Ray's ``points_to_evaluate``: known-good or must-check configs (a
    previous sweep's best, a paper's setting) run as the first trials.
    Points may be PARTIAL configs — missing keys are sampled from the
    space, fixed keys are honored exactly (constraints still apply). The
    inner searcher sees a shifted trial index, so its proposal sequence is
    identical to a run without warm-start points, and it observes the
    point-trials' results through the usual hooks (model-based searchers
    learn from them).
    """

    def __init__(self, inner: Searcher, points):
        self.inner = inner
        self.points = [dict(p) for p in points]

    def set_search_space(self, space: SearchSpace, seed: int):
        super().set_search_space(space, seed)
        self.inner.set_search_space(space, seed)

    def suggest(self, trial_index: int) -> Optional[Dict[str, Any]]:
        if trial_index < len(self.points):
            return self.space.with_overrides(
                **self.points[trial_index]
            ).sample(("point", self.seed, trial_index))
        return self.inner.suggest(trial_index - len(self.points))

    def fast_forward(self, num_trials: int) -> None:
        self.inner.fast_forward(max(0, num_trials - len(self.points)))

    def on_trial_result(self, trial_id, config, result, metric, mode):
        self.inner.on_trial_result(trial_id, config, result, metric, mode)

    def on_trial_complete(self, trial_id, config, result, metric, mode):
        self.inner.on_trial_complete(trial_id, config, result, metric, mode)

    def save_state(self) -> Dict[str, Any]:
        # The points list is constructor state; only the inner model moves.
        return {"inner": self.inner.save_state()}

    def restore_state(self, state: Dict[str, Any]) -> None:
        self.inner.restore_state(state.get("inner", {}))


def maybe_warm_start(searcher: Searcher, points) -> Searcher:
    """The runners' shared ``points_to_evaluate`` hook: wrap when points
    are given, pass through otherwise.

    A ``Repeater`` must stay OUTERMOST: it maps completed trial ids back to
    repeat groups by index, and a WarmStartSearcher above it would shift
    the suggest indices without shifting the ids (groups would misalign and
    means would mix configs).  Composing the warm start INSIDE instead
    means each point config is itself repeated — the natural semantics for
    a noisy objective."""
    if not points:
        return searcher
    from distributed_machine_learning_tpu.tune.search.repeater import (
        Repeater,
    )

    if isinstance(searcher, Repeater):
        return Repeater(
            WarmStartSearcher(searcher.inner, points),
            repeat=searcher.repeat,
            seed_key=searcher.seed_key,
        )
    return WarmStartSearcher(searcher, points)


class RandomSearch(Searcher):
    """Seeded i.i.d. sampling of the search space (Ray's default variant
    generator)."""

    def suggest(self, trial_index: int) -> Dict[str, Any]:
        return self.space.sample(("random", self.seed, trial_index))


class GridSearch(Searcher):
    """Exhaustive cartesian product over Choice domains; non-choice domains are
    sampled per grid point (matching ray.tune.grid_search semantics)."""

    def set_search_space(self, space: SearchSpace, seed: int):
        super().set_search_space(space, seed)
        from itertools import product

        from distributed_machine_learning_tpu.tune.search_space import Choice

        keys, values = [], []
        for k, dom in space.space.items():
            if isinstance(dom, Choice):
                keys.append(k)
                values.append(list(dom.categories))
        self._grid_keys = keys
        self._grid_points = list(product(*values)) if keys else [()]

    def suggest(self, trial_index: int) -> Optional[Dict[str, Any]]:
        # Walk an internal cursor so infeasible grid points (fixed values that
        # violate a joint constraint) are skipped rather than crashing the run.
        cursor = getattr(self, "_cursor", 0)
        while cursor < len(self._grid_points):
            point = dict(zip(self._grid_keys, self._grid_points[cursor]))
            cursor += 1
            try:
                cfg = self.space.with_overrides(**point).sample(
                    ("grid", self.seed, cursor - 1)
                )
            except RuntimeError:
                continue  # no feasible completion of this grid point
            self._cursor = cursor
            return cfg
        self._cursor = cursor
        return None

    def fast_forward(self, num_trials: int) -> None:
        # Re-walk the cursor over the already-proposed prefix (identical
        # feasibility skipping), discarding the configs.
        for i in range(num_trials):
            if self.suggest(i) is None:
                break

    def save_state(self) -> Dict[str, Any]:
        return {"cursor": getattr(self, "_cursor", 0)}

    def restore_state(self, state: Dict[str, Any]) -> None:
        # Restoring the cursor directly (instead of fast_forward's re-walk)
        # lands on the identical next grid point without re-evaluating
        # feasibility — bit-identical by construction.
        self._cursor = int(state.get("cursor", 0))

    @property
    def num_points(self) -> int:
        return len(self._grid_points)
