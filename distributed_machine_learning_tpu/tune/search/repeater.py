"""Repeater: evaluate each suggested config several times, learn from means.

Ray Tune parity (``ray.tune.search.Repeater``): with a noisy objective —
dropout/init/shuffle randomness at small data sizes — a single trial's
validation score is a high-variance draw, and a model-based searcher
(BayesOpt/TPE) fitted on single draws chases noise.  The Repeater wraps any
searcher: every config it proposes runs ``repeat`` times under different
seeds, and the wrapped searcher observes ONE completion per config with the
averaged score, so its model fits the mean objective.

`tune.report`'s per-trial records are unchanged (each repeat is an ordinary
trial in the experiment directory); only what the wrapped searcher learns is
aggregated.  Relies on the framework-wide trial naming contract
``trial_{index:05d}`` with ids minted in suggest order (tune/_driver.py:96,
vectorized.py, cluster worker protocol) to map completions back to repeat
groups.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from distributed_machine_learning_tpu.tune.search.base import Searcher
from distributed_machine_learning_tpu.tune.search_space import SearchSpace
from distributed_machine_learning_tpu.utils.numeric import finite_number
from distributed_machine_learning_tpu.utils.seeding import fold_seed

_TRIAL_ID_RE = re.compile(r"(\d+)$")


class Repeater(Searcher):
    """Wrap ``inner`` so each of its configs runs ``repeat`` times.

    ``seed_key``: the config key the repeats vary (default ``"seed"`` — the
    trainable's data-shuffle/init/dropout seed).  Repeat #0 keeps the
    proposed seed; later repeats fold the repeat number into it, so a
    Repeater sweep is deterministic in the experiment seed.
    """

    def __init__(self, inner: Searcher, repeat: int = 3,
                 seed_key: str = "seed"):
        if repeat < 1:
            raise ValueError(f"repeat must be >= 1, got {repeat}")
        self.inner = inner
        self.repeat = int(repeat)
        self.seed_key = seed_key
        self._group_configs: Dict[int, Dict[str, Any]] = {}
        self._group_scores: Dict[int, List[Optional[float]]] = {}

    def set_search_space(self, space: SearchSpace, seed: int):
        super().set_search_space(space, seed)
        self.inner.set_search_space(space, seed)

    def suggest(self, trial_index: int) -> Optional[Dict[str, Any]]:
        group, k = divmod(trial_index, self.repeat)
        if group not in self._group_configs:
            base = self.inner.suggest(group)
            if base is None:
                return None
            self._group_configs[group] = dict(base)
        config = dict(self._group_configs[group])
        if k > 0:
            base_seed = config.get(self.seed_key, 0)
            config[self.seed_key] = fold_seed(
                int(base_seed) if base_seed is not None else 0, "repeat", k
            )
        return config

    def fast_forward(self, num_trials: int) -> None:
        # Floor: fully-created groups advance the inner searcher's cursor;
        # a partially-created group is re-suggested fresh (its members that
        # DID finish replay through on_trial_complete as usual).
        self.inner.fast_forward(num_trials // self.repeat)

    def on_trial_result(self, trial_id, config, result, metric, mode):
        # Intentionally not forwarded: per-epoch values of a single repeat
        # are exactly the noise the averaging exists to remove.
        pass

    def save_state(self) -> Dict[str, Any]:
        return {
            "inner": self.inner.save_state(),
            "group_configs": {
                str(g): dict(c) for g, c in self._group_configs.items()
            },
            "group_scores": {
                str(g): list(s) for g, s in self._group_scores.items()
            },
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        self.inner.restore_state(state.get("inner", {}))
        self._group_configs = {
            int(g): dict(c)
            for g, c in state.get("group_configs", {}).items()
        }
        self._group_scores = {
            int(g): list(s)
            for g, s in state.get("group_scores", {}).items()
        }

    def on_trial_complete(self, trial_id, config, result, metric, mode):
        m = _TRIAL_ID_RE.search(trial_id or "")
        if not m:  # foreign id (not a framework trial): nothing to map
            return
        group = int(m.group(1)) // self.repeat
        # Resolve a searcher-level metric override through WRAPPER layers
        # (maybe_warm_start may interpose a WarmStartSearcher between this
        # Repeater and the model-based searcher that owns the override).
        owner = self.inner
        while getattr(owner, "metric", None) is None and hasattr(
            owner, "inner"
        ):
            owner = owner.inner
        eff_metric = getattr(owner, "metric", None) or metric
        value = (
            finite_number(result.get(eff_metric))
            if result is not None else None
        )
        scores = self._group_scores.setdefault(group, [])
        scores.append(value)
        if len(scores) < self.repeat:
            return
        finite = [v for v in scores if v is not None]
        base = self._group_configs.get(group, dict(config))
        # One completion per GROUP reaches the wrapped searcher: the mean
        # over the repeats that produced a score (None = errored repeat),
        # or an errored completion when every repeat failed.
        mean_result = (
            {eff_metric: sum(finite) / len(finite)} if finite else None
        )
        self.inner.on_trial_complete(
            f"repeat_group_{group:05d}", base, mean_result, metric, mode
        )
        del self._group_scores[group]
        # A dispatched group can never be suggested or completed again
        # (indices are monotonic) — don't cache its config forever.
        self._group_configs.pop(group, None)
