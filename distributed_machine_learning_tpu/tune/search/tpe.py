"""TPE (Tree-structured Parzen Estimator) search — the model half of BOHB.

The reference's only model-based search was a broken BayesOpt-over-categoricals
(`ray-tune-hpo-regression.py:474`; SURVEY.md §2b D2).  TPE (Bergstra et al.
2011) handles the mixed continuous/categorical spaces the reference actually
declares: observations are split into a *good* set (top ``gamma`` quantile by
score) and a *bad* set; candidates are drawn from a Parzen (kernel-density)
model of the good set and ranked by the density ratio l(x)/g(x).

BOHB twist (Falkner et al. 2018): with a multi-fidelity scheduler reporting
per-epoch results, the model is fit on the observations from the **largest
budget** (``training_iteration``) that has at least ``min_points`` samples, so
early-stopped trials still inform the model without drowning out full-budget
signal.  Per-epoch observations arrive through ``on_trial_result``.

Pure numpy; 1-D kernels per hyperparameter:

* continuous domains (uniform/loguniform) — Gaussian KDE in the unit cube
  (bandwidth per Scott's rule, floored), reflected at the [0,1] borders;
* ``choice`` domains — smoothed categorical frequencies;
* other/int domains — resampled from the prior (random), as in hyperopt.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from distributed_machine_learning_tpu.tune.search.base import Searcher
from distributed_machine_learning_tpu.tune.search_space import Choice, SearchSpace
from distributed_machine_learning_tpu.utils.seeding import rng_from


class _ParzenModel:
    """Per-key 1-D Parzen densities over one observation set."""

    def __init__(self, configs: List[Dict[str, Any]], space: SearchSpace,
                 cont_keys: List[str], cat_keys: List[str]):
        self.space = space
        self.cont_keys = cont_keys
        self.cat_keys = cat_keys
        # Continuous: unit-cube coordinates per key.
        self.cont: Dict[str, np.ndarray] = {}
        self.bw: Dict[str, float] = {}
        self._pts: Dict[str, np.ndarray] = {}  # observations + border reflections
        for k in cont_keys:
            x = np.array(
                [space.domain(k).to_unit(c[k]) for c in configs], dtype=np.float64
            )
            self.cont[k] = x
            n = max(len(x), 1)
            scott = n ** (-0.2) * (x.std() + 1e-3)
            self.bw[k] = float(np.clip(scott, 0.05, 0.5))
            self._pts[k] = np.concatenate([x, -x, 2.0 - x])
        # Categorical: smoothed counts.
        self.cat: Dict[str, np.ndarray] = {}
        self._cats: Dict[str, list] = {}
        self._cat_index: Dict[str, Dict[Any, int]] = {}
        for k in cat_keys:
            cats = list(space.domain(k).categories)
            self._cats[k] = cats
            self._cat_index[k] = {v: i for i, v in enumerate(cats)}
            counts = np.ones(len(cats), dtype=np.float64)  # +1 smoothing
            for c in configs:
                idx = self._cat_index[k].get(c[k])
                if idx is not None:
                    counts[idx] += 1.0
                # else: value came from an override outside the domain
            self.cat[k] = counts / counts.sum()

    def sample_cont(self, k: str, rng: np.random.Generator) -> float:
        x = self.cont[k]
        if len(x) == 0:
            return float(rng.random())
        center = float(x[int(rng.integers(len(x)))])
        u = rng.normal(center, self.bw[k])
        # Reflect at the borders (modular fold handles multiple bounces so a
        # draw past 2.0 folds back toward 1.0, not to the opposite border).
        u = abs(u) % 2.0
        if u > 1.0:
            u = 2.0 - u
        return float(u)

    def logpdf_cont(self, k: str, u: float) -> float:
        x = self.cont[k]
        if len(x) == 0:
            return 0.0
        bw = self.bw[k]
        # Mixture of Gaussians at observations (+ reflections at 0 and 1).
        z = (u - self._pts[k]) / bw
        dens = np.exp(-0.5 * z**2).sum() / (len(x) * bw * np.sqrt(2 * np.pi))
        return float(np.log(dens + 1e-12))

    def sample_cat(self, k: str, rng: np.random.Generator) -> Any:
        cats = self._cats[k]
        return cats[int(rng.choice(len(cats), p=self.cat[k]))]

    def logpdf_cat(self, k: str, value: Any) -> float:
        idx = self._cat_index[k].get(value)
        if idx is None:
            return float(np.log(1e-12))
        return float(np.log(self.cat[k][idx] + 1e-12))


class TPESearch(Searcher):
    """TPE over the declared search space; BOHB when paired with
    :class:`~...schedulers.hyperband.HyperBandScheduler`."""

    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        n_initial_points: int = 10,
        gamma: float = 0.25,
        num_candidates: int = 64,
        min_points: int = 8,
    ):
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.num_candidates = num_candidates
        self.min_points = min_points
        # budget (training_iteration) -> list of (score, config); one entry per
        # trial per budget, latest report wins.
        self._obs: Dict[int, Dict[str, Tuple[float, Dict[str, Any]]]] = {}

    def set_search_space(self, space: SearchSpace, seed: int):
        super().set_search_space(space, seed)
        self._cont_keys = space.continuous_keys()
        self._cat_keys = [
            k for k, v in space.space.items() if isinstance(v, Choice)
        ]

    # -- observation ingestion ------------------------------------------------
    def _record(self, trial_id: str, config: Dict[str, Any],
                result: Optional[Dict[str, Any]], metric: str, mode: str):
        score = self._effective_score(result, metric, mode)
        if score is None or not np.isfinite(score):
            return
        budget = int(result.get("training_iteration", 1))
        self._obs.setdefault(budget, {})[trial_id] = (score, dict(config))

    def on_trial_result(self, trial_id: str, config: Dict[str, Any],
                        result: Dict[str, Any], metric: str, mode: str):
        self._record(trial_id, config, result, metric, mode)

    def on_trial_complete(self, trial_id, config, result, metric, mode):
        self._record(trial_id, config, result, metric, mode)

    def save_state(self) -> Dict[str, Any]:
        # JSON keys must be strings; budgets are ints — stringify on the
        # way out, int() on the way back.
        return {
            "obs": {
                str(budget): {
                    tid: [score, config]
                    for tid, (score, config) in per_trial.items()
                }
                for budget, per_trial in self._obs.items()
            },
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        self._obs = {
            int(budget): {
                tid: (float(sc[0]), dict(sc[1]))
                for tid, sc in per_trial.items()
            }
            for budget, per_trial in state.get("obs", {}).items()
        }

    # -- model ----------------------------------------------------------------
    def _training_set(self) -> List[Tuple[float, Dict[str, Any]]]:
        """Observations at the largest budget with >= min_points samples."""
        for budget in sorted(self._obs, reverse=True):
            if len(self._obs[budget]) >= self.min_points:
                return list(self._obs[budget].values())
        # Fall back to the most-populated budget.
        if self._obs:
            best = max(self._obs.values(), key=len)
            return list(best.values())
        return []

    def suggest(self, trial_index: int) -> Optional[Dict[str, Any]]:
        base = self.space.sample(("tpe", self.seed, trial_index))
        obs = self._training_set()
        if len(obs) < max(self.n_initial, 2) or not (
            self._cont_keys or self._cat_keys
        ):
            return base

        rng = rng_from("tpe-model", self.seed, trial_index)
        obs.sort(key=lambda sc: sc[0])
        n_good = max(1, int(np.ceil(self.gamma * len(obs))))
        good = [c for _, c in obs[:n_good]]
        bad = [c for _, c in obs[n_good:]] or good
        l = _ParzenModel(good, self.space, self._cont_keys, self._cat_keys)
        g = _ParzenModel(bad, self.space, self._cont_keys, self._cat_keys)

        # Score candidate override-sets by density ratio, then resolve the
        # winners through the space so sample_from keys that depend on the
        # overridden values (e.g. dim_feedforward = d_model * k) re-resolve
        # and joint constraints are enforced.
        scored: List[Tuple[float, Dict[str, Any]]] = []
        for _ in range(self.num_candidates):
            over: Dict[str, Any] = {}
            ratio = 0.0
            for k in self._cont_keys:
                u = l.sample_cont(k, rng)
                over[k] = self.space.domain(k).from_unit(u)
                ratio += l.logpdf_cont(k, u) - g.logpdf_cont(k, u)
            for k in self._cat_keys:
                v = l.sample_cat(k, rng)
                over[k] = v
                ratio += l.logpdf_cat(k, v) - g.logpdf_cat(k, v)
            scored.append((ratio, over))
        scored.sort(key=lambda ro: -ro[0])
        for _, over in scored:
            try:
                return self.space.with_overrides(**over).sample(
                    ("tpe-resolve", self.seed, trial_index)
                )
            except RuntimeError:
                continue  # overrides violate joint constraints; try next best
        return base
