from distributed_machine_learning_tpu.tune.search.base import (
    GridSearch,
    RandomSearch,
    Searcher,
    WarmStartSearcher,
)
from distributed_machine_learning_tpu.tune.search.bayesopt import BayesOptSearch
from distributed_machine_learning_tpu.tune.search.repeater import Repeater
from distributed_machine_learning_tpu.tune.search.tpe import TPESearch

__all__ = ["Searcher", "RandomSearch", "GridSearch", "BayesOptSearch",
           "TPESearch", "WarmStartSearcher", "Repeater"]
