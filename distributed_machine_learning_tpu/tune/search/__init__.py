from distributed_machine_learning_tpu.tune.search.base import (
    GridSearch,
    RandomSearch,
    Searcher,
)
from distributed_machine_learning_tpu.tune.search.bayesopt import BayesOptSearch

__all__ = ["Searcher", "RandomSearch", "GridSearch", "BayesOptSearch"]
