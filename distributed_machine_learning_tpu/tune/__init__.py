"""Public tune API.

Mirrors the surface the reference consumed from Ray Tune
(`ray-tune-hpo-regression.py:7-9, 373, 379-400, 469-478`):

    from distributed_machine_learning_tpu import tune

    analysis = tune.run(
        tune.with_parameters(my_trainable, train_data=..., val_data=...),
        param_space={"lr": tune.loguniform(1e-5, 1e-2), ...},
        metric="validation_mape", mode="min", num_samples=50,
        scheduler=tune.ASHAScheduler(...),
        search_alg=tune.BayesOptSearch(...),
    )
    print(analysis.best_config)
"""

from distributed_machine_learning_tpu.tune.callbacks import (
    Callback,
    JsonlCallback,
    LoggerCallback,
    ProfilerCallback,
    ProgressReporter,
    TensorBoardCallback,
)
from distributed_machine_learning_tpu.tune.experiment import (
    ExperimentAnalysis,
    ExperimentStore,
)
from distributed_machine_learning_tpu.tune.runner import run
from distributed_machine_learning_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.search import (
    BayesOptSearch,
    Repeater,
    GridSearch,
    RandomSearch,
    Searcher,
    TPESearch,
    WarmStartSearcher,
)
from distributed_machine_learning_tpu.tune.stoppers import (
    MaximumIterationStopper,
    Stopper,
    TrialPlateauStopper,
)
from distributed_machine_learning_tpu.tune.search_space import (
    Constraint,
    SearchSpace,
    choice,
    constant,
    loguniform,
    lograndint,
    qloguniform,
    qrandint,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from distributed_machine_learning_tpu.tune.session import (
    get_checkpoint,
    get_devices,
    get_trial_id,
    heartbeat,
    report,
    standalone,
    with_parameters,
)
from distributed_machine_learning_tpu.tune.trainable import (
    clear_cohort_program_cache,
    train_regressor,
)
from distributed_machine_learning_tpu.tune.trainable_sharded import (
    train_sharded_regressor,
)
from distributed_machine_learning_tpu.tune.vectorized import (
    clear_program_cache as _clear_vectorized_program_cache,
    run_vectorized,
)
from distributed_machine_learning_tpu.tune.trial import Resources, Trial, TrialStatus


def clear_program_cache() -> None:
    """Free every cached traced program and its staged device data: the
    vectorized runner's cross-call cache AND tune.run's cohort cache
    (one call frees everything that pins device memory)."""
    _clear_vectorized_program_cache()
    clear_cohort_program_cache()

__all__ = [
    "run",
    "clear_program_cache",
    "clear_cohort_program_cache",
    "run_vectorized",
    "report",
    "heartbeat",
    "get_checkpoint",
    "get_devices",
    "get_trial_id",
    "standalone",
    "with_parameters",
    "train_regressor",
    "train_sharded_regressor",
    "choice",
    "uniform",
    "loguniform",
    "quniform",
    "qloguniform",
    "randint",
    "qrandint",
    "lograndint",
    "randn",
    "sample_from",
    "constant",
    "Constraint",
    "SearchSpace",
    "ASHAScheduler",
    "HyperBandScheduler",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "TrialScheduler",
    "RandomSearch",
    "GridSearch",
    "BayesOptSearch",
    "Repeater",
    "TPESearch",
    "WarmStartSearcher",
    "Stopper",
    "TrialPlateauStopper",
    "MaximumIterationStopper",
    "Searcher",
    "ExperimentAnalysis",
    "ExperimentStore",
    "Callback",
    "LoggerCallback",
    "JsonlCallback",
    "ProfilerCallback",
    "ProgressReporter",
    "TensorBoardCallback",
    "Resources",
    "Trial",
    "TrialStatus",
]
