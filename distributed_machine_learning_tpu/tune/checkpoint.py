"""Pytree checkpoint save/restore.

The reference has no checkpointing at all (SURVEY.md §5: no torch.save/load,
no ``tune.checkpoint_dir`` anywhere); PBT and preemption-aware recovery make it
first-class here.  Format: flax msgpack for the array pytree (framework- and
process-portable, no pickle), written atomically so a preempted write never
leaves a truncated checkpoint behind.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, Optional

import jax
import numpy as np
from flax import serialization


def _to_host(tree):
    """Device arrays -> numpy so serialization never hangs on device buffers."""
    return jax.tree.map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, tree
    )


def save_checkpoint(path: str, tree: Dict[str, Any]) -> str:
    """Serialize a pytree dict to ``path`` atomically. Returns the path."""
    payload = serialization.to_bytes(_to_host(tree))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)  # atomic on POSIX
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_checkpoint(path: str) -> Optional[Dict[str, Any]]:
    """Decode a checkpoint without needing a target template (msgpack restore)."""
    if not path or not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


def restore_into(template, tree: Dict[str, Any]):
    """Restore a raw decoded dict into ``template``'s pytree structure/dtypes."""
    return serialization.from_state_dict(template, tree)
