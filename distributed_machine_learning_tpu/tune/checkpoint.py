"""Pytree checkpoint save/restore over pluggable storage — the
compatibility shim over the :mod:`distributed_machine_learning_tpu.ckpt`
subsystem.

Two on-disk formats, one API:

* **legacy msgpack blob** (``ckpt_NNNNNN.msgpack`` + ``.manifest.json``
  sha256 sidecar) — flax msgpack of the whole pytree, written atomically;
  the format every pre-``ckpt/`` experiment on disk already uses.
* **sharded generation** (``gen_NNNNNN/`` — per-shard chunk files + JSON
  index + COMMIT marker, ``ckpt/format.py``) — async-friendly and
  topology-portable (restore onto a different mesh/device count).

``save_checkpoint``/``load_checkpoint`` dispatch on the path;
generation-walking logic (``find_latest_checkpoint``,
``newest_valid_checkpoint``, ``load_checkpoint_with_fallback``,
``prune_checkpoints``) delegates to ``ckpt.manager``, which understands
both formats in one directory — so executors, cluster requeue, resume, and
serve export all keep their call sites while gaining sharded checkpoints.
Which format new checkpoints use is the caller's choice via
``checkpoint_path(..., checkpoint_format=...)`` (``tune.run`` exposes it).

No pickle anywhere on this path — both formats stay process- and
framework-portable (enforced by the import-guard test in CI).
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import re
import threading
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.ckpt import format as _sharded_fmt
from distributed_machine_learning_tpu.ckpt.format import (  # noqa: F401
    CheckpointCorruptionError,
)
from distributed_machine_learning_tpu.ckpt.metrics import get_metrics
from distributed_machine_learning_tpu.tune.storage import get_storage

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")

MANIFEST_SUFFIX = ".manifest.json"


def manifest_path_for(path: str) -> str:
    return path + MANIFEST_SUFFIX


def _to_host(tree):
    """Device arrays -> numpy so serialization never hangs on device buffers."""
    return jax.tree.map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, tree
    )


def _is_sharded(path: str) -> bool:
    """Format dispatch for one path: generation-dir name, else the
    ``.msgpack`` suffix decides cheaply, else probe for an index file."""
    base = os.path.basename(str(path).rstrip("/"))
    if _sharded_fmt.GEN_RE.match(base):
        return True
    if base.endswith(".msgpack") or base.endswith(".ckpt"):
        return False
    return _sharded_fmt.is_sharded_path(path)


def save_checkpoint(path: str, tree: Dict[str, Any]) -> str:
    """Serialize a pytree dict to ``path`` (any storage scheme). Returns path.

    A ``gen_NNNNNN`` path writes the sharded chunked format (atomic COMMIT
    protocol); anything else writes the legacy msgpack blob whose
    ``<path>.manifest.json`` sidecar (sha256 + byte count) lands AFTER the
    payload — a crash between the two leaves a checkpoint that is merely
    unverifiable, never a manifest pointing at absent data.
    """
    from distributed_machine_learning_tpu import obs

    if _is_sharded(path):
        with obs.span("ckpt.save", {"format": "sharded"}):
            return _sharded_fmt.save_sharded(path, tree)
    with obs.span("ckpt.save", {"format": "msgpack"}):
        t0 = time.time()
        payload = serialization.to_bytes(_to_host(tree))
        backend, p = get_storage(path)
        backend.write_bytes(p, payload)
        manifest = {
            "sha256": hashlib.sha256(payload).hexdigest(),
            "bytes": len(payload),
            "format": "flax-msgpack",
        }
        backend.write_bytes(
            manifest_path_for(p), json.dumps(manifest).encode()
        )
        get_metrics().record_save(time.time() - t0, len(payload), 1)
    return path


def load_checkpoint(
    path: str, verify: bool = True, shardings=None,
) -> Optional[Dict[str, Any]]:
    """Decode a checkpoint without needing a target template.

    Sharded generations restore through ``ckpt.format.load_sharded`` —
    pass ``shardings`` to reshard array leaves onto a target mesh; without
    it arrays gather to full numpy (bit-identical to what was saved,
    whatever topology saved it).  Legacy blobs ignore ``shardings`` (they
    are host-gathered by construction).

    With ``verify`` (default) integrity is checked before decoding —
    manifest sha256 for msgpack (a missing manifest demotes to
    decode-checking), COMMIT + per-chunk sha256 for sharded — and damage
    raises :class:`CheckpointCorruptionError`.
    """
    if not path:
        return None
    from distributed_machine_learning_tpu import obs

    if _is_sharded(path):
        with obs.span("ckpt.restore", {"format": "sharded"}):
            return _sharded_fmt.load_sharded(
                path, verify=verify, shardings=shardings
            )
    with obs.span("ckpt.restore", {"format": "msgpack"}):
        return _load_msgpack(path, verify)


def _load_msgpack(path: str, verify: bool) -> Optional[Dict[str, Any]]:
    t0 = time.time()
    backend, p = get_storage(path)
    data = backend.read_bytes(p)
    if data is None:
        return None
    if verify:
        raw = backend.read_bytes(manifest_path_for(p))
        if raw is not None:
            try:
                expected = json.loads(raw).get("sha256")
            except ValueError:
                expected = None
            if expected is not None and (
                hashlib.sha256(data).hexdigest() != expected
            ):
                raise CheckpointCorruptionError(
                    f"checksum mismatch for {path} "
                    f"({len(data)} bytes on storage)"
                )
        try:
            tree = serialization.msgpack_restore(data)
        except Exception as exc:  # noqa: BLE001 - damaged bytes, any decoder error
            raise CheckpointCorruptionError(
                f"undecodable checkpoint at {path}: {exc!r}"
            ) from exc
        get_metrics().record_restore(time.time() - t0, len(data))
        return tree
    tree = serialization.msgpack_restore(data)
    get_metrics().record_restore(time.time() - t0, len(data))
    return tree


def verify_checkpoint(path: str) -> bool:
    """True if ``path`` exists and passes its integrity checks."""
    try:
        return load_checkpoint(path) is not None
    except CheckpointCorruptionError:
        return False


def _iteration_of(path: str) -> int:
    from distributed_machine_learning_tpu.ckpt.manager import step_of_path

    return step_of_path(path)


def load_checkpoint_with_fallback(
    path: Optional[str], directory: Optional[str] = None, log=None,
    shardings=None,
) -> Tuple[Optional[Dict[str, Any]], Optional[str], int]:
    """Restore ``path``; on corruption fall back to the newest
    valid generation (either format) under ``directory``.

    Returns ``(tree, used_path, used_iteration)`` — ``(None, None, 0)``
    when nothing restorable survives (the caller restarts from scratch).
    The corrupt file is left in place (forensics; retention prunes it like
    any old generation) — callers must rewind their iteration bookkeeping
    to ``used_iteration``.
    """
    from distributed_machine_learning_tpu.ckpt.manager import (
        restore_with_fallback,
    )

    emit = log or (lambda msg: print(f"[checkpoint] {msg}", flush=True))
    return restore_with_fallback(path, directory, log=emit,
                                 shardings=shardings)


def restore_into(template, tree: Dict[str, Any]):
    """Restore a raw decoded dict into ``template``'s pytree structure/dtypes."""
    return serialization.from_state_dict(template, tree)


def checkpoint_path(directory: str, iteration: int,
                    checkpoint_format: str = "msgpack") -> str:
    from distributed_machine_learning_tpu.ckpt.manager import step_path

    return step_path(directory, iteration, checkpoint_format)


def find_latest_checkpoint(directory: str):
    """(path, iteration) of the newest generation (either format) under
    ``directory``, or (None, 0) when there is none — how a resumed
    experiment rediscovers each trial's restore point."""
    from distributed_machine_learning_tpu.ckpt.manager import (
        latest_generation,
    )

    return latest_generation(directory)


def newest_valid_checkpoint(directory: str, max_iteration=None):
    """(path, iteration) of the newest generation that PASSES its
    integrity check, or (None, 0).  The restore target for trials requeued
    off a silent worker (cluster lease expiry / stall fencing): the lost
    incarnation may have died mid-write, so the newest entry on disk is
    not necessarily a loadable one — sharded generations must be COMMITTED
    and checksum-clean, msgpack blobs must match their manifest.
    ``max_iteration`` skips generations above it (the at-least-once
    fencing guard — see ``quarantine_unreported``)."""
    from distributed_machine_learning_tpu.ckpt.manager import (
        newest_valid_generation,
    )

    return newest_valid_generation(directory, max_step=max_iteration)


def quarantine_unreported(directory: str, last_reported_iteration: int,
                          tag: str = "", log=None) -> int:
    """Rename every generation newer than ``last_reported_iteration`` out
    of the generation namespace (prefix ``fenced[.tag].``) — they were
    written by a fenced/expired incarnation for epochs whose reports never
    reached the driver, and restoring one would skip those reports forever
    (the at-least-once fencing race, docs/operations.md).  Returns the
    count quarantined; bytes stay on storage for forensics."""
    from distributed_machine_learning_tpu.ckpt.manager import (
        quarantine_generations_above,
    )

    return quarantine_generations_above(
        directory, last_reported_iteration, tag=tag, log=log
    )


def cleanup_uncommitted(directory: str, log=None) -> int:
    """Remove torn sharded generations (no COMMIT) — safe only at start,
    before any writer is live.  See ``ckpt.manager.cleanup_uncommitted``."""
    from distributed_machine_learning_tpu.ckpt.manager import (
        cleanup_uncommitted as _cleanup,
    )

    return _cleanup(directory, log=log)


def _abspath_unless_remote(path: str) -> str:
    """abspath local paths only — os.path.abspath would mangle gs://-style
    URLs into '<cwd>/gs:/...' (orbax handles remote schemes itself)."""
    if re.match(r"^[a-z0-9]+://", path):
        return path
    return os.path.abspath(path)


def export_orbax(checkpoint_path: str, out_dir: str) -> str:
    """Convert a framework checkpoint to an orbax StandardCheckpoint.

    Interop bridge OUT of the framework: the pytree (params / opt_state /
    batch_stats / scalars) becomes a directory any orbax-consuming JAX
    stack restores directly — handing a tuned model to a separate
    serving/fine-tuning codebase without importing this package.  Works
    from either format (a sharded generation gathers first).  Returns
    ``out_dir``. Raises ImportError if orbax is absent (it is an optional
    dependency).
    """
    import orbax.checkpoint as ocp

    tree = load_checkpoint(checkpoint_path)
    if tree is None:
        raise FileNotFoundError(f"no checkpoint at {checkpoint_path!r}")
    out_dir = _abspath_unless_remote(out_dir)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(out_dir, tree)
    return out_dir


def import_orbax(src_dir: str) -> Dict[str, Any]:
    """Restore an orbax StandardCheckpoint into a raw pytree dict —
    the inverse bridge (``restore_into`` then shapes it to a template)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(_abspath_unless_remote(src_dir))


class AsyncCheckpointWriter:
    """Overlap checkpoint writes with training (orbax-style async save).

    ``submit(path, tree)`` returns immediately; the device->host transfer,
    serialization, and storage write run on ONE background thread, in
    submission order (both formats — a ``gen_NNNNNN`` path writes the
    sharded chunked format).  The trial thread goes straight back to
    training — at real checkpoint sizes the epoch that used to stall
    behind the write now runs concurrently with it, which the
    ``ckpt.metrics`` overlap counters make observable.

    Correctness contract (why this is safe in-process):
    * ``submit`` snapshots EVERY array leaf: jax arrays get a device-side
      copy (cheap — HBM bandwidth; the D2H transfer stays on the writer
      thread), because the caller's train step donates its buffers
      (``donate_argnums``) and the next step would delete the submitted
      arrays out from under the serializer ("Array has been deleted" —
      donation is a no-op on CPU, so only real TPU runs hit it). Mutable
      numpy leaves are host-copied for the same reason.
    * A reader who might race a pending write (retry restore, PBT exploit
      of a peer's checkpoint) calls ``wait(path)`` first; the threaded
      executor routes every restore through it. Cross-process restores
      (cluster workers) keep synchronous saves instead — a remote reader
      cannot wait on this process's queue.
    * Write errors re-raise on ``wait``; ``close`` logs any unclaimed
      errors through ``log`` (or re-raises with ``raise_errors=True``) —
      never a silent drop.
    """

    def __init__(self, log=None):
        self._q: "queue.Queue" = queue.Queue()
        self._lock = named_lock("tune.checkpoint.writer")
        self._pending: Dict[str, threading.Event] = {}
        self._errors: Dict[str, BaseException] = {}
        self._log = log or (lambda msg: print(
            f"[checkpoint] {msg}", flush=True
        ))
        self._thread = threading.Thread(
            target=self._worker, name="ckpt-writer", daemon=True
        )
        self._thread.start()

    def _worker(self):
        metrics = get_metrics()
        while True:
            item = self._q.get()
            if item is None:
                return
            path, tree, done, steps_at_submit = item
            try:
                save_checkpoint(path, tree)
                metrics.record_async_completion(steps_at_submit)
            except BaseException as exc:  # noqa: BLE001 - surfaced on wait
                metrics.add("save_errors")
                with self._lock:
                    self._errors[path] = exc
            finally:
                with self._lock:
                    self._pending.pop(path, None)
                done.set()

    @staticmethod
    def _snapshot_leaf(x):
        # jax.Array.copy() is a device-side copy: donation of the original
        # cannot delete it, and the D2H read stays on the writer thread.
        if isinstance(x, (jax.Array, np.ndarray)):
            return x.copy()
        return x

    def submit(self, path: str, tree: Dict[str, Any]) -> str:
        """Enqueue a write; returns ``path`` immediately."""
        metrics = get_metrics()
        t0 = time.time()
        snapshot = jax.tree.map(self._snapshot_leaf, tree)
        metrics.add("save_block_s", time.time() - t0)
        done = threading.Event()
        with self._lock:
            self._pending[path] = done
        self._q.put((path, snapshot, done, metrics.step_count()))
        return path

    def wait(self, path: Optional[str] = None,
             timeout: Optional[float] = None) -> bool:
        """Block until ``path`` (or every pending write) is durable; re-raise
        its write error if one occurred. Returns False if ``timeout``
        expired with writes still pending."""
        deadline = None if timeout is None else time.monotonic() + timeout
        if path is None:
            with self._lock:
                events = list(self._pending.values())
            for ev in events:
                left = None if deadline is None else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                if not ev.wait(left):
                    return False
            with self._lock:
                # Pop only what we surface: the raised error is claimed —
                # re-raising it on every later wait() (and re-logging at
                # close()) turns one bad write into a permanent poison
                # (advisor r3). Other paths' errors stay claimable so they
                # are never silently dropped.
                first = next(iter(self._errors), None)
                err = self._errors.pop(first, None) if first else None
            if err is not None:
                raise err
            return True
        with self._lock:
            ev = self._pending.get(path)
        if ev is not None and not ev.wait(
            None if deadline is None else max(deadline - time.monotonic(), 0.0)
        ):
            return False
        with self._lock:
            err = self._errors.pop(path, None)
        if err is not None:
            raise err
        return True

    def close(self, raise_errors: bool = False,
              timeout: Optional[float] = 30.0) -> None:
        """Flush pending writes (bounded by ``timeout``) and stop the worker.

        Unclaimed write errors are logged (or re-raised when
        ``raise_errors``); a write still hung at the deadline is abandoned
        with a log line rather than blocking teardown forever.
        """
        if not self._thread.is_alive():
            return
        flushed = True
        try:
            flushed = self.wait(timeout=timeout)
        except BaseException as exc:
            if raise_errors:
                self._q.put(None)
                self._thread.join(timeout=10)
                raise
            # wait() popped (claimed) the error it raised; surface it here
            # so an unclaimed failure is never silently dropped.
            self._log(
                "WARNING: checkpoint write(s) failed and were never "
                f"waited on; first: {exc!r}"
            )
        if not flushed:
            with self._lock:
                stuck = list(self._pending)
            self._log(
                f"WARNING: abandoning {len(stuck)} hung checkpoint "
                f"write(s) at teardown: {stuck[:3]}"
            )
        # Errors for writes that completed while wait() was timing out on a
        # different pending path can still be unclaimed — log those too.
        with self._lock:
            errors = dict(self._errors)
            self._errors.clear()
        if errors and not raise_errors:
            first_path, first_err = next(iter(errors.items()))
            self._log(
                f"WARNING: {len(errors)} checkpoint write(s) failed and "
                f"were never waited on; first: {first_path}: {first_err!r}"
            )
        self._q.put(None)
        # Only wait for the worker when the queue actually drained — a hung
        # write would pin this join for its full timeout, and the thread is
        # a daemon, so abandoning it is safe.
        if flushed:
            self._thread.join(timeout=10)


def prune_checkpoints(directory: str, keep: int, protect=None,
                      pending_latest: Optional[str] = None) -> int:
    """Keep only the ``keep`` newest generations (either format) in
    ``directory``.

    ``protect`` (a full path, or an iterable of them) is never deleted even if
    old — e.g. a checkpoint another trial's PBT exploit is about to restore.
    ``pending_latest``: a checkpoint path submitted to the async writer but
    possibly not on disk yet — behaviorally an alias for a ``protect`` entry,
    kept as the call-site's declaration of an in-flight write.  While it is
    in flight the newest ``keep`` DURABLE generations are all retained —
    deleting them against a write that may still fail (crash, preemption,
    storage error) could leave the trial with zero restorable checkpoints,
    exactly the scenario checkpointing covers.  The on-disk set transiently
    overshoots by up to the executor's write-pipeline depth (``keep``+2
    with the depth-2 pipeline) while writes land; later prunes — and the
    runner's final retention pass after the writer drains — converge it
    back to exactly ``keep``.
    Returns the number of generations deleted.
    """
    from distributed_machine_learning_tpu.ckpt.manager import (
        prune_generations,
    )

    return prune_generations(directory, keep, protect=protect,
                             pending_latest=pending_latest)
