"""Pytree checkpoint save/restore over pluggable storage.

The reference has no checkpointing at all (SURVEY.md §5: no torch.save/load,
no ``tune.checkpoint_dir`` anywhere); PBT and preemption-aware recovery make it
first-class here.  Format: flax msgpack for the array pytree (framework- and
process-portable, no pickle).  Paths route through ``tune.storage`` so the
same code writes local files (atomically — a preempted write never leaves a
truncated checkpoint), ``gs://`` objects on a real pod, or the in-memory test
fake, selected purely by the path's scheme.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional

import jax
import numpy as np
from flax import serialization

from distributed_machine_learning_tpu.tune.storage import get_storage

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")


def _to_host(tree):
    """Device arrays -> numpy so serialization never hangs on device buffers."""
    return jax.tree.map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, tree
    )


def save_checkpoint(path: str, tree: Dict[str, Any]) -> str:
    """Serialize a pytree dict to ``path`` (any storage scheme). Returns path."""
    payload = serialization.to_bytes(_to_host(tree))
    backend, p = get_storage(path)
    backend.write_bytes(p, payload)
    return path


def load_checkpoint(path: str) -> Optional[Dict[str, Any]]:
    """Decode a checkpoint without needing a target template (msgpack restore)."""
    if not path:
        return None
    backend, p = get_storage(path)
    data = backend.read_bytes(p)
    if data is None:
        return None
    return serialization.msgpack_restore(data)


def restore_into(template, tree: Dict[str, Any]):
    """Restore a raw decoded dict into ``template``'s pytree structure/dtypes."""
    return serialization.from_state_dict(template, tree)


def checkpoint_path(directory: str, iteration: int) -> str:
    backend, d = get_storage(directory)
    return backend.join(d, f"ckpt_{iteration:06d}.msgpack")


def find_latest_checkpoint(directory: str):
    """(path, iteration) of the newest ``ckpt_*.msgpack`` under ``directory``
    (any storage backend), or (None, 0) when there is none — how a resumed
    experiment rediscovers each trial's restore point."""
    backend, d = get_storage(directory)
    best_path, best_it = None, 0
    for name in backend.listdir(d):
        m = _CKPT_RE.match(name)
        if m and int(m.group(1)) >= best_it:
            best_path, best_it = backend.join(d, name), int(m.group(1))
    return best_path, best_it


def prune_checkpoints(directory: str, keep: int, protect=None) -> int:
    """Keep only the ``keep`` newest ``ckpt_*.msgpack`` files in ``directory``.

    ``protect`` (a full path, or an iterable of them) is never deleted even if
    old — e.g. a checkpoint another trial's PBT exploit is about to restore.
    Returns the number of files deleted.
    """
    if keep <= 0:
        return 0
    if protect is None:
        protected = set()
    elif isinstance(protect, str):
        protected = {protect}
    else:
        protected = set(protect)
    backend, d = get_storage(directory)
    found = []
    for name in backend.listdir(d):
        m = _CKPT_RE.match(name)
        if m:
            found.append((int(m.group(1)), name))
    found.sort()
    deleted = 0
    for _, name in found[:-keep] if len(found) > keep else []:
        full = backend.join(d, name)
        if full in protected:
            continue
        backend.delete(full)
        deleted += 1
    return deleted
