"""Pytree checkpoint save/restore over pluggable storage.

The reference has no checkpointing at all (SURVEY.md §5: no torch.save/load,
no ``tune.checkpoint_dir`` anywhere); PBT and preemption-aware recovery make it
first-class here.  Format: flax msgpack for the array pytree (framework- and
process-portable, no pickle).  Paths route through ``tune.storage`` so the
same code writes local files (atomically — a preempted write never leaves a
truncated checkpoint), ``gs://`` objects on a real pod, or the in-memory test
fake, selected purely by the path's scheme.

Integrity: every save also writes a ``<path>.manifest.json`` sidecar with
the payload's sha256 (orbax treats checkpoint integrity as first-class for
the same reason — shared storage bitrot and interrupted writes are real).
``load_checkpoint`` verifies the checksum (and that the bytes decode) and
raises :class:`CheckpointCorruptionError` on damage;
``load_checkpoint_with_fallback`` then walks older generations newest-first
so a trial restores from the newest checksum-valid checkpoint instead of
crashing — retention (``keep_checkpoints_num``) keeps the last K
generations around precisely to make that fallback possible.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import re
import threading
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from distributed_machine_learning_tpu.tune.storage import get_storage

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")

MANIFEST_SUFFIX = ".manifest.json"


class CheckpointCorruptionError(Exception):
    """Stored checkpoint bytes fail their checksum or do not decode."""


def manifest_path_for(path: str) -> str:
    return path + MANIFEST_SUFFIX


def _to_host(tree):
    """Device arrays -> numpy so serialization never hangs on device buffers."""
    return jax.tree.map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, tree
    )


def save_checkpoint(path: str, tree: Dict[str, Any]) -> str:
    """Serialize a pytree dict to ``path`` (any storage scheme). Returns path.

    A ``<path>.manifest.json`` sidecar (sha256 + byte count) is written
    AFTER the payload: a crash between the two leaves a checkpoint that is
    merely unverifiable (legacy semantics — decode-checked only), never a
    manifest pointing at absent data.
    """
    payload = serialization.to_bytes(_to_host(tree))
    backend, p = get_storage(path)
    backend.write_bytes(p, payload)
    manifest = {
        "sha256": hashlib.sha256(payload).hexdigest(),
        "bytes": len(payload),
        "format": "flax-msgpack",
    }
    backend.write_bytes(
        manifest_path_for(p), json.dumps(manifest).encode()
    )
    return path


def load_checkpoint(path: str, verify: bool = True) -> Optional[Dict[str, Any]]:
    """Decode a checkpoint without needing a target template (msgpack restore).

    With ``verify`` (default), the sidecar manifest's sha256 is checked
    before decoding and undecodable bytes raise
    :class:`CheckpointCorruptionError` — a missing manifest (legacy
    checkpoint, or a save interrupted between payload and sidecar) demotes
    to decode-checking only.
    """
    if not path:
        return None
    backend, p = get_storage(path)
    data = backend.read_bytes(p)
    if data is None:
        return None
    if verify:
        raw = backend.read_bytes(manifest_path_for(p))
        if raw is not None:
            try:
                expected = json.loads(raw).get("sha256")
            except ValueError:
                expected = None
            if expected is not None and (
                hashlib.sha256(data).hexdigest() != expected
            ):
                raise CheckpointCorruptionError(
                    f"checksum mismatch for {path} "
                    f"({len(data)} bytes on storage)"
                )
        try:
            return serialization.msgpack_restore(data)
        except Exception as exc:  # noqa: BLE001 - damaged bytes, any decoder error
            raise CheckpointCorruptionError(
                f"undecodable checkpoint at {path}: {exc!r}"
            ) from exc
    return serialization.msgpack_restore(data)


def verify_checkpoint(path: str) -> bool:
    """True if ``path`` exists and passes its integrity checks."""
    try:
        return load_checkpoint(path) is not None
    except CheckpointCorruptionError:
        return False


def _iteration_of(path: str) -> int:
    m = _CKPT_RE.match(os.path.basename(path.rstrip("/")))
    return int(m.group(1)) if m else 0


def load_checkpoint_with_fallback(
    path: Optional[str], directory: Optional[str] = None, log=None,
) -> Tuple[Optional[Dict[str, Any]], Optional[str], int]:
    """Restore ``path``; on corruption fall back to the newest
    checksum-valid generation under ``directory``.

    Returns ``(tree, used_path, used_iteration)`` — ``(None, None, 0)``
    when nothing restorable survives (the caller restarts from scratch,
    which is the pre-integrity behavior for a missing checkpoint).  The
    corrupt file is left in place (forensics; retention prunes it like any
    old generation) — callers must rewind their iteration bookkeeping to
    ``used_iteration``.
    """
    emit = log or (lambda msg: print(f"[checkpoint] {msg}", flush=True))
    if not path:
        # No restore target = a fresh trial; never restore one by accident.
        return None, None, 0
    try:
        tree = load_checkpoint(path)
        if tree is not None:
            return tree, path, _iteration_of(path)
        emit(f"restore target {path} is missing")
    except CheckpointCorruptionError as exc:
        emit(f"restore target is corrupt: {exc}")
    if not directory:
        return None, None, 0
    backend, d = get_storage(directory)
    generations = []
    for name in backend.listdir(d):
        m = _CKPT_RE.match(name)
        if m:
            generations.append((int(m.group(1)), name))
    for it, name in sorted(generations, reverse=True):
        full = backend.join(d, name)
        if path and full == path:
            continue  # already tried (and failed) above
        try:
            tree = load_checkpoint(full)
        except CheckpointCorruptionError as exc:
            emit(f"skipping corrupt generation {name}: {exc}")
            continue
        if tree is not None:
            emit(f"fell back to checksum-valid generation {name} (it={it})")
            return tree, full, it
    return None, None, 0


def restore_into(template, tree: Dict[str, Any]):
    """Restore a raw decoded dict into ``template``'s pytree structure/dtypes."""
    return serialization.from_state_dict(template, tree)


def checkpoint_path(directory: str, iteration: int) -> str:
    backend, d = get_storage(directory)
    return backend.join(d, f"ckpt_{iteration:06d}.msgpack")


def find_latest_checkpoint(directory: str):
    """(path, iteration) of the newest ``ckpt_*.msgpack`` under ``directory``
    (any storage backend), or (None, 0) when there is none — how a resumed
    experiment rediscovers each trial's restore point."""
    backend, d = get_storage(directory)
    best_path, best_it = None, 0
    for name in backend.listdir(d):
        m = _CKPT_RE.match(name)
        if m and int(m.group(1)) >= best_it:
            best_path, best_it = backend.join(d, name), int(m.group(1))
    return best_path, best_it


def newest_valid_checkpoint(directory: str):
    """(path, iteration) of the newest generation that PASSES its integrity
    check, or (None, 0).  The restore target for trials requeued off a
    silent worker (cluster lease expiry / stall fencing): the lost
    incarnation may have died mid-write, so the newest file on disk is not
    necessarily a loadable one — walk generations newest-first and trust
    only a verified checksum (legacy manifest-less files verify by
    decodability, matching ``load_checkpoint``)."""
    backend, d = get_storage(directory)
    generations = []
    for name in backend.listdir(d):
        m = _CKPT_RE.match(name)
        if m:
            generations.append((int(m.group(1)), name))
    for it, name in sorted(generations, reverse=True):
        full = backend.join(d, name)
        if verify_checkpoint(full):
            return full, it
    return None, 0


def _abspath_unless_remote(path: str) -> str:
    """abspath local paths only — os.path.abspath would mangle gs://-style
    URLs into '<cwd>/gs:/...' (orbax handles remote schemes itself)."""
    if re.match(r"^[a-z0-9]+://", path):
        return path
    return os.path.abspath(path)


def export_orbax(checkpoint_path: str, out_dir: str) -> str:
    """Convert a framework checkpoint to an orbax StandardCheckpoint.

    Interop bridge OUT of the framework: the msgpack pytree (params /
    opt_state / batch_stats / scalars) becomes a directory any
    orbax-consuming JAX stack restores directly — handing a tuned model
    to a separate serving/fine-tuning codebase without importing this
    package. Returns ``out_dir``. Raises ImportError if orbax is absent
    (it is an optional dependency).
    """
    import orbax.checkpoint as ocp

    tree = load_checkpoint(checkpoint_path)
    if tree is None:
        raise FileNotFoundError(f"no checkpoint at {checkpoint_path!r}")
    out_dir = _abspath_unless_remote(out_dir)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(out_dir, tree)
    return out_dir


def import_orbax(src_dir: str) -> Dict[str, Any]:
    """Restore an orbax StandardCheckpoint into a raw pytree dict —
    the inverse bridge (``restore_into`` then shapes it to a template)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(_abspath_unless_remote(src_dir))


class AsyncCheckpointWriter:
    """Overlap checkpoint writes with training (orbax-style async save).

    ``submit(path, tree)`` returns immediately; the device->host transfer,
    msgpack serialization, and storage write run on ONE background thread,
    in submission order. The trial thread goes straight back to training —
    at real checkpoint sizes the epoch that used to stall behind the write
    now runs concurrently with it.

    Correctness contract (why this is safe in-process):
    * ``submit`` snapshots EVERY array leaf: jax arrays get a device-side
      copy (cheap — HBM bandwidth; the D2H transfer stays on the writer
      thread), because the caller's train step donates its buffers
      (``donate_argnums``) and the next step would delete the submitted
      arrays out from under the serializer ("Array has been deleted" —
      donation is a no-op on CPU, so only real TPU runs hit it). Mutable
      numpy leaves are host-copied for the same reason.
    * A reader who might race a pending write (retry restore, PBT exploit
      of a peer's checkpoint) calls ``wait(path)`` first; the threaded
      executor routes every restore through it. Cross-process restores
      (cluster workers) keep synchronous saves instead — a remote reader
      cannot wait on this process's queue.
    * Write errors re-raise on ``wait``; ``close`` logs any unclaimed
      errors through ``log`` (or re-raises with ``raise_errors=True``) —
      never a silent drop.
    """

    def __init__(self, log=None):
        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._pending: Dict[str, threading.Event] = {}
        self._errors: Dict[str, BaseException] = {}
        self._log = log or (lambda msg: print(
            f"[checkpoint] {msg}", flush=True
        ))
        self._thread = threading.Thread(
            target=self._worker, name="ckpt-writer", daemon=True
        )
        self._thread.start()

    def _worker(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            path, tree, done = item
            try:
                save_checkpoint(path, tree)
            except BaseException as exc:  # noqa: BLE001 - surfaced on wait
                with self._lock:
                    self._errors[path] = exc
            finally:
                with self._lock:
                    self._pending.pop(path, None)
                done.set()

    @staticmethod
    def _snapshot_leaf(x):
        # jax.Array.copy() is a device-side copy: donation of the original
        # cannot delete it, and the D2H read stays on the writer thread.
        if isinstance(x, (jax.Array, np.ndarray)):
            return x.copy()
        return x

    def submit(self, path: str, tree: Dict[str, Any]) -> str:
        """Enqueue a write; returns ``path`` immediately."""
        snapshot = jax.tree.map(self._snapshot_leaf, tree)
        done = threading.Event()
        with self._lock:
            self._pending[path] = done
        self._q.put((path, snapshot, done))
        return path

    def wait(self, path: Optional[str] = None,
             timeout: Optional[float] = None) -> bool:
        """Block until ``path`` (or every pending write) is durable; re-raise
        its write error if one occurred. Returns False if ``timeout``
        expired with writes still pending."""
        deadline = None if timeout is None else time.time() + timeout
        if path is None:
            with self._lock:
                events = list(self._pending.values())
            for ev in events:
                left = None if deadline is None else deadline - time.time()
                if left is not None and left <= 0:
                    return False
                if not ev.wait(left):
                    return False
            with self._lock:
                # Pop only what we surface: the raised error is claimed —
                # re-raising it on every later wait() (and re-logging at
                # close()) turns one bad write into a permanent poison
                # (advisor r3). Other paths' errors stay claimable so they
                # are never silently dropped.
                first = next(iter(self._errors), None)
                err = self._errors.pop(first, None) if first else None
            if err is not None:
                raise err
            return True
        with self._lock:
            ev = self._pending.get(path)
        if ev is not None and not ev.wait(
            None if deadline is None else max(deadline - time.time(), 0.0)
        ):
            return False
        with self._lock:
            err = self._errors.pop(path, None)
        if err is not None:
            raise err
        return True

    def close(self, raise_errors: bool = False,
              timeout: Optional[float] = 30.0) -> None:
        """Flush pending writes (bounded by ``timeout``) and stop the worker.

        Unclaimed write errors are logged (or re-raised when
        ``raise_errors``); a write still hung at the deadline is abandoned
        with a log line rather than blocking teardown forever.
        """
        if not self._thread.is_alive():
            return
        flushed = True
        try:
            flushed = self.wait(timeout=timeout)
        except BaseException as exc:
            if raise_errors:
                self._q.put(None)
                self._thread.join(timeout=10)
                raise
            # wait() popped (claimed) the error it raised; surface it here
            # so an unclaimed failure is never silently dropped.
            self._log(
                "WARNING: checkpoint write(s) failed and were never "
                f"waited on; first: {exc!r}"
            )
        if not flushed:
            with self._lock:
                stuck = list(self._pending)
            self._log(
                f"WARNING: abandoning {len(stuck)} hung checkpoint "
                f"write(s) at teardown: {stuck[:3]}"
            )
        # Errors for writes that completed while wait() was timing out on a
        # different pending path can still be unclaimed — log those too.
        with self._lock:
            errors = dict(self._errors)
            self._errors.clear()
        if errors and not raise_errors:
            first_path, first_err = next(iter(errors.items()))
            self._log(
                f"WARNING: {len(errors)} checkpoint write(s) failed and "
                f"were never waited on; first: {first_path}: {first_err!r}"
            )
        self._q.put(None)
        # Only wait for the worker when the queue actually drained — a hung
        # write would pin this join for its full timeout, and the thread is
        # a daemon, so abandoning it is safe.
        if flushed:
            self._thread.join(timeout=10)


def prune_checkpoints(directory: str, keep: int, protect=None,
                      pending_latest: Optional[str] = None) -> int:
    """Keep only the ``keep`` newest ``ckpt_*.msgpack`` files in ``directory``.

    ``protect`` (a full path, or an iterable of them) is never deleted even if
    old — e.g. a checkpoint another trial's PBT exploit is about to restore.
    ``pending_latest``: a checkpoint path submitted to the async writer but
    possibly not on disk yet — behaviorally an alias for a ``protect`` entry,
    kept as the call-site's declaration of an in-flight write.  While it is
    in flight the newest ``keep`` DURABLE files are all retained — deleting
    them against a write that may still fail (crash, preemption, storage
    error) could leave the trial with zero restorable checkpoints, exactly
    the scenario checkpointing covers.  The on-disk set transiently
    overshoots by up to the executor's write-pipeline depth (``keep``+2
    with the depth-2 pipeline) while writes land; later prunes — and the
    runner's final retention pass after the writer drains — converge it
    back to exactly ``keep``.
    Returns the number of files deleted.
    """
    if keep <= 0:
        return 0
    if protect is None:
        protected = set()
    elif isinstance(protect, str):
        protected = {protect}
    else:
        protected = set(protect)
    if pending_latest is not None:
        protected.add(pending_latest)
    backend, d = get_storage(directory)
    found = []
    for name in backend.listdir(d):
        m = _CKPT_RE.match(name)
        if m:
            found.append((int(m.group(1)), name))
    found.sort()
    excess = found[:-keep] if len(found) > keep else []
    deleted = 0
    for _, name in excess:
        full = backend.join(d, name)
        if full in protected:
            continue
        backend.delete(full)
        # Integrity sidecar rides with its checkpoint (absent for legacy
        # generations; delete is a no-op then).
        backend.delete(manifest_path_for(full))
        deleted += 1
    return deleted
