"""Experiment callbacks: observability hooks on the runner's event loop.

The reference had no observability beyond a log file and Ray's results dir
(SURVEY.md §5).  Callbacks receive every trial lifecycle event from the
single-threaded runner loop (so they never need locks) and power the built-in
structured logging, JSONL event stream, and profiler integration.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from distributed_machine_learning_tpu.tune.trial import Trial
from distributed_machine_learning_tpu.utils.numeric import finite_number
from distributed_machine_learning_tpu.utils.logging import (
    JsonlEventLog,
    add_file_handler,
    get_logger,
    remove_handler,
)


def with_default_reporter(callbacks, verbose: int):
    """The shared verbose>=2 convention for both runners: a live trial
    table (Ray Tune's default console surface) unless one is already
    wired.  Returns a fresh list; never mutates the caller's."""
    callbacks = list(callbacks or [])
    if verbose >= 2 and not any(
        isinstance(cb, ProgressReporter) for cb in callbacks
    ):
        callbacks.append(ProgressReporter())
    return callbacks


def dispatch_safely(callbacks, hook: str, *args, log=lambda msg: None):
    """Invoke ``hook`` on every callback, isolating observer failures.

    Shared by the threaded and vectorized drivers: a raising callback is
    logged and dropped for that event, never fatal to the sweep (a trial
    thread may be blocked waiting on the event loop that runs observers)."""
    for cb in callbacks:
        try:
            getattr(cb, hook)(*args)
        except Exception as exc:  # noqa: BLE001 - observer isolation
            log(f"{type(cb).__name__}.{hook} raised: {exc!r}")


class Callback:
    """Base class; override any subset of hooks.

    Hooks run on the single runner thread, after the trial thread has been
    unblocked — a raising callback is logged and skipped, never fatal.
    ``on_trial_start`` may fire more than once per trial (fault retries, PBT
    requeues), and every failure fires ``on_trial_error`` even when the trial
    will be retried.  ``on_heartbeat`` ticks whenever the runner is idle
    (~every 0.5s) so time-based callbacks don't depend on trial traffic.
    """

    def setup(self, experiment_root: str, metric: str, mode: str):
        pass

    def on_heartbeat(self):
        pass

    def on_trial_start(self, trial: Trial):
        pass

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial: Trial):
        pass

    def on_trial_error(self, trial: Trial, error: str):
        pass

    def on_experiment_counters(self, counters: Dict[str, int]):
        """Experiment-level counters at teardown, prefixed by family
        (``liveness/stalls_detected``, ``faults/trial_crashes``, ...).
        Fires just before ``on_experiment_end``, and only when any
        counter family is active (a liveness watchdog or a chaos plan)."""
        pass

    def on_experiment_end(self, trials: List[Trial], wall_clock_s: float):
        pass


class LoggerCallback(Callback):
    """Structured per-event logging through the framework logger tree.

    Replaces the reference's hard-coded-path file logging (C23,
    `ray-tune-hpo-regression-sample.py:16-23`): pass ``log_file`` to also log
    to a file of your choosing.
    """

    def __init__(self, log_file: Optional[str] = None):
        self._log_file = log_file
        self._log = None
        self._handler = None

    def setup(self, experiment_root: str, metric: str, mode: str):
        self._log = get_logger("tune")
        if self._log_file is not None:
            self._handler = add_file_handler(self._log_file)
        self._metric = metric
        self._log.info("experiment started (root=%s, metric=%s/%s)",
                       experiment_root, metric, mode)

    def on_trial_start(self, trial: Trial):
        self._log.info("%s started: %s", trial.trial_id, trial.config)

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]):
        val = result.get(self._metric)
        self._log.info("%s iter %s: %s=%s", trial.trial_id,
                       result.get("training_iteration"), self._metric, val)

    def on_trial_complete(self, trial: Trial):
        self._log.info("%s terminated after %d result(s) in %.1fs",
                       trial.trial_id, len(trial.results), trial.runtime_s())

    def on_trial_error(self, trial: Trial, error: str):
        self._log.error("%s errored: %s", trial.trial_id,
                        error.strip().splitlines()[-1] if error else "?")

    def on_experiment_end(self, trials: List[Trial], wall_clock_s: float):
        self._log.info("experiment finished: %d trials in %.1fs",
                       len(trials), wall_clock_s)
        if self._handler is not None:
            remove_handler(self._handler)
            self._handler = None


class JsonlCallback(Callback):
    """Machine-readable experiment event stream -> ``<root>/events.jsonl``."""

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._log: Optional[JsonlEventLog] = None

    def setup(self, experiment_root: str, metric: str, mode: str):
        path = self._path or os.path.join(experiment_root, "events.jsonl")
        self._log = JsonlEventLog(path)
        self._log.write("experiment_start", {"root": experiment_root,
                                             "metric": metric, "mode": mode})

    def on_trial_start(self, trial: Trial):
        self._log.write("trial_start", {"trial_id": trial.trial_id,
                                        "config": trial.config})

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]):
        # The runner already stamps trial_id into each result record.
        self._log.write("trial_result", {**result, "trial_id": trial.trial_id})

    def on_trial_complete(self, trial: Trial):
        self._log.write("trial_complete", {"trial_id": trial.trial_id,
                                           "num_results": len(trial.results),
                                           "runtime_s": trial.runtime_s()})

    def on_trial_error(self, trial: Trial, error: str):
        self._log.write("trial_error", {"trial_id": trial.trial_id,
                                        "error": error})

    def on_experiment_end(self, trials: List[Trial], wall_clock_s: float):
        self._log.write("experiment_end", {"num_trials": len(trials),
                                           "wall_clock_s": wall_clock_s})
        self._log.close()


class TensorBoardCallback(Callback):
    """Per-trial TensorBoard scalar logging (Ray Tune's default TB surface).

    One run directory per trial under ``<root>/tensorboard/<trial_id>/`` —
    the layout TensorBoard's run selector expects (each trial is a run, so
    sweeps overlay as curve families).  Every numeric field of every
    ``tune.report`` lands as a scalar at ``step=training_iteration``; the
    trial's hyperparameters are stamped once as ``config/<key>`` scalars so
    runs are identifiable in TB without opening params.json.  Writes need no
    tensorflow/tensorboardX: the event-file format is hand-encoded
    (utils/tensorboard.py).
    """

    def __init__(self, logdir: Optional[str] = None):
        self._logdir = logdir
        self._writers: Dict[str, Any] = {}

    def setup(self, experiment_root: str, metric: str, mode: str):
        self._root = self._logdir or os.path.join(
            experiment_root, "tensorboard"
        )

    def _writer(self, trial: Trial):
        w = self._writers.get(trial.trial_id)
        if w is None:
            from distributed_machine_learning_tpu.utils.tensorboard import (
                SummaryWriter,
            )

            w = SummaryWriter(os.path.join(self._root, trial.trial_id))
            self._writers[trial.trial_id] = w
            for key, val in (trial.config or {}).items():
                if isinstance(val, bool) or not isinstance(
                    val, (int, float)
                ):
                    continue
                w.add_scalar(f"config/{key}", float(val), step=0)
        return w

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]):
        step = int(result.get("training_iteration", len(trial.results)) or 0)
        scalars = [
            (key, float(val))
            for key, val in result.items()
            if not isinstance(val, bool) and isinstance(val, (int, float))
        ]
        if scalars:
            self._writer(trial).add_scalars(scalars, step=step)

    def _close(self, trial_id: str):
        w = self._writers.pop(trial_id, None)
        if w is not None:
            w.close()

    def on_trial_complete(self, trial: Trial):
        # Close (not just flush): one open fd per live trial, not per trial
        # ever started — a 1000+-trial sweep would exhaust the fd limit. A
        # retried/requeued trial that reports again just gets a fresh event
        # file in the same run dir; TensorBoard merges them.
        self._close(trial.trial_id)

    def on_trial_error(self, trial: Trial, error: str):
        self._close(trial.trial_id)

    def on_experiment_counters(self, counters: Dict[str, int]):
        # Experiment-scope run ("_experiment" sorts above trial runs in
        # TB's selector): stall/requeue/fence and injected-fault counters
        # graph next to the per-trial curves they explain.
        from distributed_machine_learning_tpu.utils.tensorboard import (
            SummaryWriter,
        )

        w = SummaryWriter(os.path.join(self._root, "_experiment"))
        try:
            w.add_scalars(
                [(key, float(val)) for key, val in sorted(counters.items())],
                step=0,
            )
        finally:
            w.close()

    def on_experiment_end(self, trials: List[Trial], wall_clock_s: float):
        for w in self._writers.values():
            w.close()
        self._writers.clear()


class ProgressReporter(Callback):
    """Live console status table — parity with Ray Tune's ``CLIReporter``.

    The reference's only live feedback was Ray's built-in trial table; the
    runner's ``verbose`` one-liner carries counts but no per-trial state.
    This callback renders, at most every ``interval_s`` seconds and only when
    something changed, a compact table of running trials (iteration, latest
    metric, runtime) plus status counts, the best value so far, and measured
    throughput (terminated trials/hour — the BASELINE.md metric, computed the
    same way ``bench.py`` reports it).  A final summary with the best trial's
    config always prints at experiment end.

    Pass ``file`` to redirect (e.g. a log file); default is stdout, matching
    the runner's own ``[tune]`` lines.
    """

    def __init__(self, interval_s: float = 15.0, max_rows: int = 12,
                 file=None):
        self._interval_s = interval_s
        self._max_rows = max_rows
        self._file = file
        self._trials: Dict[str, Trial] = {}
        self._best_value: Optional[float] = None
        self._best_trial_id: Optional[str] = None
        self._last_print = 0.0
        self._dirty = False
        self._start = time.time()

    def setup(self, experiment_root: str, metric: str, mode: str):
        self._metric = metric
        self._mode = mode
        # Full reset: a reporter reused across tune.run calls must not carry
        # the previous experiment's trials/best into the new run's output.
        self._trials = {}
        self._best_value = None
        self._best_trial_id = None
        self._dirty = False
        self._start = time.time()
        self._last_print = 0.0  # first event after setup prints immediately

    # -- event tracking ----------------------------------------------------

    def _touch(self, trial: Trial):
        self._trials[trial.trial_id] = trial
        self._dirty = True

    def on_trial_start(self, trial: Trial):
        self._touch(trial)

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]):
        self._touch(trial)
        val = finite_number(result.get(self._metric))
        if val is not None:  # NaN/inf (diverged trial) never becomes best
            better = (
                self._best_value is None
                or (self._mode == "min" and val < self._best_value)
                or (self._mode == "max" and val > self._best_value)
            )
            if better:
                self._best_value = val
                self._best_trial_id = trial.trial_id
        self._maybe_render()

    def on_trial_complete(self, trial: Trial):
        self._touch(trial)
        self._maybe_render()

    def on_trial_error(self, trial: Trial, error: str):
        self._touch(trial)
        self._maybe_render()

    def on_heartbeat(self):
        # Time-based refresh so runtime columns advance on quiet sweeps:
        # running trials make the table inherently dirty (their time_s
        # column is live), so render on interval whenever any trial runs.
        if any(t.status.value == "RUNNING" for t in self._trials.values()):
            self._dirty = True
        self._maybe_render()

    def on_experiment_end(self, trials: List[Trial], wall_clock_s: float):
        for t in trials:
            self._trials[t.trial_id] = t
        self._render(final=True, wall_clock_s=wall_clock_s)

    # -- rendering ---------------------------------------------------------

    def _numeric_history(self, trial: Trial) -> List[float]:
        """The trial's plottable metric values: numbers only (a trainable
        may report None/strings — TensorBoardCallback guards the same way),
        NaN dropped (a diverged epoch must not rank or display)."""
        return [
            f for f in map(finite_number, trial.metric_history(self._metric))
            if f is not None
        ]

    def _maybe_render(self):
        if self._dirty and time.time() - self._last_print >= self._interval_s:
            self._render()

    def _render(self, final: bool = False, wall_clock_s: float = None):
        import sys

        self._last_print = time.time()
        self._dirty = False
        out = self._file or sys.stdout
        trials = list(self._trials.values())
        counts: Dict[str, int] = {}
        for t in trials:
            counts[t.status.value] = counts.get(t.status.value, 0) + 1
        elapsed = wall_clock_s if wall_clock_s is not None else (
            time.time() - self._start
        )
        done = counts.get("TERMINATED", 0)
        tph = done / (elapsed / 3600.0) if elapsed > 0 and done else 0.0
        status = ", ".join(f"{k}: {v}" for k, v in sorted(counts.items()))
        lines = [
            f"== {'Final result' if final else 'Status'} "
            f"({elapsed:.0f}s) == {status or 'no trials yet'}"
            + (f" | {tph:.0f} trials/h" if done else "")
        ]
        if self._best_value is not None:
            lines.append(
                f"   best {self._metric}: {self._best_value:.6g} "
                f"({self._best_trial_id})"
            )
            best = self._trials.get(self._best_trial_id)
            if final and best is not None:
                lines.append(f"   best config: {best.config}")
        # Running trials first (what a live table is for); at the end, the
        # top finishers by metric instead.
        if final:
            def key(t):
                # Rank by best-in-history so the table agrees with the
                # "best" line (a trial can end worse than its best epoch);
                # non-numeric/NaN-only histories sort last.
                hist = self._numeric_history(t)
                if not hist:
                    return float("inf")
                return min(hist) if self._mode == "min" else -max(hist)
            rows = sorted(trials, key=key)[: self._max_rows]
        else:
            rows = [t for t in trials if t.status.value == "RUNNING"]
            rows.sort(key=lambda t: -t.training_iteration)
            rows = rows[: self._max_rows]
        if rows:
            header = ("trial", "status", "iter", self._metric, "time_s")
            table = [header]
            for t in rows:
                hist = self._numeric_history(t)
                # Final table shows each trial's BEST value (what it's
                # ranked by); the live table shows the latest.
                if hist and final:
                    shown = min(hist) if self._mode == "min" else max(hist)
                elif hist:
                    shown = hist[-1]
                table.append((
                    t.trial_id,
                    t.status.value,
                    str(t.training_iteration),
                    f"{shown:.6g}" if hist else "-",
                    f"{t.runtime_s():.1f}",
                ))
            widths = [max(len(r[i]) for r in table)
                      for i in range(len(header))]
            for row in table:
                lines.append("   " + "  ".join(
                    c.ljust(w) for c, w in zip(row, widths)
                ).rstrip())
            hidden = (len(trials) if final else
                      sum(1 for t in trials
                          if t.status.value == "RUNNING")) - len(rows)
            if hidden > 0:
                lines.append(f"   ... and {hidden} more")
        print("\n".join(lines), file=out, flush=True)


class ProfilerCallback(Callback):
    """Capture a ``jax.profiler`` trace of the experiment.

    The trace is process-global (trials share the process), so this profiles
    the whole sweep — XLA compilations, device compute, and the host-side
    scheduler — into ``<root>/profile`` for TensorBoard/XProf.  ``duration_s``
    bounds the capture window to keep traces small on long sweeps.
    """

    def __init__(self, logdir: Optional[str] = None,
                 duration_s: Optional[float] = None):
        self._logdir = logdir
        self._duration_s = duration_s
        self._started_at: Optional[float] = None
        self._active = False

    def setup(self, experiment_root: str, metric: str, mode: str):
        import jax

        self._dir = self._logdir or os.path.join(experiment_root, "profile")
        jax.profiler.start_trace(self._dir)
        self._active = True
        self._started_at = time.time()

    def _maybe_stop(self):
        if self._active and self._duration_s is not None and (
            time.time() - self._started_at > self._duration_s
        ):
            self._stop()

    def _stop(self):
        import jax

        if self._active:
            self._active = False
            jax.profiler.stop_trace()

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]):
        self._maybe_stop()

    def on_heartbeat(self):
        # Enforce duration_s by wall clock, not trial traffic: without this a
        # long first epoch (or a crashed sole trial) would overrun the window.
        self._maybe_stop()

    def on_experiment_end(self, trials: List[Trial], wall_clock_s: float):
        self._stop()
