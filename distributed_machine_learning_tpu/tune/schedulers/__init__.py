from distributed_machine_learning_tpu.tune.schedulers.asha import ASHAScheduler
from distributed_machine_learning_tpu.tune.schedulers.base import (
    CONTINUE,
    FIFOScheduler,
    REQUEUE,
    STOP,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.schedulers.hyperband import (
    HyperBandScheduler,
)
from distributed_machine_learning_tpu.tune.schedulers.median import MedianStoppingRule
from distributed_machine_learning_tpu.tune.schedulers.pb2 import PB2
from distributed_machine_learning_tpu.tune.schedulers.pbt import PopulationBasedTraining

__all__ = [
    "ASHAScheduler",
    "HyperBandScheduler",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "TrialScheduler",
    "CONTINUE",
    "STOP",
    "REQUEUE",
]
