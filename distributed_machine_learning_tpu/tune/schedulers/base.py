"""Trial-scheduler interface.

Native replacement for the ASHA/PBT scheduling the reference delegated to Ray
Tune (`ray-tune-hpo-regression.py:473`; SURVEY.md §2b D1).  The runner calls
``on_trial_result`` synchronously on every per-epoch report; the returned
decision takes effect before the trainable runs its next epoch.
"""

from __future__ import annotations

from typing import Any, Dict

from distributed_machine_learning_tpu.tune.trial import Trial

CONTINUE = "continue"
STOP = "stop"
REQUEUE = "requeue"  # stop, then re-run the same trial (mutated config / restore)


class TrialScheduler:
    def set_experiment(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def _score(self, result: Dict[str, Any]) -> float:
        """Normalize so that LOWER is always better internally."""
        value = float(result[self.metric])
        return value if self.mode == "min" else -value

    def on_trial_add(self, trial: Trial):
        pass

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial):
        pass

    def on_trial_error(self, trial: Trial):
        pass

    def save_state(self) -> Dict[str, Any]:
        """JSON-serializable snapshot of ALL decision-relevant mutable
        state — journaled after every scheduling decision
        (``tune/journal.py``) so a restarted head restores a scheduler
        that makes bit-identical decisions (ASHA resumes mid-rung, PBT
        keeps its exploit history).  Live ``Trial`` references are NOT
        state — resume rebuilds them via ``on_trial_add`` before calling
        ``restore_state``.  Stateless schedulers (FIFO) inherit this
        empty default."""
        return {}

    def restore_state(self, state: Dict[str, Any]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    """No early stopping; trials run to completion in submission order."""
