"""ASHA: asynchronous successive halving.

Native implementation of the capability the reference consumed from Ray's
``ASHAScheduler`` (`ray-tune-hpo-regression.py:473`, `-sample.py:163`) — and
actually effective here, because trainables report per epoch instead of once
at trial end (SURVEY.md §3.1/§3.4).

Algorithm (Li et al. 2018): rungs at iteration r, r*eta, r*eta^2, ... up to
``max_t``.  When a trial reaches a rung, record its metric; it is promoted
(continues) iff it is in the top 1/eta of results recorded *so far* at that
rung — asynchronous, so no waiting for a full bracket.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List

from distributed_machine_learning_tpu.tune.schedulers.base import (
    CONTINUE,
    STOP,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.trial import Trial


class ASHAScheduler(TrialScheduler):
    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 3.0,
        time_attr: str = "training_iteration",
    ):
        if grace_period < 1:
            raise ValueError("grace_period must be >= 1")
        if reduction_factor <= 1:
            raise ValueError("reduction_factor must be > 1")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.eta = reduction_factor
        self.time_attr = time_attr

        # rung iteration -> list of scores recorded at that rung (lower=better)
        max_rungs = int(
            math.log(max(max_t / grace_period, 1), reduction_factor) + 1
        )
        self.rungs: List[int] = [
            int(grace_period * reduction_factor ** k) for k in range(max_rungs)
        ]
        self.rung_scores: Dict[int, List[float]] = {r: [] for r in self.rungs}
        self._trial_next_rung: Dict[str, int] = {}

    def set_experiment(self, metric: str, mode: str):
        # Respect an explicitly configured metric/mode (Ray allows scheduler-
        # level settings overriding the experiment default); None means unset.
        self.metric = self.metric if self.metric is not None else metric
        self.mode = self.mode if self.mode is not None else mode

    def on_trial_add(self, trial: Trial):
        self._trial_next_rung[trial.trial_id] = 0

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        if self.metric not in result:
            return CONTINUE
        t = int(result.get(self.time_attr, trial.training_iteration))
        if t >= self.max_t:
            return STOP

        rung_idx = self._trial_next_rung.get(trial.trial_id, 0)
        if rung_idx >= len(self.rungs) or t < self.rungs[rung_idx]:
            return CONTINUE

        # The trial may skip rungs if it reports sparsely; use the highest
        # rung it has reached.
        while rung_idx + 1 < len(self.rungs) and t >= self.rungs[rung_idx + 1]:
            rung_idx += 1
        rung = self.rungs[rung_idx]
        score = self._score(result)
        scores = self.rung_scores[rung]
        scores.append(score)
        self._trial_next_rung[trial.trial_id] = rung_idx + 1

        # Promote iff within the top 1/eta of scores seen at this rung so far.
        k = int(len(scores) / self.eta)
        if k < 1:
            # Not enough peers yet: ASHA promotes optimistically.
            return CONTINUE
        cutoff = sorted(scores)[k - 1]
        return CONTINUE if score <= cutoff else STOP

    def save_state(self) -> Dict[str, Any]:
        return {
            "rung_scores": {
                str(r): list(s) for r, s in self.rung_scores.items()
            },
            "trial_next_rung": dict(self._trial_next_rung),
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        # Runs AFTER on_trial_add re-registered every live trial, so the
        # journaled rung positions overwrite the fresh zeros.
        for r, scores in state.get("rung_scores", {}).items():
            if int(r) in self.rung_scores:
                self.rung_scores[int(r)] = [float(v) for v in scores]
        self._trial_next_rung.update({
            str(t): int(r)
            for t, r in state.get("trial_next_rung", {}).items()
        })

    def debug_state(self) -> Dict[int, int]:
        return {r: len(s) for r, s in self.rung_scores.items()}
