"""Async HyperBand: the bracket scheduler half of BOHB.

The reference only used ASHA (`ray-tune-hpo-regression.py:473`), but the
framework's north star (BASELINE.json configs; SURVEY.md §2b D1) also calls for
BOHB = async HyperBand brackets (Li et al. 2018) + a TPE model proposing
configs (Falkner et al. 2018, `search/tpe.py`).

A single successive-halving bracket commits to one grace period; HyperBand
hedges by running several brackets whose grace periods span
``grace_period * eta^s`` for s = 0..num_brackets-1, assigning new trials to
brackets round-robin weighted by each bracket's trial budget.  Each bracket is
an independent :class:`ASHAScheduler` (async, so no barrier at rung
boundaries — a stopped trial frees its TPU core immediately).
"""

from __future__ import annotations

from typing import Any, Dict, List

from distributed_machine_learning_tpu.tune.schedulers.asha import ASHAScheduler
from distributed_machine_learning_tpu.tune.schedulers.base import (
    CONTINUE,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.trial import Trial


class HyperBandScheduler(TrialScheduler):
    """Asynchronous HyperBand over per-epoch metric streams.

    Pair with :class:`~distributed_machine_learning_tpu.tune.search.tpe.TPESearch`
    for BOHB.
    """

    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 3.0,
        num_brackets: int = 3,
        time_attr: str = "training_iteration",
    ):
        if num_brackets < 1:
            raise ValueError("num_brackets must be >= 1")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.eta = reduction_factor
        self.time_attr = time_attr

        self.brackets: List[ASHAScheduler] = []
        for s in range(num_brackets):
            g = int(grace_period * reduction_factor**s)
            if s > 0 and g >= max_t:
                break  # a bracket whose first rung is max_t never stops anything
            self.brackets.append(
                ASHAScheduler(
                    metric=metric,
                    mode=mode,
                    max_t=max_t,
                    grace_period=g,
                    reduction_factor=reduction_factor,
                    time_attr=time_attr,
                )
            )
        # HyperBand allocates the most trials to the most-aggressive bracket
        # (smallest grace period, most halvings): n_s ~ eta^s where s counts
        # halvings remaining, i.e. weight eta^(num_brackets-1-idx) for bracket
        # idx ordered by increasing grace period.
        n = len(self.brackets)
        self._weights = [self.eta ** (n - 1 - i) for i in range(n)]
        self._assigned_counts = [0] * len(self.brackets)
        self._trial_bracket: Dict[str, int] = {}

    def set_experiment(self, metric: str, mode: str):
        self.metric = self.metric if self.metric is not None else metric
        self.mode = self.mode if self.mode is not None else mode
        for b in self.brackets:
            b.set_experiment(self.metric, self.mode)

    def _pick_bracket(self) -> int:
        # Fill towards the target proportions: pick the bracket with the
        # largest deficit of assigned trials vs its weight share.
        total_w = sum(self._weights)
        total_n = sum(self._assigned_counts) + 1
        deficits = [
            w / total_w - n / total_n
            for w, n in zip(self._weights, self._assigned_counts)
        ]
        return max(range(len(deficits)), key=lambda i: deficits[i])

    def on_trial_add(self, trial: Trial):
        idx = self._pick_bracket()
        self._assigned_counts[idx] += 1
        self._trial_bracket[trial.trial_id] = idx
        self.brackets[idx].on_trial_add(trial)

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        idx = self._trial_bracket.get(trial.trial_id)
        if idx is None:
            return CONTINUE
        return self.brackets[idx].on_trial_result(trial, result)

    def save_state(self) -> Dict[str, Any]:
        return {
            "brackets": [b.save_state() for b in self.brackets],
            "assigned_counts": list(self._assigned_counts),
            "trial_bracket": dict(self._trial_bracket),
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        for b, sub in zip(self.brackets, state.get("brackets", [])):
            b.restore_state(sub)
        counts = state.get("assigned_counts")
        if counts is not None:
            self._assigned_counts = [int(c) for c in counts]
        self._trial_bracket.update({
            str(t): int(i)
            for t, i in state.get("trial_bracket", {}).items()
        })

    def debug_state(self) -> List[Dict[int, int]]:
        return [b.debug_state() for b in self.brackets]
