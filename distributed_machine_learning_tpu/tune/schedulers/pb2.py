"""PB2 — Population Based Bandits (Parker-Holder et al., NeurIPS 2020).

PBT with a model-based explore step: instead of randomly perturbing
continuous hyperparameters at exploit time, PB2 fits a Gaussian process to
the observed *score improvements* as a function of the hyperparameter values
that produced them, and picks new values by UCB — so the population steers
its learning-rate/weight-decay schedule toward the settings that have been
paying off, which matters exactly where PBT's random walk wastes trials
(small populations).

The reference has neither PBT nor PB2 (no checkpointing at all, SURVEY.md
§5); this rounds out the scheduler menu a Ray Tune user expects
(`ray.tune.schedulers.pb2.PB2`).  Exploit, quantile ranking, checkpoint
budget-preservation, and categorical mutation are inherited from
``PopulationBasedTraining``; only continuous-key exploration changes.  The
GP is the same pure-numpy RBF machinery as ``BayesOptSearch`` — no library
dependency.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from distributed_machine_learning_tpu.tune.schedulers.base import CONTINUE
from distributed_machine_learning_tpu.tune.schedulers.pbt import (
    PopulationBasedTraining,
)
from distributed_machine_learning_tpu.tune.search.bayesopt import gp_posterior
from distributed_machine_learning_tpu.tune.search_space import Domain
from distributed_machine_learning_tpu.tune.trial import Trial


class PB2(PopulationBasedTraining):
    """Drop-in PBT replacement; continuous mutations become GP-UCB choices.

    Extra knobs: ``kappa`` (UCB exploration weight — higher explores more),
    ``lengthscale``/``noise`` (GP hyperparams on the unit cube),
    ``num_candidates`` (acquisition grid size).  Continuous keys are the
    ``hyperparam_mutations`` entries whose spec is a continuous ``Domain``
    (``tune.uniform``/``tune.loguniform``); everything else mutates exactly
    as in PBT.
    """

    def __init__(self, *args, kappa: float = 1.0, lengthscale: float = 0.2,
                 noise: float = 1e-4, num_candidates: int = 256,
                 window: int = 512, **kwargs):
        super().__init__(*args, **kwargs)
        self.kappa = kappa
        self.lengthscale = lengthscale
        self.noise = noise
        self.num_candidates = num_candidates
        self.window = window
        self._cont_keys = [
            k for k, spec in self.mutations.items()
            if isinstance(spec, Domain) and spec.is_continuous
        ]
        # Observations: (unit-cube hyperparam vector, score improvement it
        # produced over one reporting step).  Lower score = better, so
        # improvement = previous - current.  Sliding window (Ray's PB2 fits
        # a recent time window too): bounds the O(n^3) GP refit AND keeps
        # late-phase mutations steered by late-phase evidence — early
        # epochs' big deltas would otherwise dominate the mean forever.
        self._obs: list = []
        # trial_id -> (iteration, score) of the last observed report.
        self._last_score: Dict[str, tuple] = {}

    # -- observe improvements ------------------------------------------------
    def _encode(self, config: Dict[str, Any]):
        try:
            return np.array(
                [self.mutations[k].to_unit(config[k])
                 for k in self._cont_keys],
                dtype=np.float64,
            )
        except (KeyError, TypeError, ValueError):
            return None  # config missing a key / non-numeric: skip this obs

    def observe_result(self, trial: Trial, result: Dict[str, Any]) -> None:
        """One improvement observation per consecutive-report pair; also the
        hook run_vectorized calls directly (it bypasses on_trial_result for
        the PBT family — the gather replaces REQUEUE)."""
        if self.metric not in result or not self._cont_keys:
            return
        score = self._score(result)
        it = int(result.get("training_iteration",
                            trial.training_iteration))
        prev = self._last_score.get(trial.trial_id)
        # A non-monotone iteration means the trial restarted from a
        # checkpoint WITHOUT a scheduler decision (driver failure-retry
        # rewinds to the last checkpoint, resume requeues) — a delta
        # across that boundary would blame the config for the rewound
        # weights, so it only re-baselines.
        if prev is not None and it > prev[0]:
            x = self._encode(trial.config)
            if x is not None:
                self._obs.append((x, prev[1] - score))
                if len(self._obs) > self.window:
                    del self._obs[: -self.window]
        self._last_score[trial.trial_id] = (it, score)

    def reset_improvement_chain(self, trial_id: str) -> None:
        self._last_score.pop(trial_id, None)

    def device_mutation_spec(self):
        """None: GP-UCB explore refits on host observations at EVERY
        generation — it cannot be baked into a compiled generation scan.
        run_vectorized therefore composes PB2 with the host-boundary path
        (``pbt_mode="boundary"``): the GP keeps observing every report via
        :meth:`observe_result` and its choices ride the same device-side
        gather, one dispatch per perturbation interval."""
        return None

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        self.observe_result(trial, result)
        decision = super().on_trial_result(trial, result)
        if decision != CONTINUE:
            # The trial restarts from a donor's weights under a new config;
            # a delta across that boundary would credit the new config with
            # the donor's head start, so the improvement chain resets.
            self.reset_improvement_chain(trial.trial_id)
        return decision

    # -- explore (GP-UCB over the continuous keys) ---------------------------
    def _mutate(self, config: Dict[str, Any],
                rng: np.random.Generator) -> Dict[str, Any]:
        new = super()._mutate(config, rng)  # categorical + in-domain clamp
        if not self._cont_keys or len(self._obs) < 4:
            return new
        X = np.stack([x for x, _ in self._obs])
        y = np.array([dy for _, dy in self._obs])
        cand = rng.random((self.num_candidates, len(self._cont_keys)))
        try:
            mu, sigma, _ = gp_posterior(
                X, y, cand, self.lengthscale, self.noise
            )
        except np.linalg.LinAlgError:
            return new  # degenerate observations: keep the PBT mutation
        u = cand[int(np.argmax(mu + self.kappa * sigma))]  # max improvement
        for k, ui in zip(self._cont_keys, u):
            new[k] = self.mutations[k].from_unit(float(ui))
        return new

    def save_state(self) -> Dict[str, Any]:
        state = super().save_state()
        state["obs"] = [
            [[float(v) for v in x], float(dy)] for x, dy in self._obs
        ]
        state["last_score"] = {
            t: [int(i), float(s)]
            for t, (i, s) in self._last_score.items()
        }
        return state

    def restore_state(self, state: Dict[str, Any]) -> None:
        super().restore_state(state)
        self._obs = [
            (np.array(x, dtype=np.float64), float(dy))
            for x, dy in state.get("obs", [])
        ]
        self._last_score = {
            str(t): (int(v[0]), float(v[1]))
            for t, v in state.get("last_score", {}).items()
        }

    def debug_state(self):
        state = super().debug_state()
        state["num_observations"] = len(self._obs)
        return state
