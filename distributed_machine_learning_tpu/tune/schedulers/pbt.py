"""Population-Based Training (stop-and-respawn variant).

The reference has no PBT and no checkpointing (SURVEY.md §5); BASELINE.json
config 3 requires PBT exercising checkpoint mutate/restore.  Design: at every
``perturbation_interval`` reports, a bottom-quantile trial is stopped, its
config mutated (explore), its weights replaced by a top-quantile peer's latest
checkpoint (exploit), and the trial is requeued — the executor restarts it and
the trainable resumes from the restored epoch.  Stop-and-respawn keeps the
trainable a plain function (no in-band weight surgery) and matches how
preemption-tolerant TPU trials must restart anyway.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from distributed_machine_learning_tpu.tune.schedulers.base import (
    CONTINUE,
    REQUEUE,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.search_space import (
    Domain,
    LogRandInt,
    LogUniform,
    RandInt,
    Uniform,
)
from distributed_machine_learning_tpu.tune.trial import Trial
from distributed_machine_learning_tpu.utils.seeding import rng_from

# Resample values of the compiled exploit/explore step land on a fixed
# host-precomputed grid (geometric for loguniform domains, linear for
# uniform) instead of an exp/log inverse transform: transcendental ops are
# NOT bit-stable between XLA's fused (jit) and eager kernels, and the
# golden parity contract (compiled step == host reference, bit for bit) is
# what makes the in-device path debuggable.  1024 points across an HPO
# domain is far below the noise floor of any search.
RESAMPLE_GRID_POINTS = 1024

# Multiplicative scalarization weights: score = quality
# * step_latency_s ** lat_w * param_millions ** param_w (mode="min" only —
# every term is a cost).  Latency and params are constant across the rows
# of one population (same architecture), so WITHIN a population the
# ranking is pure quality; across populations / groups the scalarized
# score (emitted per record as ``pbt_objective``) is what makes a
# serve-bound sweep pick the best *deployable* model.
_OBJECTIVE_WEIGHTS = {
    "quality": (0.0, 0.0),
    "quality_latency": (1.0, 0.0),
    "quality_latency_params": (1.0, 1.0),
    # Post-quantization selection (quant/): weights stay (0, 0) — the
    # scalarization factor is a frozen per-population constant (bit-parity
    # contract with the compiled generation step), so int8 scoring cannot
    # ride it in-generation.  Instead the vectorized driver fake-quantizes
    # every surviving row at sweep end and emits its int8 validation MAPE
    # as a final ``pbt_objective`` record — selection (best trial, export)
    # then prefers the model that SURVIVES int8, not the one that merely
    # wins at f32.
    "quality_after_quant": (0.0, 0.0),
}


def _parse_objective(objective) -> Tuple[str, Tuple[float, float]]:
    if objective is None:
        objective = "quality"
    if isinstance(objective, str):
        if objective not in _OBJECTIVE_WEIGHTS:
            raise ValueError(
                f"objective must be one of {sorted(_OBJECTIVE_WEIGHTS)} or a "
                f"weight dict {{'latency': w, 'params': w}}, got {objective!r}"
            )
        return objective, _OBJECTIVE_WEIGHTS[objective]
    if isinstance(objective, dict):
        unknown = set(objective) - {"latency", "params"}
        if unknown:
            raise ValueError(
                f"objective weight dict supports 'latency'/'params', got "
                f"{sorted(unknown)}"
            )
        lat = float(objective.get("latency", 0.0))
        par = float(objective.get("params", 0.0))
        return f"custom_lat{lat:g}_par{par:g}", (lat, par)
    raise TypeError(f"objective must be a string or dict, got {objective!r}")


class PopulationBasedTraining(TrialScheduler):
    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        perturbation_interval: int = 2,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        perturbation_factors=(0.8, 1.2),
        seed: int = 0,
        objective=None,
    ):
        if not hyperparam_mutations:
            raise ValueError("PBT requires hyperparam_mutations")
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.factors = perturbation_factors
        self.seed = seed
        self.objective, self.objective_weights = _parse_objective(objective)
        # quality_after_quant: in-generation ranking is pure quality; the
        # driver adds the post-quantization scoring pass at sweep end.
        self.quant_aware = self.objective == "quality_after_quant"
        # trial_id -> [(iteration, score), ...] in report order (lower=better)
        self._history: Dict[str, list] = {}
        self._num_perturbations = 0
        # Decision trace of the deterministic generation step (compiled and
        # boundary-reference paths append one entry per generation): the
        # golden parity test replays these through
        # :func:`reference_generation_step` and asserts bit equality.
        self._generation_log: list = []

    def set_experiment(self, metric: str, mode: str):
        self.metric = self.metric if self.metric is not None else metric
        self.mode = self.mode if self.mode is not None else mode

    # -- explore -------------------------------------------------------------
    def _mutate(self, config: Dict[str, Any], rng: np.random.Generator) -> Dict[str, Any]:
        new = dict(config)
        for key, spec in self.mutations.items():
            resample = rng.random() < self.resample_p or key not in new
            if isinstance(spec, Domain):
                if resample:
                    new[key] = spec.sample(rng)
                elif isinstance(new.get(key), (int, float)) and not isinstance(new[key], bool):
                    val = new[key] * self.factors[int(rng.integers(len(self.factors)))]
                    lo = getattr(spec, "low", None)
                    hi = getattr(spec, "high", None)
                    if lo is not None and hi is not None:
                        # Clamp into the domain: a x1.2 step from near the
                        # upper bound must not leave it (Ray clamps too).
                        # Direct min/max — no to_unit round-trip, which
                        # would log(0)-crash on a zero value under
                        # loguniform and float-ify int hyperparams.
                        # RandInt/LogRandInt highs are EXCLUSIVE (numpy
                        # convention): their top legal value is high-1.
                        if isinstance(spec, (RandInt, LogRandInt)):
                            hi = hi - 1
                        val = min(max(val, lo), hi)
                        q = getattr(spec, "q", None)
                        if q:
                            # Quantized domains: a multiplied-then-clamped
                            # value must snap back onto the q grid inside
                            # the domain (sample() guarantees multiples;
                            # explore must not reintroduce non-multiples).
                            val = min(max(round(val / q) * q, spec._lo),
                                      spec._hi)
                    new[key] = type(new[key])(val)
                else:
                    new[key] = spec.sample(rng)
            elif isinstance(spec, (list, tuple)):
                if resample or new.get(key) not in spec:
                    new[key] = spec[int(rng.integers(len(spec)))]
                else:  # step to a neighbor in the ordered list
                    i = list(spec).index(new[key])
                    j = int(np.clip(i + rng.choice([-1, 1]), 0, len(spec) - 1))
                    new[key] = spec[j]
            elif callable(spec):
                new[key] = spec()
            else:
                raise TypeError(f"Unsupported mutation spec for {key!r}: {spec!r}")
        return new

    # -- exploit -------------------------------------------------------------
    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        if self.metric not in result:
            return CONTINUE
        it = int(result.get("training_iteration", trial.training_iteration))
        self._history.setdefault(trial.trial_id, []).append(
            (it, self._score(result))
        )

        if it == 0 or it % self.interval != 0:
            return CONTINUE

        # Iteration-bucketed ranking: each peer is judged by its most recent
        # score at-or-before iteration `it`, so a trial at epoch 2 is never
        # quantile-ranked against a peer's epoch-6 score (which would
        # systematically judge late starters "bad" and bias exploitation).
        scores: Dict[str, float] = {}
        for tid, hist in self._history.items():
            eligible = [s for i2, s in hist if i2 <= it]
            if eligible:
                scores[tid] = eligible[-1]
        if len(scores) < 4:  # need a meaningful quantile split
            return CONTINUE
        ranked = sorted(scores.items(), key=lambda kv: kv[1])  # best first
        k = max(1, int(len(ranked) * self.quantile))
        top_ids = [tid for tid, _ in ranked[:k]]
        bottom_ids = {tid for tid, _ in ranked[-k:]}

        if trial.trial_id not in bottom_ids or trial.trial_id in top_ids:
            return CONTINUE

        rng = rng_from("pbt", self.seed, trial.trial_id, it)
        donors = []
        for tid in top_ids:
            donor = self._find_trial(tid)
            if donor is None or not donor.latest_checkpoint:
                continue
            # PBT semantics (the reference delegates these to Ray, whose
            # exploit copies the donor's state INCLUDING its progress):
            # the laggard adopts the donor's weights and iteration — the
            # trainable resumes at restored epoch + 1 — so a donor AHEAD
            # of the laggard is fine and is in fact the common case when
            # trial starts stagger on shared devices (an earlier
            # ahead-donors-are-ineligible rule made respawn-PBT
            # structurally inert e2e: every top trial was ahead of every
            # bottom one).  The only ineligible donor is one whose
            # checkpoint leaves NO remaining budget — restoring a
            # final-epoch state would terminate the laggard immediately,
            # silently deleting its training run.
            # 20 is the trainables' own num_epochs default — a config that
            # omits the key still trains 20 epochs, so the guard must not
            # silently disable for it (review r5).
            budget = int(donor.config.get("num_epochs", 20) or 0)
            if budget and donor.latest_checkpoint_iteration >= budget:
                continue
            donors.append(donor)
        if not donors:
            return CONTINUE
        donor = donors[int(rng.integers(len(donors)))]

        # Exploit: resume from the donor's weights; explore: mutate its config.
        trial.restore_path = donor.latest_checkpoint
        trial.restore_base = donor.latest_checkpoint_iteration
        trial.config = self._mutate(dict(donor.config), rng)
        self._num_perturbations += 1
        return REQUEUE

    # -- vectorized-runner surface -------------------------------------------
    # run_vectorized replaces the REQUEUE protocol with a device-side gather
    # and bypasses on_trial_result entirely; these hooks let model-based
    # subclasses (PB2) keep learning from the per-epoch stream anyway.

    def observe_result(self, trial: Trial, result: Dict[str, Any]) -> None:
        """Record whatever the explore model learns from one report
        (no decision).  Base PBT learns nothing."""

    def device_mutation_spec(self) -> Optional[Dict[str, Any]]:
        """Static constants of the compiled exploit/explore step, or None.

        None means these mutations cannot be compiled into the population
        program — run_vectorized then keeps the host-boundary path.  The
        compilable subset: every mutated key is ``learning_rate`` /
        ``weight_decay`` (optimizer-state hyperparams) with a continuous
        unquantized ``Uniform``/``LogUniform`` domain.  List specs,
        quantized domains, callables, and model-based explores (PB2
        overrides this to None) all need per-generation host decisions.
        """
        keys = tuple(sorted(self.mutations))
        if not keys or not set(keys) <= {"learning_rate", "weight_decay"}:
            return None
        specs = []
        for k in keys:
            spec = self.mutations[k]
            if not isinstance(spec, (Uniform, LogUniform)):
                return None
            if getattr(spec, "q", None):
                return None  # quantized grids need _mutate's snap logic
            specs.append({
                "key": k,
                "lo": float(spec.low),
                "hi": float(spec.high),
                "log": isinstance(spec, LogUniform),
            })
        return {
            "sign": 1.0 if (self.mode or "min") == "min" else -1.0,
            "quantile": float(self.quantile),
            "resample_p": float(self.resample_p),
            "factors": tuple(float(f) for f in self.factors),
            "keys": keys,
            "specs": tuple(specs),
            "grid_points": RESAMPLE_GRID_POINTS,
        }

    def reset_improvement_chain(self, trial_id: str) -> None:
        """The trial's weights were just replaced (exploit): any
        cross-boundary score delta is meaningless.  Base PBT keeps none."""

    def on_trial_add(self, trial: Trial):
        self._trials = getattr(self, "_trials", {})
        self._trials[trial.trial_id] = trial

    def _find_trial(self, trial_id: str) -> Optional[Trial]:
        return getattr(self, "_trials", {}).get(trial_id)

    def save_state(self) -> Dict[str, Any]:
        # ``_trials`` (live Trial refs) is deliberately absent: resume
        # rebuilds it through on_trial_add before restore_state runs.
        return {
            "history": {t: [[int(i), float(s)] for i, s in h]
                        for t, h in self._history.items()},
            "num_perturbations": self._num_perturbations,
            "generation_log": list(self._generation_log),
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        self._history = {
            str(t): [(int(i), float(s)) for i, s in h]
            for t, h in state.get("history", {}).items()
        }
        self._num_perturbations = int(state.get("num_perturbations", 0))
        self._generation_log = list(state.get("generation_log", []))

    def debug_state(self):
        return {"num_perturbations": self._num_perturbations}


# ---------------------------------------------------------------------------
# Device-parity machinery: the compiled exploit/explore step and this
# host-side reference must agree BIT FOR BIT on the same seed.  Everything
# here is built from operations that are exactly reproducible between the
# two: threefry draw bits (platform- and jit-invariant by design), IEEE
# float32 multiply/min/max, integer truncation, and table lookups into a
# host-precomputed resample grid (see RESAMPLE_GRID_POINTS).
# ---------------------------------------------------------------------------


def resample_grid(spec_entry: Dict[str, Any],
                  n: int = RESAMPLE_GRID_POINTS) -> np.ndarray:
    """The float32 resample table for one mutated hyperparameter.

    Geometric spacing for log domains, linear otherwise — computed ONCE on
    host and shared verbatim by the compiled program (baked constant) and
    the reference, so 'resample' is a gather both sides do identically.
    """
    if spec_entry["log"]:
        g = np.geomspace(spec_entry["lo"], spec_entry["hi"], n)
    else:
        g = np.linspace(spec_entry["lo"], spec_entry["hi"], n)
    return np.asarray(g, np.float32)


def generation_draw_count(spec: Dict[str, Any]) -> int:
    """Uniform draws consumed per row per generation: one donor pick plus
    (resample?, value) per mutated key."""
    return 1 + 2 * len(spec["keys"])


def generation_draws(seed: int, n_rows: int, gen: int,
                     n_draws: int) -> np.ndarray:
    """The ``(n_rows, n_draws)`` uniforms for generation ``gen``.

    Derivation: per-row key ``fold_in(key(seed), row)`` folded with the
    generation index — exactly the chain the compiled program evaluates
    in-device (per-row keys travel with their rows; threefry bits are
    identical eager vs jit), so the boundary path and this reference see
    the same randomness as the scan.
    """
    import jax
    import jax.numpy as jnp

    base = jax.random.key(int(seed))
    keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
        jnp.arange(n_rows)
    )
    return np.asarray(
        jax.vmap(
            lambda k: jax.random.uniform(
                jax.random.fold_in(k, gen), (n_draws,)
            )
        )(keys)
    )


def reference_generation_step(
    spec: Dict[str, Any],
    scores: np.ndarray,
    row_lr: np.ndarray,
    row_wd: np.ndarray,
    valid: np.ndarray,
    draws: np.ndarray,
    fire: bool,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host-side reference of ONE exploit/explore generation.

    Pure numpy control flow over the shared draw bits; the compiled step in
    ``tune/_regression_program.py`` is this function expressed as
    gather/where — the golden parity test asserts they produce identical
    ``(src, new_lr, new_wd, exploited)`` on the same inputs.

    Semantics (mirroring the respawn scheduler above): rows ranked by
    sign-adjusted score with non-finite rows strictly worst (never donate,
    first rescued) and invalid rows (dummy pads / stopped trials) excluded;
    the bottom quantile adopts a uniformly drawn FINITE top-quantile row's
    state (``src``) and its hyperparams, each mutated resample-or-multiply
    with clamping into the domain.  No exploit when fewer than 4 live rows,
    when the best live score is non-finite, or after the final generation
    (``fire=False``).
    """
    k = len(scores)
    src = np.arange(k)
    new_lr = np.asarray(row_lr, np.float32).copy()
    new_wd = np.asarray(row_wd, np.float32).copy()
    exploited = np.zeros(k, bool)
    s = np.asarray(scores, np.float32) * np.float32(spec["sign"])
    rank = np.where(np.isfinite(s), s, np.float32(np.inf)).astype(np.float32)
    order = sorted(
        range(k), key=lambda i: (0 if valid[i] else 1, rank[i], i)
    )
    n_valid = int(np.sum(np.asarray(valid, bool)))
    if not fire or n_valid < 4 or not np.isfinite(rank[order[0]]):
        return src, new_lr, new_wd, exploited
    q = max(1, int(n_valid * spec["quantile"]))
    donors = order[:q]
    finite_donors = [i for i in donors if np.isfinite(rank[i])]
    n_ok = len(finite_donors)
    if n_ok == 0:
        return src, new_lr, new_wd, exploited
    lag_start = max(q, n_valid - q)
    for i in order[lag_start:n_valid]:
        u0 = np.float32(draws[i, 0])
        d = finite_donors[
            min(int(u0 * np.float32(n_ok)), n_ok - 1)
        ]
        src[i] = d
        exploited[i] = True
    # Explore operates on full columns (same vector shapes as the compiled
    # step) and applies only to exploited rows; a key present in the
    # population state but NOT mutated still adopts the donor's value —
    # exploit copies the donor's whole config.
    vals = {"learning_rate": new_lr, "weight_decay": new_wd}
    out = {}
    n_factors = len(spec["factors"])
    factors = np.asarray(spec["factors"], np.float32)
    for m, e in enumerate(spec["specs"]):
        base = vals[e["key"]]
        donor_v = base[src]
        u_res = np.asarray(draws[:, 1 + 2 * m], np.float32)
        u_val = np.asarray(draws[:, 2 + 2 * m], np.float32)
        grid = resample_grid(e, spec.get("grid_points",
                                         RESAMPLE_GRID_POINTS))
        gi = np.clip(
            (u_val * np.float32(len(grid))).astype(np.int32), 0,
            len(grid) - 1,
        )
        resampled = grid[gi]
        fi = np.clip(
            (u_val * np.float32(n_factors)).astype(np.int32), 0,
            n_factors - 1,
        )
        stepped = np.clip(
            donor_v * factors[fi], np.float32(e["lo"]), np.float32(e["hi"])
        ).astype(np.float32)
        cand = np.where(u_res < np.float32(spec["resample_p"]),
                        resampled, stepped)
        out[e["key"]] = np.where(exploited, cand, base).astype(np.float32)
    for key in ("learning_rate", "weight_decay"):
        if key not in spec["keys"]:
            out[key] = np.where(
                exploited, vals[key][src], vals[key]
            ).astype(np.float32)
    return src, out["learning_rate"], out["weight_decay"], exploited


def pbt_state_block(sched) -> Optional[Dict[str, Any]]:
    """The ``pbt`` counter family for a driver's experiment_state extra —
    what the respawn drivers (tune.run / run_distributed) can report; the
    vectorized runner overlays its richer in-device counters on top."""
    if not isinstance(sched, PopulationBasedTraining):
        return None
    return {
        "mode": "respawn",
        "exploits": sched._num_perturbations,
        "explores": sched._num_perturbations,
        "objective": sched.objective,
    }
