"""Population-Based Training (stop-and-respawn variant).

The reference has no PBT and no checkpointing (SURVEY.md §5); BASELINE.json
config 3 requires PBT exercising checkpoint mutate/restore.  Design: at every
``perturbation_interval`` reports, a bottom-quantile trial is stopped, its
config mutated (explore), its weights replaced by a top-quantile peer's latest
checkpoint (exploit), and the trial is requeued — the executor restarts it and
the trainable resumes from the restored epoch.  Stop-and-respawn keeps the
trainable a plain function (no in-band weight surgery) and matches how
preemption-tolerant TPU trials must restart anyway.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from distributed_machine_learning_tpu.tune.schedulers.base import (
    CONTINUE,
    REQUEUE,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.search_space import (
    Domain,
    LogRandInt,
    RandInt,
)
from distributed_machine_learning_tpu.tune.trial import Trial
from distributed_machine_learning_tpu.utils.seeding import rng_from


class PopulationBasedTraining(TrialScheduler):
    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        perturbation_interval: int = 2,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        perturbation_factors=(0.8, 1.2),
        seed: int = 0,
    ):
        if not hyperparam_mutations:
            raise ValueError("PBT requires hyperparam_mutations")
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.factors = perturbation_factors
        self.seed = seed
        # trial_id -> [(iteration, score), ...] in report order (lower=better)
        self._history: Dict[str, list] = {}
        self._num_perturbations = 0

    def set_experiment(self, metric: str, mode: str):
        self.metric = self.metric if self.metric is not None else metric
        self.mode = self.mode if self.mode is not None else mode

    # -- explore -------------------------------------------------------------
    def _mutate(self, config: Dict[str, Any], rng: np.random.Generator) -> Dict[str, Any]:
        new = dict(config)
        for key, spec in self.mutations.items():
            resample = rng.random() < self.resample_p or key not in new
            if isinstance(spec, Domain):
                if resample:
                    new[key] = spec.sample(rng)
                elif isinstance(new.get(key), (int, float)) and not isinstance(new[key], bool):
                    val = new[key] * self.factors[int(rng.integers(len(self.factors)))]
                    lo = getattr(spec, "low", None)
                    hi = getattr(spec, "high", None)
                    if lo is not None and hi is not None:
                        # Clamp into the domain: a x1.2 step from near the
                        # upper bound must not leave it (Ray clamps too).
                        # Direct min/max — no to_unit round-trip, which
                        # would log(0)-crash on a zero value under
                        # loguniform and float-ify int hyperparams.
                        # RandInt/LogRandInt highs are EXCLUSIVE (numpy
                        # convention): their top legal value is high-1.
                        if isinstance(spec, (RandInt, LogRandInt)):
                            hi = hi - 1
                        val = min(max(val, lo), hi)
                        q = getattr(spec, "q", None)
                        if q:
                            # Quantized domains: a multiplied-then-clamped
                            # value must snap back onto the q grid inside
                            # the domain (sample() guarantees multiples;
                            # explore must not reintroduce non-multiples).
                            val = min(max(round(val / q) * q, spec._lo),
                                      spec._hi)
                    new[key] = type(new[key])(val)
                else:
                    new[key] = spec.sample(rng)
            elif isinstance(spec, (list, tuple)):
                if resample or new.get(key) not in spec:
                    new[key] = spec[int(rng.integers(len(spec)))]
                else:  # step to a neighbor in the ordered list
                    i = list(spec).index(new[key])
                    j = int(np.clip(i + rng.choice([-1, 1]), 0, len(spec) - 1))
                    new[key] = spec[j]
            elif callable(spec):
                new[key] = spec()
            else:
                raise TypeError(f"Unsupported mutation spec for {key!r}: {spec!r}")
        return new

    # -- exploit -------------------------------------------------------------
    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        if self.metric not in result:
            return CONTINUE
        it = int(result.get("training_iteration", trial.training_iteration))
        self._history.setdefault(trial.trial_id, []).append(
            (it, self._score(result))
        )

        if it == 0 or it % self.interval != 0:
            return CONTINUE

        # Iteration-bucketed ranking: each peer is judged by its most recent
        # score at-or-before iteration `it`, so a trial at epoch 2 is never
        # quantile-ranked against a peer's epoch-6 score (which would
        # systematically judge late starters "bad" and bias exploitation).
        scores: Dict[str, float] = {}
        for tid, hist in self._history.items():
            eligible = [s for i2, s in hist if i2 <= it]
            if eligible:
                scores[tid] = eligible[-1]
        if len(scores) < 4:  # need a meaningful quantile split
            return CONTINUE
        ranked = sorted(scores.items(), key=lambda kv: kv[1])  # best first
        k = max(1, int(len(ranked) * self.quantile))
        top_ids = [tid for tid, _ in ranked[:k]]
        bottom_ids = {tid for tid, _ in ranked[-k:]}

        if trial.trial_id not in bottom_ids or trial.trial_id in top_ids:
            return CONTINUE

        rng = rng_from("pbt", self.seed, trial.trial_id, it)
        donors = []
        for tid in top_ids:
            donor = self._find_trial(tid)
            if donor is None or not donor.latest_checkpoint:
                continue
            # PBT semantics (the reference delegates these to Ray, whose
            # exploit copies the donor's state INCLUDING its progress):
            # the laggard adopts the donor's weights and iteration — the
            # trainable resumes at restored epoch + 1 — so a donor AHEAD
            # of the laggard is fine and is in fact the common case when
            # trial starts stagger on shared devices (an earlier
            # ahead-donors-are-ineligible rule made respawn-PBT
            # structurally inert e2e: every top trial was ahead of every
            # bottom one).  The only ineligible donor is one whose
            # checkpoint leaves NO remaining budget — restoring a
            # final-epoch state would terminate the laggard immediately,
            # silently deleting its training run.
            # 20 is the trainables' own num_epochs default — a config that
            # omits the key still trains 20 epochs, so the guard must not
            # silently disable for it (review r5).
            budget = int(donor.config.get("num_epochs", 20) or 0)
            if budget and donor.latest_checkpoint_iteration >= budget:
                continue
            donors.append(donor)
        if not donors:
            return CONTINUE
        donor = donors[int(rng.integers(len(donors)))]

        # Exploit: resume from the donor's weights; explore: mutate its config.
        trial.restore_path = donor.latest_checkpoint
        trial.restore_base = donor.latest_checkpoint_iteration
        trial.config = self._mutate(dict(donor.config), rng)
        self._num_perturbations += 1
        return REQUEUE

    # -- vectorized-runner surface -------------------------------------------
    # run_vectorized replaces the REQUEUE protocol with a device-side gather
    # and bypasses on_trial_result entirely; these hooks let model-based
    # subclasses (PB2) keep learning from the per-epoch stream anyway.

    def observe_result(self, trial: Trial, result: Dict[str, Any]) -> None:
        """Record whatever the explore model learns from one report
        (no decision).  Base PBT learns nothing."""

    def reset_improvement_chain(self, trial_id: str) -> None:
        """The trial's weights were just replaced (exploit): any
        cross-boundary score delta is meaningless.  Base PBT keeps none."""

    def on_trial_add(self, trial: Trial):
        self._trials = getattr(self, "_trials", {})
        self._trials[trial.trial_id] = trial

    def _find_trial(self, trial_id: str) -> Optional[Trial]:
        return getattr(self, "_trials", {}).get(trial_id)

    def debug_state(self):
        return {"num_perturbations": self._num_perturbations}
