"""Median stopping rule: stop a trial whose best result so far is worse than
the median of other trials' running averages at the same iteration."""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from distributed_machine_learning_tpu.tune.schedulers.base import (
    CONTINUE,
    STOP,
    TrialScheduler,
)
from distributed_machine_learning_tpu.tune.trial import Trial


class MedianStoppingRule(TrialScheduler):
    def __init__(
        self,
        metric: str = None,
        mode: str = None,
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        # trial_id -> list of scores per iteration (lower=better)
        self._history: Dict[str, List[float]] = {}

    def set_experiment(self, metric: str, mode: str):
        self.metric = self.metric if self.metric is not None else metric
        self.mode = self.mode if self.mode is not None else mode

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        if self.metric not in result:
            return CONTINUE
        score = self._score(result)
        self._history.setdefault(trial.trial_id, []).append(score)
        it = len(self._history[trial.trial_id])
        if it <= self.grace_period:
            return CONTINUE

        running_avgs = [
            float(np.mean(h[:it]))
            for tid, h in self._history.items()
            if tid != trial.trial_id and len(h) >= it
        ]
        if len(running_avgs) < self.min_samples:
            return CONTINUE
        best_so_far = min(self._history[trial.trial_id])
        return STOP if best_so_far > float(np.median(running_avgs)) else CONTINUE

    def save_state(self) -> Dict[str, Any]:
        return {
            "history": {t: list(h) for t, h in self._history.items()},
        }

    def restore_state(self, state: Dict[str, Any]) -> None:
        self._history = {
            str(t): [float(v) for v in h]
            for t, h in state.get("history", {}).items()
        }
