"""Pluggable byte storage for checkpoints and experiment artifacts.

The reference keeps results on a local ``local_dir`` only
(`/root/reference/ray-tune-hpo-regression.py:476`); a TPU pod needs shared
storage — checkpoints written by one host must be restorable on another
(PBT exploit across workers, preemption recovery), and the BASELINE north
star names GCS explicitly.  This module dispatches on the path scheme:

* plain paths / ``file://``  -> ``LocalStorage`` (atomic POSIX writes)
* ``gs://``, ``s3://``, ...  -> ``FsspecStorage`` (via fsspec/gcsfs when
  installed; a clear error otherwise — the libraries are optional)
* ``mem://``                 -> ``MemoryStorage`` (process-local fake for
  tests; no disk, no network)

Every consumer (checkpoint save/load, retention pruning) goes through
``get_storage`` so a ``storage_path='gs://bucket/exp'`` flows end to end
without any caller branching on scheme.
"""

from __future__ import annotations

import os
import posixpath
import tempfile
import threading
from typing import Dict, List, Optional, Tuple


class StorageBackend:
    """Minimal byte-level interface checkpoints need."""

    def write_bytes(self, path: str, data: bytes) -> str:
        raise NotImplementedError

    def read_bytes(self, path: str) -> Optional[bytes]:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        """Names (not full paths) of entries under ``path``; [] if absent."""
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def join(self, *parts: str) -> str:
        return posixpath.join(*parts)


class LocalStorage(StorageBackend):
    """Local filesystem with atomic writes (temp file + rename)."""

    def write_bytes(self, path: str, data: bytes) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)  # atomic on POSIX
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return path

    def read_bytes(self, path: str) -> Optional[bytes]:
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))

    def delete(self, path: str) -> None:
        if os.path.exists(path):
            os.unlink(path)

    def join(self, *parts: str) -> str:
        return os.path.join(*parts)


class MemoryStorage(StorageBackend):
    """Process-local in-memory store keyed by full path (test fake).

    A single shared namespace (class-level) so independently constructed
    instances — e.g. the saver inside the executor and the loader in a test —
    see the same data, mirroring how a bucket behaves across components.
    """

    _store: Dict[str, bytes] = {}
    _lock = threading.Lock()

    def write_bytes(self, path: str, data: bytes) -> str:
        with self._lock:
            self._store[path] = bytes(data)
        return path

    def read_bytes(self, path: str) -> Optional[bytes]:
        with self._lock:
            return self._store.get(path)

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._store

    def listdir(self, path: str) -> List[str]:
        prefix = path.rstrip("/") + "/"
        with self._lock:
            names = {
                key[len(prefix):].split("/", 1)[0]
                for key in self._store if key.startswith(prefix)
            }
        return sorted(names)

    def delete(self, path: str) -> None:
        with self._lock:
            self._store.pop(path, None)

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._store.clear()


class FsspecStorage(StorageBackend):
    """Remote object storage (gs://, s3://, ...) through fsspec."""

    def __init__(self, scheme: str):
        try:
            import fsspec
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError(
                f"storage scheme {scheme!r} needs the optional 'fsspec' "
                f"package (plus the filesystem driver, e.g. 'gcsfs' for "
                f"gs://); install it or use a local storage_path"
            ) from e
        self._fs = fsspec.filesystem(scheme)
        self._scheme = scheme

    def _strip(self, path: str) -> str:
        return path.split("://", 1)[1] if "://" in path else path

    def write_bytes(self, path: str, data: bytes) -> str:
        with self._fs.open(self._strip(path), "wb") as f:
            f.write(data)
        return path

    def read_bytes(self, path: str) -> Optional[bytes]:
        p = self._strip(path)
        if not self._fs.exists(p):
            return None
        with self._fs.open(p, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return self._fs.exists(self._strip(path))

    def listdir(self, path: str) -> List[str]:
        p = self._strip(path)
        if not self._fs.exists(p):
            return []
        return sorted(posixpath.basename(e.rstrip("/"))
                      for e in self._fs.ls(p, detail=False))

    def delete(self, path: str) -> None:
        p = self._strip(path)
        if self._fs.exists(p):
            self._fs.rm(p)


_local = LocalStorage()
_memory = MemoryStorage()
_fsspec_cache: Dict[str, FsspecStorage] = {}


def get_storage(path: str) -> Tuple[StorageBackend, str]:
    """Backend + normalized path for ``path``, dispatched on its scheme."""
    if "://" not in path:
        return _local, path
    scheme, rest = path.split("://", 1)
    if scheme == "file":
        return _local, rest
    if scheme == "mem":
        return _memory, path  # keep full mem:// key
    backend = _fsspec_cache.get(scheme)
    if backend is None:
        backend = _fsspec_cache[scheme] = FsspecStorage(scheme)
    return backend, path
