"""Pluggable byte storage for checkpoints and experiment artifacts.

The reference keeps results on a local ``local_dir`` only
(`/root/reference/ray-tune-hpo-regression.py:476`); a TPU pod needs shared
storage — checkpoints written by one host must be restorable on another
(PBT exploit across workers, preemption recovery), and the BASELINE north
star names GCS explicitly.  This module dispatches on the path scheme:

* plain paths / ``file://``  -> ``LocalStorage`` (atomic POSIX writes)
* ``gs://``, ``s3://``, ...  -> ``FsspecStorage`` (via fsspec/gcsfs when
  installed; a clear error otherwise — the libraries are optional)
* ``mem://``                 -> ``MemoryStorage`` (process-local fake for
  tests; no disk, no network)

Every consumer (checkpoint save/load, retention pruning) goes through
``get_storage`` so a ``storage_path='gs://bucket/exp'`` flows end to end
without any caller branching on scheme.

Failure hardening (chaos.py is the harness that proves it):

* ``get_storage`` composes two wrappers around the scheme backend:
  an optional **fault wrapper** (installed by ``chaos.activate`` — injects
  deterministic, seeded IOErrors/corruption/latency for tests) and a
  **retry wrapper** (``RetryingStorage``: exponential backoff + jitter +
  a bounded attempt budget for transient I/O faults — shared storage on a
  pod is exactly the place writes flake).  Order matters: retries sit
  OUTSIDE the fault layer so an injected transient error is absorbed the
  same way a real one would be.
* ``retry_call`` is the same policy as a bare function, used by the
  experiment store's local JSON writes (state snapshots, params) which
  bypass the byte-backend interface.
"""

from __future__ import annotations

import hashlib
import os
import posixpath
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple
from distributed_machine_learning_tpu.analysis.locks import named_lock


class StorageBackend:
    """Minimal byte-level interface checkpoints need."""

    def write_bytes(self, path: str, data: bytes) -> str:
        raise NotImplementedError

    def read_bytes(self, path: str) -> Optional[bytes]:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        """Names (not full paths) of entries under ``path``; [] if absent."""
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def join(self, *parts: str) -> str:
        return posixpath.join(*parts)


class LocalStorage(StorageBackend):
    """Local filesystem with atomic writes (temp file + rename)."""

    def write_bytes(self, path: str, data: bytes) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)  # atomic on POSIX
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return path

    def read_bytes(self, path: str) -> Optional[bytes]:
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))

    def delete(self, path: str) -> None:
        if os.path.exists(path):
            os.unlink(path)

    def join(self, *parts: str) -> str:
        return os.path.join(*parts)


class MemoryStorage(StorageBackend):
    """Process-local in-memory store keyed by full path (test fake).

    A single shared namespace (class-level) so independently constructed
    instances — e.g. the saver inside the executor and the loader in a test —
    see the same data, mirroring how a bucket behaves across components.
    """

    _store: Dict[str, bytes] = {}
    _lock = named_lock("tune.storage.mem")

    def write_bytes(self, path: str, data: bytes) -> str:
        with self._lock:
            self._store[path] = bytes(data)
        return path

    def read_bytes(self, path: str) -> Optional[bytes]:
        with self._lock:
            return self._store.get(path)

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._store

    def listdir(self, path: str) -> List[str]:
        prefix = path.rstrip("/") + "/"
        with self._lock:
            names = {
                key[len(prefix):].split("/", 1)[0]
                for key in self._store if key.startswith(prefix)
            }
        return sorted(names)

    def delete(self, path: str) -> None:
        with self._lock:
            self._store.pop(path, None)

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._store.clear()


class FsspecStorage(StorageBackend):
    """Remote object storage (gs://, s3://, ...) through fsspec."""

    def __init__(self, scheme: str):
        try:
            import fsspec
        except ImportError as e:  # pragma: no cover - env-dependent
            raise ImportError(
                f"storage scheme {scheme!r} needs the optional 'fsspec' "
                f"package (plus the filesystem driver, e.g. 'gcsfs' for "
                f"gs://); install it or use a local storage_path"
            ) from e
        self._fs = fsspec.filesystem(scheme)
        self._scheme = scheme

    def _strip(self, path: str) -> str:
        return path.split("://", 1)[1] if "://" in path else path

    def write_bytes(self, path: str, data: bytes) -> str:
        with self._fs.open(self._strip(path), "wb") as f:
            f.write(data)
        return path

    def read_bytes(self, path: str) -> Optional[bytes]:
        p = self._strip(path)
        if not self._fs.exists(p):
            return None
        with self._fs.open(p, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return self._fs.exists(self._strip(path))

    def listdir(self, path: str) -> List[str]:
        p = self._strip(path)
        if not self._fs.exists(p):
            return []
        return sorted(posixpath.basename(e.rstrip("/"))
                      for e in self._fs.ls(p, detail=False))

    def delete(self, path: str) -> None:
        p = self._strip(path)
        if self._fs.exists(p):
            self._fs.rm(p)


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded-budget exponential backoff for transient storage faults.

    ``attempts`` is the TOTAL number of tries (1 = no retry).  Delay before
    retry k (1-based) is ``base_delay_s * 2**(k-1)`` capped at
    ``max_delay_s``, plus a deterministic jitter in ``[0, jitter * delay]``
    derived from the operation key — reproducible under a seeded chaos
    plan, decorrelated across concurrent writers against real storage.
    """

    attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.5
    retry_on: Tuple[type, ...] = field(default=(OSError, TimeoutError))

    def delay_for(self, attempt: int, key: str = "") -> float:
        delay = min(self.base_delay_s * (2 ** attempt), self.max_delay_s)
        if self.jitter > 0:
            h = hashlib.sha256(f"{key}/{attempt}".encode()).digest()
            frac = int.from_bytes(h[:8], "little") / 2**64
            delay += self.jitter * delay * frac
        return delay


DEFAULT_RETRY_POLICY = RetryPolicy()

# Module-level knobs, both consulted by get_storage on every call:
# the fault wrapper is chaos.py's injection point; the retry policy is the
# process-wide default (None disables retries entirely).
_fault_wrapper: Optional[Callable[[StorageBackend], StorageBackend]] = None
_default_retry_policy: Optional[RetryPolicy] = DEFAULT_RETRY_POLICY


def set_fault_wrapper(
    wrapper: Optional[Callable[[StorageBackend], StorageBackend]],
) -> None:
    """Install (or clear, with None) a backend wrapper applied by
    ``get_storage`` INSIDE the retry layer — chaos.py's choke point."""
    global _fault_wrapper
    _fault_wrapper = wrapper


def set_default_retry_policy(policy: Optional[RetryPolicy]) -> None:
    """Process-wide retry policy for all storage access (None disables)."""
    global _default_retry_policy
    _default_retry_policy = policy


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               key: str = "", log: Optional[Callable[[str], None]] = None,
               **kwargs):
    """Run ``fn(*args, **kwargs)`` under ``policy`` (default: the process
    policy).  Retries only the policy's exception types; the final attempt's
    error propagates unchanged so callers keep their existing error paths."""
    policy = policy if policy is not None else _default_retry_policy
    if policy is None or policy.attempts <= 1:
        return fn(*args, **kwargs)
    last_exc: Optional[BaseException] = None
    for attempt in range(policy.attempts):
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as exc:
            last_exc = exc
            if attempt == policy.attempts - 1:
                raise
            delay = policy.delay_for(attempt, key)
            if log is not None:
                log(
                    f"transient storage fault (attempt "
                    f"{attempt + 1}/{policy.attempts}): {exc!r}; retrying "
                    f"in {delay:.3f}s"
                )
            time.sleep(delay)
    raise last_exc  # pragma: no cover - loop always returns or raises


class RetryingStorage(StorageBackend):
    """Decorator adding the retry policy to every byte operation.

    Wraps any backend (including a chaos ``FaultyStorage``); ``join`` and
    identity-ish helpers delegate straight through.
    """

    def __init__(self, inner: StorageBackend,
                 policy: Optional[RetryPolicy] = None):
        self.inner = inner
        self.policy = policy or DEFAULT_RETRY_POLICY

    def _retry(self, op: str, fn: Callable, path: str, *args):
        return retry_call(fn, path, *args, policy=self.policy,
                          key=f"{op}:{path}")

    def write_bytes(self, path: str, data: bytes) -> str:
        return self._retry("write", self.inner.write_bytes, path, data)

    def read_bytes(self, path: str) -> Optional[bytes]:
        return self._retry("read", self.inner.read_bytes, path)

    def exists(self, path: str) -> bool:
        return self._retry("exists", self.inner.exists, path)

    def listdir(self, path: str) -> List[str]:
        return self._retry("listdir", self.inner.listdir, path)

    def delete(self, path: str) -> None:
        return self._retry("delete", self.inner.delete, path)

    def join(self, *parts: str) -> str:
        return self.inner.join(*parts)


_local = LocalStorage()
_memory = MemoryStorage()
_fsspec_cache: Dict[str, FsspecStorage] = {}


def _raw_storage(path: str) -> Tuple[StorageBackend, str]:
    if "://" not in path:
        return _local, path
    scheme, rest = path.split("://", 1)
    if scheme == "file":
        return _local, rest
    if scheme == "mem":
        return _memory, path  # keep full mem:// key
    backend = _fsspec_cache.get(scheme)
    if backend is None:
        backend = _fsspec_cache[scheme] = FsspecStorage(scheme)
    return backend, path


def get_storage(path: str) -> Tuple[StorageBackend, str]:
    """Backend + normalized path for ``path``, dispatched on its scheme.

    The returned backend is wrapped with the active fault layer (chaos
    injection, when installed) and the process retry policy, in that order
    — retries absorb injected transient faults exactly as real ones.
    """
    backend, p = _raw_storage(path)
    if _fault_wrapper is not None:
        backend = _fault_wrapper(backend)
    if _default_retry_policy is not None:
        backend = RetryingStorage(backend, _default_retry_policy)
    return backend, p
