"""Child-process entry for the process-per-trial executor.

Run as ``python -m distributed_machine_learning_tpu.tune._process_child`` by
``ProcessTrialExecutor`` with the trial's device visibility already fixed in
the process environment (the TPU analogue of Ray setting
``CUDA_VISIBLE_DEVICES`` per trial actor, `ray-tune-hpo-regression.py:286`;
SURVEY.md §7 step 3).  Speaks a length-prefixed pickle protocol over binary
stdio:

    parent -> child   {"trial_id", "config", "trainable": bytes,
                       "restore": pytree|None, "sys_path": [...]}   (init)
    child  -> parent  ("result", metrics, ckpt_bytes|None)
    parent -> child   ("decision", "continue"|"stop"|"pause")
    child  -> parent  ("beat",)            (tune.heartbeat(); no reply)
    child  -> parent  ("complete",) | ("error", traceback_str)

The child's real stdout is reserved for frames; ``print`` inside trainables
is redirected to stderr so it can't corrupt the stream.
"""

from __future__ import annotations

import pickle
import struct
import sys
import traceback

_LEN = struct.Struct(">Q")


def read_frame(stream):
    header = stream.read(_LEN.size)
    if len(header) < _LEN.size:
        raise EOFError("frame stream closed")
    (n,) = _LEN.unpack(header)
    payload = stream.read(n)
    if len(payload) < n:
        raise EOFError("truncated frame")
    return pickle.loads(payload)


def write_frame(stream, obj) -> None:
    payload = pickle.dumps(obj)
    stream.write(_LEN.pack(len(payload)) + payload)
    stream.flush()


class _TrialStub:
    """Just enough of a Trial for Session users inside the child."""

    def __init__(self, trial_id: str, config: dict):
        self.trial_id = trial_id
        self.config = config


def main() -> None:
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    sys.stdout = sys.stderr  # user prints must not corrupt the frame stream

    # Everything from here on reports failures as frames: an unpicklable
    # trainable or a broken import must surface as the trial's error, not as
    # a silent child death.
    try:
        init = read_frame(stdin)
        for p in reversed(init.get("sys_path", [])):
            if p not in sys.path:
                sys.path.insert(0, p)
        import cloudpickle

        trainable = cloudpickle.loads(init["trainable"])

        import jax

        from distributed_machine_learning_tpu.tune.session import (
            PauseTrial,
            Session,
            StopTrial,
            set_session,
        )
        from distributed_machine_learning_tpu.utils.compile_cache import (
            get_tracker,
        )
        tracker = get_tracker()
        devices = jax.devices()
    except BaseException:  # noqa: BLE001
        write_frame(stdout, ("error", traceback.format_exc()))
        return

    def report_fn(metrics, checkpoint) -> str:
        metrics.setdefault("compile_time_s", round(tracker.thread_seconds(), 4))
        metrics.setdefault("compile_cache_hits", tracker.thread_cache_hits())
        ckpt_bytes = None
        if checkpoint is not None:
            ckpt_bytes = pickle.dumps(jax.device_get(checkpoint))
        write_frame(stdout, ("result", dict(metrics), ckpt_bytes))
        msg = read_frame(stdin)
        assert msg[0] == "decision", msg
        return msg[1]

    # Mid-epoch liveness: tune.heartbeat() in the trainable emits a "beat"
    # frame so the parent's watchdog sees progress between reports.  Rate-
    # limited host-side — a heartbeat in a hot step loop must not flood the
    # pipe.  Same thread as report_fn (the trainable's), so frame writes
    # never interleave.
    import time as _time

    last_beat = [0.0]

    def heartbeat_fn() -> None:
        now = _time.monotonic()
        if now - last_beat[0] >= 0.05:
            last_beat[0] = now
            write_frame(stdout, ("beat",))

    restore = init.get("restore")
    try:
        set_session(
            Session(
                _TrialStub(init["trial_id"], dict(init["config"])),
                report_fn,
                lambda: restore,
                devices,
                heartbeat_fn=heartbeat_fn,
            )
        )
        trainable(dict(init["config"]))
        write_frame(stdout, ("complete",))
    except (StopTrial, PauseTrial):
        write_frame(stdout, ("complete",))
    except BaseException:  # noqa: BLE001 - everything goes back to the parent
        write_frame(stdout, ("error", traceback.format_exc()))


if __name__ == "__main__":
    main()
