"""Child-process entry for the process-per-trial executor.

Run as ``python -m distributed_machine_learning_tpu.tune._process_child`` by
``ProcessTrialExecutor`` with the trial's device visibility already fixed in
the process environment (the TPU analogue of Ray setting
``CUDA_VISIBLE_DEVICES`` per trial actor, `ray-tune-hpo-regression.py:286`;
SURVEY.md §7 step 3).  Speaks a length-prefixed pickle protocol over binary
stdio:

    child  -> parent  ("warm",)            (pre-warmed child finished its
                                            imports; sent before any frame
                                            is read when DML_PREWARM=1)
    parent -> child   ("precompile", {"key", "trainable": bytes, "config",
                       "sys_path"})        (compile this program during
                                            scheduler think-time)
    child  -> parent  ("prewarmed", key, backend_compiles) |
                      ("prewarm_error", key, traceback_str)
    parent -> child   {"trial_id", "config", "trainable": bytes,
                       "restore": pytree|None, "sys_path": [...]}   (init)
    child  -> parent  ("result", metrics, ckpt_bytes|None)
    parent -> child   ("decision", "continue"|"stop"|"pause")
    child  -> parent  ("beat",)            (tune.heartbeat(); no reply)
    child  -> parent  ("complete",) | ("error", traceback_str)

**Pre-warmed mode** (``DML_PREWARM=1``): the executor spawns the child
BEFORE any trial is assigned; the child front-loads the slow part of trial
startup — jax import, device enumeration, persistent compile-cache attach —
and then blocks on stdin.  Dispatch-to-first-step latency collapses to
frame parsing + the trainable's own work.  A ``precompile`` frame goes one
step further: the child runs the trainable under a session that stops at
the FIRST report boundary, which traces and compiles every program the
trial would use (populating the shared persistent/AOT caches) while the
scheduler is still thinking.

The child's real stdout is reserved for frames; ``print`` inside trainables
is redirected to stderr so it can't corrupt the stream.
"""

from __future__ import annotations

import os
import pickle
import struct
import sys
import traceback

_LEN = struct.Struct(">Q")

PREWARM_ENV = "DML_PREWARM"


def read_frame(stream):
    header = stream.read(_LEN.size)
    if len(header) < _LEN.size:
        raise EOFError("frame stream closed")
    (n,) = _LEN.unpack(header)
    payload = stream.read(n)
    if len(payload) < n:
        raise EOFError("truncated frame")
    return pickle.loads(payload)


def write_frame(stream, obj) -> None:
    payload = pickle.dumps(obj)
    stream.write(_LEN.pack(len(payload)) + payload)
    stream.flush()


class _TrialStub:
    """Just enough of a Trial for Session users inside the child."""

    def __init__(self, trial_id: str, config: dict):
        self.trial_id = trial_id
        self.config = config


def _extend_sys_path(paths):
    for p in reversed(paths or []):
        if p not in sys.path:
            sys.path.insert(0, p)


class _StopAfterFirstReport(Exception):
    """Precompile sentinel: every program is compiled by the time the first
    report boundary is reached; nothing after it is compile work."""


def _run_precompile(msg, stdout) -> None:
    """Trace + compile the trial's programs without running the trial.

    Runs the trainable under a session whose report raises at the first
    boundary — by then the epoch/eval programs are compiled and sitting in
    the jit, persistent, and AOT caches for the REAL incarnation (this
    child or any sibling process) to hit."""
    key = msg.get("key", "")
    try:
        _extend_sys_path(msg.get("sys_path"))
        import cloudpickle
        import jax

        from distributed_machine_learning_tpu.compilecache import get_tracker
        from distributed_machine_learning_tpu.tune.session import (
            Session,
            set_session,
        )

        trainable = cloudpickle.loads(msg["trainable"])
        tracker = get_tracker()
        compiles_before = tracker.total_backend_compiles()

        def report_fn(_metrics, _checkpoint) -> str:
            raise _StopAfterFirstReport()

        config = dict(msg.get("config") or {})
        try:
            set_session(
                Session(
                    _TrialStub(f"prewarm-{key}", config),
                    report_fn,
                    lambda: None,
                    jax.devices(),
                )
            )
            trainable(config)
        except _StopAfterFirstReport:
            pass
        finally:
            set_session(None)
        write_frame(
            stdout,
            ("prewarmed", key,
             tracker.total_backend_compiles() - compiles_before),
        )
    except BaseException:  # noqa: BLE001 - report, keep serving
        write_frame(stdout, ("prewarm_error", key, traceback.format_exc()))


def main() -> None:
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    sys.stdout = sys.stderr  # user prints must not corrupt the frame stream

    prewarmed = os.environ.get(PREWARM_ENV) == "1"
    if prewarmed:
        # Front-load the slow imports BEFORE any trial exists, then tell the
        # parent this runner is hot.  Import errors surface as an error
        # frame, exactly as they would on the cold path.
        try:
            import cloudpickle  # noqa: F401
            import jax  # noqa: F401

            from distributed_machine_learning_tpu.compilecache import (
                enable_persistent_cache,
                get_tracker,
            )

            enable_persistent_cache()
            get_tracker()  # install monitoring listeners pre-trial
            jax.devices()  # device enumeration is part of cold start
            write_frame(stdout, ("warm",))
        except BaseException:  # noqa: BLE001
            write_frame(stdout, ("error", traceback.format_exc()))
            return

    # Frame loop: precompile requests may arrive (and repeat) before the
    # init frame; the first init frame runs the trial and ends the process.
    while True:
        try:
            frame = read_frame(stdin)
        except EOFError:
            return  # pool teardown before any trial was assigned
        if isinstance(frame, tuple) and frame and frame[0] == "precompile":
            _run_precompile(frame[1], stdout)
            continue
        break

    init = frame
    # Everything from here on reports failures as frames: an unpicklable
    # trainable or a broken import must surface as the trial's error, not as
    # a silent child death.
    try:
        _extend_sys_path(init.get("sys_path", []))
        import cloudpickle

        trainable = cloudpickle.loads(init["trainable"])

        import jax

        from distributed_machine_learning_tpu import obs
        from distributed_machine_learning_tpu.tune.session import (
            PauseTrial,
            Session,
            StopTrial,
            set_session,
        )
        from distributed_machine_learning_tpu.compilecache import (
            get_tracker,
        )
        tracker = get_tracker()
        devices = jax.devices()
        # Join the driver's trace (same trace id; spans parent under the
        # driver's trial.dispatch span) and point flight dumps at the
        # experiment dir.  A SIGTERM — the runner's stall/time-limit kill
        # path — dumps this process's flight ring + open-span stacks
        # BEFORE dying, so a killed wedge leaves its hang site behind.
        obs.configure_from_frame(
            init.get("obs"), label=f"child{os.getpid()}"
        )

        import signal as _signal

        def _on_sigterm(_signum, _frame):
            obs.dump_flight_recorder(
                f"sigterm_{init.get('trial_id', 'trial')}"
            )
            obs.flush()
            os._exit(128 + _signal.SIGTERM)

        try:
            _signal.signal(_signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            # Not the main thread / unsupported platform: forensics are
            # then the parent's job, the trial itself is unaffected.
            pass
    except BaseException:  # noqa: BLE001
        write_frame(stdout, ("error", traceback.format_exc()))
        return

    def report_fn(metrics, checkpoint) -> str:
        metrics.setdefault("compile_time_s", round(tracker.thread_seconds(), 4))
        metrics.setdefault("compile_cache_hits", tracker.thread_cache_hits())
        ckpt_bytes = None
        if checkpoint is not None:
            ckpt_bytes = pickle.dumps(jax.device_get(checkpoint))
        write_frame(stdout, ("result", dict(metrics), ckpt_bytes))
        msg = read_frame(stdin)
        assert msg[0] == "decision", msg
        return msg[1]

    # Mid-epoch liveness: tune.heartbeat() in the trainable emits a "beat"
    # frame so the parent's watchdog sees progress between reports.  Rate-
    # limited host-side — a heartbeat in a hot step loop must not flood the
    # pipe.  Same thread as report_fn (the trainable's), so frame writes
    # never interleave.
    import time as _time

    last_beat = [0.0]

    def heartbeat_fn() -> None:
        now = _time.monotonic()
        if now - last_beat[0] >= 0.05:
            last_beat[0] = now
            write_frame(stdout, ("beat",))

    restore = init.get("restore")
    try:
        set_session(
            Session(
                _TrialStub(init["trial_id"], dict(init["config"])),
                report_fn,
                lambda: restore,
                devices,
                heartbeat_fn=heartbeat_fn,
            )
        )
        with obs.maybe_profile_trial(
            init.get("obs_profile_dir"), init["trial_id"]
        ), obs.span(
            "trial",
            {"trial_id": init["trial_id"],
             "incarnation": int(init.get("incarnation", 0))},
        ):
            trainable(dict(init["config"]))
        write_frame(stdout, ("complete",))
    except (StopTrial, PauseTrial):
        write_frame(stdout, ("complete",))
    except BaseException:  # noqa: BLE001 - everything goes back to the parent
        write_frame(stdout, ("error", traceback.format_exc()))
    finally:
        obs.flush()


if __name__ == "__main__":
    main()
